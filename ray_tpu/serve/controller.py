"""ServeController — the reconciler control plane.

Analog of the reference's ``python/ray/serve/_private/controller.py:85``
(``ServeController``) + ``deployment_state.py`` (target-vs-actual reconcile
:2807) + ``long_poll.py`` (config push): a singleton actor owning desired
state; a background reconcile thread starts/stops replica actors to match;
handles learn replica sets via versioned long-poll snapshots. The request
path NEVER touches the controller (reference's data/control split).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.core.config import config
from ray_tpu.util import flightrec
from ray_tpu.utils.logging import get_logger, log_swallowed
from ray_tpu.serve.autoscaling import (DeploymentSignals, GangPreemption,
                                       SLOPolicy, TTFTRollup)
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig

logger = get_logger("serve_controller")
from ray_tpu.serve.replica import ReplicaActor

CONTROLLER_NAME = "SERVE_CONTROLLER"


def _runtime_preempt(resources: Dict[str, float], count: int,
                     min_priority: int) -> int:
    """Route a gang-preemption request to whichever runtime this controller
    replica lives in (CoreWorker RPC in multiprocess, the in-process
    PlacementGroupManager otherwise)."""
    from ray_tpu.core.runtime import get_runtime

    fn = getattr(get_runtime(), "preempt_gangs", None)
    return int(fn(resources, count, min_priority)) if fn is not None else 0


def _replica_shape(t: "_DeploymentTarget") -> Dict[str, float]:
    """One replica's resource demand, from its actor options (the shape a
    preemption must make placeable)."""
    opts = t.config.ray_actor_options or {}
    shape: Dict[str, float] = {}
    if opts.get("num_cpus"):
        shape["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus"):
        shape["TPU"] = float(opts["num_tpus"])
    for k, v in (opts.get("resources") or {}).items():
        shape[k] = float(v)
    return shape or {"CPU": 1.0}


@dataclass
class _DeploymentTarget:
    name: str
    callable_or_class: Any
    init_args: tuple
    init_kwargs: dict
    config: DeploymentConfig
    route_prefix: Optional[str] = None
    target_replicas: int = 1
    version: int = 0  # bumped on redeploy; stale-version replicas are culled


class ServeControllerActor:
    def __init__(self):
        self._targets: Dict[str, _DeploymentTarget] = {}
        # name -> [(version, actor handle)]
        self._replicas: Dict[str, List[Any]] = {}
        self._version = 0
        self._lock = threading.Lock()
        self._running = True
        self._metrics: Dict[str, float] = {}  # deployment -> ongoing EWMA
        self._metrics_t: Dict[str, float] = {}  # deployment -> last report
        # SLO autoscaling state: one policy per deployment (holds the
        # hysteresis/cooldown timers) + the rate-limited TTFT rollup reader.
        self._policies: Dict[str, SLOPolicy] = {}
        # SLO-pressure capacity reclaim: an upscale decision under a TTFT
        # breach may revoke lower-gang_priority training gangs through the
        # runtime's preempt_gangs path before the new replicas try to place.
        self._gang_preemption = GangPreemption(_runtime_preempt)
        self._ttft = TTFTRollup(
            min_interval_s=config().serve_slo_rollup_interval_s)
        self._last_slo_eval: Dict[str, float] = {}
        # deployment -> {replica key -> loaded multiplexed model ids}
        self._model_ids: Dict[str, Dict[str, list]] = {}
        # deployment -> {replica key -> metrics dict (ongoing, slot
        # occupancy, queue depth, ...)} — the routers' occupancy signal.
        self._replica_load: Dict[str, Dict[str, dict]] = {}
        self._model_poll_tick = 0
        # Rolling updates: old-version replicas keep serving until the new
        # version is fully up, then retire here — excluded from routing,
        # killed only once drained (or past the grace cap). Entries are
        # (replica, since, pending get_metrics ref or None).
        self._retiring: Dict[str, List[Any]] = {}
        # Serializes the reconcile body: actor calls (deploy/delete) and
        # the background loop both reconcile; unsynchronized passes would
        # double-spawn replicas or clobber _retiring.
        self._reconcile_lock = threading.Lock()
        # Replicas confirmed ready (answered check_health); rollouts only
        # retire the old version once every NEW replica is ready.
        self._ready: set = set()
        self._ready_probes: Dict[str, Any] = {}  # actor id -> in-flight ref
        # Replica actor ids observed DEAD (ActorError from a health probe or
        # the state poll): reconcile culls them from the fleet so the
        # scale-up loop respawns replacements — a replica lost mid-scale-up
        # must still converge to the target count.
        self._dead: set = set()
        # Drain-then-retire (cluster KV tier): deployment -> {victim actor
        # key -> survivor actor key}. Published in get_snapshot as
        # "migrations" so routers REWRITE the victim's prefix-affinity
        # entries to the survivor instead of sweeping them.
        self._drain_map: Dict[str, Dict[str, str]] = {}
        # victim actor key -> (out_ref, in_ref, started_at): in-flight KV
        # migrations; _collect_retired holds the kill until the victim's
        # kv_migrate_out resolves (or the drain timeout lapses).
        self._migrations: Dict[str, tuple] = {}
        self._reconcile_thread = threading.Thread(target=self._loop, daemon=True)
        self._reconcile_thread.start()

    # -- control API ---------------------------------------------------------
    def deploy(
        self,
        name: str,
        callable_or_class: Any,
        init_args: tuple,
        init_kwargs: dict,
        config: DeploymentConfig,
        route_prefix: Optional[str],
    ) -> bool:
        with self._lock:
            target = _DeploymentTarget(
                name, callable_or_class, init_args, init_kwargs, config, route_prefix
            )
            asc = config.autoscaling_config
            target.target_replicas = (
                max(asc.min_replicas, 1) if asc else config.num_replicas
            )
            prev = self._targets.get(name)
            target.version = prev.version + 1 if prev is not None else 0
            self._targets[name] = target
        self._reconcile_once()
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            self._targets.pop(name, None)
        self._reconcile_once()
        return True

    def list_deployments(self) -> Dict[str, dict]:
        with self._lock:
            return {
                n: {
                    "target_replicas": t.target_replicas,
                    "num_replicas": len(
                        [r for v, r in self._replicas.get(n, []) if v == t.version]
                    ),
                    "route_prefix": t.route_prefix,
                    "max_ongoing_requests": t.config.max_ongoing_requests,
                }
                for n, t in self._targets.items()
            }

    def set_target_replicas(self, name: str, n: int) -> bool:
        """Pin a deployment's replica count (operator override / tests).
        With autoscaling configured the next policy decision may move it
        again; the scale-down path is the same drain-then-retire either
        way."""
        with self._lock:
            t = self._targets.get(name)
            if t is None:
                return False
            t.target_replicas = max(0, int(n))
        self._reconcile_once()
        return True

    def shutdown(self) -> bool:
        self._running = False
        with self._lock:
            self._targets.clear()
        # The reconcile thread is exiting: kill every replica NOW (graceful
        # draining is for rollouts, not controller teardown) — parking them
        # in _retiring here would leak them forever.
        with self._reconcile_lock:
            victims = [r for reps in self._replicas.values()
                       for _v, r in reps]
            victims += [r for lst in self._retiring.values()
                        for r, _since, _ref in lst]
            self._replicas.clear()
            self._retiring.clear()
            self._migrations.clear()
            self._drain_map.clear()
        for r in victims:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001 — shutdown is best-effort
                log_swallowed(logger, "replica kill at shutdown")
        return True

    # -- long poll (reference: long_poll.py LongPollHost) --------------------
    def get_snapshot(self, known_version: int = -1, timeout_s: float = 0.0):
        """Routing table snapshot; blocks up to timeout_s for a new version."""
        deadline = time.monotonic() + timeout_s
        while self._version == known_version and time.monotonic() < deadline:
            time.sleep(0.005)
        with self._lock:
            table = {}
            for name, t in self._targets.items():
                all_reps = self._replicas.get(name, [])
                fresh = [r for v, r in all_reps if v == t.version]
                ready = [r for r in fresh
                         if r.actor_id.hex() in self._ready]
                outgoing = [r for v, r in all_reps if v != t.version]
                # Rolling redeploy gate: NEW-version replicas join routing
                # only once they pass readiness, and the outgoing fleet
                # keeps serving ALONGSIDE them until it retires (reconcile
                # drains it once every fresh replica is ready) — shifting
                # 100% of traffic onto the first ready new replica would
                # overload it mid-rollout (the reference's rolling update
                # keeps both serving the same way,
                # serve/_private/deployment_state.py). On a first deploy
                # there is no outgoing version: route to the initializing
                # replicas so requests queue instead of 503ing.
                if outgoing:
                    reps = ready + outgoing
                else:
                    reps = ready or fresh
                table[name] = {
                    "replicas": reps,
                    "max_ongoing_requests": t.config.max_ongoing_requests,
                    "route_prefix": t.route_prefix,
                    # model-aware routing (pow_2_scheduler.py:127-135)
                    "model_ids": dict(self._model_ids.get(name, {})),
                    # KV-occupancy-aware routing + admission shedding:
                    # last-polled per-replica metrics (slots_busy,
                    # queue_depth, ...). Advisory — may lag the poll period.
                    "replica_load": dict(self._replica_load.get(name, {})),
                    # Per-tenant admission quotas (serve/admission.py);
                    # handles enforce them in front of the router.
                    "tenant_quotas": t.config.tenant_quotas,
                    # Drain-then-retire rewrites: victim actor key ->
                    # survivor actor key. Routers follow these to move a
                    # drained replica's prefix-affinity entries to the
                    # replica that imported its KV chains.
                    "migrations": dict(self._drain_map.get(name, {})),
                }
            return self._version, table

    # -- metrics / autoscaling ----------------------------------------------
    def record_autoscaling_metrics(self, deployment: str, ongoing: float) -> bool:
        """Handle-side ongoing-requests report (0.2s push cadence). Stores
        an EWMA so one quiet sample between bursts doesn't zero the scaling
        signal. This hook ONLY updates the signal — the scaling decision
        lives solely in the loop's ``_autoscale`` (one decision path; no
        per-report resize trigger)."""
        prev = self._metrics.get(deployment)
        self._metrics[deployment] = (
            float(ongoing) if prev is None else 0.5 * prev + 0.5 * ongoing)
        self._metrics_t[deployment] = time.monotonic()
        return True

    # -- reconcile loop ------------------------------------------------------
    def _loop(self):
        while self._running:
            try:
                self._autoscale()
                self._reconcile_once()
                self._model_poll_tick += 1
                if self._model_poll_tick % 10 == 0:
                    self._poll_multiplexed_ids()
            except Exception:  # noqa: BLE001 — loop must survive
                log_swallowed(logger, "controller reconcile tick")
            time.sleep(0.05)

    def _poll_multiplexed_ids(self):
        """Collect each replica's loaded model set AND load metrics in one
        ``get_state`` RPC (the reference pushes from replicas via
        record_multiplexed_model_ids; polling keeps the replica surface
        passive). A replica that doesn't answer in time — e.g. serially busy
        with a long inference — KEEPS its last-known entry: stale
        warm-routing info beats flapping the routers' tables exactly when
        the replica is loaded. Model-set changes bump the long-poll version;
        pure load changes do NOT (load flaps every poll — routers pick it up
        on their next periodic refresh instead of long-poll churn)."""
        with self._lock:
            replicas = {n: list(rs) for n, rs in self._replicas.items()}
        changed = False
        for name, pairs in replicas.items():
            with self._lock:
                table = dict(self._model_ids.get(name, {}))
            load: Dict[str, dict] = {}
            live_keys = set()
            for _v, replica in pairs:
                key = replica.actor_id.hex()
                live_keys.add(key)
                try:
                    state = ray_tpu.get(
                        replica.get_state.remote(), timeout=0.5)
                except Exception as e:  # noqa: BLE001 — busy or mid-restart:
                    from ray_tpu.core.exceptions import ActorError

                    if isinstance(e, ActorError):
                        self._dead.add(key)  # reconcile respawns it
                    continue       # keep the previous entry
                ids = state.get("model_ids") or []
                if ids:
                    table[key] = ids
                else:
                    table.pop(key, None)
                load[key] = state.get("metrics", {})
            table = {k: v for k, v in table.items() if k in live_keys}
            with self._lock:
                prev_load = self._replica_load.get(name, {})
                # Keep last-known load for replicas that didn't answer.
                kept = {k: v for k, v in prev_load.items()
                        if k in live_keys and k not in load}
                self._replica_load[name] = {**kept, **load}
                if self._model_ids.get(name) != table:
                    self._model_ids[name] = table
                    changed = True
        if changed:
            with self._lock:
                self._version += 1

    # Ongoing-EWMA reports older than this are treated as zero — a handle
    # process that died mid-burst must not pin the signal high forever.
    METRICS_STALE_S = 5.0

    def _autoscale(self):
        """ONE decision path for every scaling signal: delegate each
        deployment to its :class:`SLOPolicy` over a fused
        :class:`DeploymentSignals` snapshot (handle EWMA + replica-poll
        engine stats + TTFT rollup). Rate-limited per deployment by
        serve_autoscaling_interval_s — the 50ms reconcile tick is far
        faster than the signals refresh."""
        with self._lock:
            targets = list(self._targets.values())
        now = time.monotonic()
        interval = config().serve_autoscaling_interval_s
        for t in targets:
            asc = t.config.autoscaling_config
            if asc is None:
                self._policies.pop(t.name, None)
                continue
            if now - self._last_slo_eval.get(t.name, float("-inf")) < interval:
                continue
            self._last_slo_eval[t.name] = now
            policy = self._policies.get(t.name)
            if policy is None or policy.config is not asc:
                # New deployment or redeploy with a new config: fresh
                # policy (cooldown timers reset with the new targets).
                policy = SLOPolicy(asc)
                self._policies[t.name] = policy
            # With the KV tier on every downscale is a drain-by-migration:
            # one victim per decision so each gets a survivor to drain to.
            policy.drain_single_step = bool(config().kv_tier_enabled)
            sig = self._build_signals(t, asc, now)
            desired = policy.desired(t.target_replicas, sig, now)
            if desired > t.target_replicas and policy.ttft_violated(sig):
                # Latency SLO breached AND we're growing: reclaim capacity
                # from lower-priority gangs so the new replicas can place.
                self._gang_preemption.maybe_reclaim(
                    t.name, _replica_shape(t),
                    desired - t.target_replicas, now)
            if desired != t.target_replicas:
                logger.info(
                    "autoscale %s: %d -> %d (pressure=%.2f ttft_p99=%s)",
                    t.name, t.target_replicas, desired,
                    policy.pressure(sig), sig.ttft_p99_s)
                with self._lock:
                    t.target_replicas = desired

    def _build_signals(self, t: _DeploymentTarget, asc: AutoscalingConfig,
                       now: float) -> DeploymentSignals:
        """Fuse the per-replica ``get_state`` poll (engine queue/slot/KV
        stats) with the handle-side ongoing EWMA into one snapshot."""
        with self._lock:
            load = dict(self._replica_load.get(t.name, {}))
            replicas = len([r for v, r in self._replicas.get(t.name, [])
                            if v == t.version])
        ongoing = self._metrics.get(t.name, 0.0)
        if now - self._metrics_t.get(t.name, float("-inf")) \
                > self.METRICS_STALE_S:
            ongoing = 0.0
        queue = busy = total = kv_active = kv_total = polled_ongoing = 0.0
        for m in load.values():
            queue += float(m.get("queue_depth") or 0)
            busy += float(m.get("slots_busy") or 0)
            total += float(m.get("slots_total") or 0)
            active = float(m.get("kv_blocks_active") or 0)
            kv_active += active
            # Cached blocks are reclaimable; only active vs whole pool
            # counts as occupancy pressure.
            kv_total += (active + float(m.get("kv_blocks_cached") or 0)
                         + float(m.get("kv_blocks_free") or 0))
            polled_ongoing += float(m.get("ongoing") or 0)
        ttft = None
        if asc.ttft_p99_slo_s is not None:
            ttft = self._ttft.p99(t.name, now)
        return DeploymentSignals(
            replicas=max(1, replicas),
            # The replica poll also counts in-flight requests — take the
            # larger of the two views (handles may be gone; polls may lag).
            ongoing=max(ongoing, polled_ongoing),
            queue_depth=queue, slots_busy=busy, slots_total=total,
            kv_active=kv_active, kv_total=kv_total, ttft_p99_s=ttft)

    # How long a retiring replica may linger past the router-snapshot age
    # while finishing in-flight requests before it is force-killed.
    RETIRE_GRACE_MAX_S = 15.0
    # Minimum retirement age: at least one router snapshot refresh must
    # elapse so no router is still picking the retiree when it exits.
    RETIRE_MIN_S = 1.5

    def _reconcile_once(self):
        with self._reconcile_lock:
            self._reconcile_locked()

    def _reconcile_locked(self):
        with self._lock:
            targets = dict(self._targets)
        changed = False
        # scale up/down existing deployments — ROLLING on redeploy: the new
        # version spins up to full strength AND turns ready while the old
        # one keeps serving; old replicas then retire (unrouted, drained)
        # rather than being killed under live requests
        # (deployment_state.py's rolling update).
        # Readiness transitions re-publish the routing table: get_snapshot
        # gates new-version replicas on self._ready, so a replica turning
        # ready must bump the long-poll version or routers never pick it up.
        ready_before = set(self._ready)
        # Cull replicas observed dead (ActorError on a probe/poll): dropping
        # them from the fleet makes the scale-up loop below spawn
        # replacements — death during scale-up still converges to target.
        if self._dead:
            dead, self._dead = self._dead, set()
            for key in dead:
                flightrec.record("serve", key[:16], "replica dead")
            for name in list(self._replicas):
                kept = [(v, r) for v, r in self._replicas[name]
                        if r.actor_id.hex() not in dead]
                if len(kept) != len(self._replicas[name]):
                    self._replicas[name] = kept
                    changed = True
            self._ready -= dead
            for key in dead:
                self._ready_probes.pop(key, None)
        for name, t in targets.items():
            current = self._replicas.setdefault(name, [])
            fresh = [(v, r) for v, r in current if v == t.version]
            stale = [(v, r) for v, r in current if v != t.version]
            while len(fresh) < t.target_replicas:
                opts = dict(t.config.ray_actor_options)
                actor_opts: Dict[str, Any] = {}
                if "num_cpus" in opts:
                    actor_opts["num_cpus"] = opts.pop("num_cpus")
                if "num_tpus" in opts:
                    actor_opts["num_tpus"] = opts.pop("num_tpus")
                if "resources" in opts:
                    actor_opts["resources"] = opts.pop("resources")
                if t.config.max_concurrency > 1:
                    # Threaded replica: concurrent streams run inside one
                    # actor (continuous-batching engines need this).
                    actor_opts["max_concurrency"] = t.config.max_concurrency
                replica_cls = ray_tpu.remote(ReplicaActor)
                replica = replica_cls.options(**actor_opts).remote(
                    name,
                    t.callable_or_class,
                    t.init_args,
                    t.init_kwargs,
                    t.config.user_config,
                )
                fresh.append((t.version, replica))
                changed = True
            while len(fresh) > t.target_replicas:
                _, victim = fresh.pop()
                self._retiring.setdefault(name, []).append(
                    (victim, time.monotonic(), None))
                changed = True
            # Probe readiness EVERY tick (not only mid-rollout): the
            # routing gate above needs self._ready populated for first
            # deploys and scale-ups too.
            fresh_all_ready = self._all_ready(r for _v, r in fresh)
            if stale and fresh_all_ready:
                # New version fully up AND ready (answered check_health):
                # stop routing to the old one (the snapshot lists
                # current-version replicas) and drain it. Until then the
                # old version keeps serving — no availability stall while
                # slow replica __init__s run.
                self._retiring.setdefault(name, []).extend(
                    (r, time.monotonic(), None) for _, r in stale)
                stale = []
                changed = True
            current[:] = fresh + stale
        # drop deleted deployments (their replicas drain too)
        for name in list(self._replicas):
            if name not in targets:
                self._retiring.setdefault(name, []).extend(
                    (r, time.monotonic(), None)
                    for _, r in self._replicas.pop(name))
                changed = True
        self._collect_retired()
        if changed or self._ready != ready_before:
            with self._lock:
                self._version += 1

    def _all_ready(self, replicas) -> bool:
        """Non-blocking readiness: fire one check_health per replica, then
        harvest on later ticks — the reconcile loop must never block on a
        slow replica __init__."""
        all_ready = True
        for r in replicas:
            key = r.actor_id.hex()
            if key in self._ready:
                continue
            ref = self._ready_probes.get(key)
            if ref is None:
                self._ready_probes[key] = r.check_health.remote()
                all_ready = False
                continue
            done, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
            if not done:
                all_ready = False
                continue
            self._ready_probes.pop(key, None)
            try:
                ray_tpu.get(ref, timeout=1.0)
                self._ready.add(key)
            except Exception as e:  # noqa: BLE001 — probe again next tick
                from ray_tpu.core.exceptions import ActorError

                if isinstance(e, ActorError):
                    self._dead.add(key)  # reconcile respawns it
                all_ready = False
        if len(self._ready) > 4096:  # dead replicas' entries
            self._ready.clear()
        return all_ready

    def _collect_retired(self):
        now = time.monotonic()
        for name in list(self._retiring):
            keep = []
            for replica, since, probe in self._retiring[name]:
                age = now - since
                done = age > self.RETIRE_GRACE_MAX_S
                if not done and age > self.RETIRE_MIN_S:
                    # Async drain probe: fire get_metrics, harvest next
                    # tick — never block the reconcile loop on a busy
                    # replica.
                    if probe is None:
                        probe = replica.get_metrics.remote()
                    else:
                        ready, _ = ray_tpu.wait([probe], num_returns=1,
                                                timeout=0)
                        if ready:
                            try:
                                metrics = ray_tpu.get(probe, timeout=1.0)
                                done = metrics.get("ongoing", 0) <= 0
                            except Exception:  # noqa: BLE001 — dead
                                done = True
                            probe = None
                if done:
                    # Drain-THEN-retire (cluster KV tier): the replica has
                    # finished its in-flight streams — before the kill,
                    # migrate its warm prefix chains (now including those
                    # streams' final turns) to a survivor and hold the
                    # kill until the migration resolves or times out.
                    done = self._migration_settled(name, replica, now)
                if done:
                    try:
                        ray_tpu.kill(replica)
                    except Exception:  # noqa: BLE001 — already dead
                        log_swallowed(logger, "retired replica kill")
                else:
                    keep.append((replica, since, probe))
            if keep:
                self._retiring[name] = keep
            else:
                self._retiring.pop(name, None)

    def _migration_settled(self, name: str, replica, now: float) -> bool:
        """True when the drained replica's KV migration is complete (or the
        tier is off / no survivor exists / the drain timed out) — only then
        may the kill proceed. First call starts the migration."""
        try:
            if not bool(config().kv_tier_enabled):
                return True
        except Exception:  # noqa: BLE001 — config gone mid-teardown
            return True
        key = replica.actor_id.hex()
        mig = self._migrations.get(key)
        if mig is None:
            return not self._start_migration(name, replica)
        out_ref, in_ref, started = mig
        try:
            timeout = float(config().kv_tier_drain_timeout_s)
        except Exception:  # noqa: BLE001
            timeout = 10.0
        resolved, _ = ray_tpu.wait([out_ref], num_returns=1, timeout=0)
        # +2s: the survivor's kv_migrate_in holds the lane open for the
        # same drain timeout — give the victim's send loop that long too.
        if not resolved and now - started <= timeout + 2.0:
            return False
        self._migrations.pop(key, None)
        for ref in (out_ref, in_ref):  # harvest so errors don't go unread
            try:
                n = ray_tpu.get(ref, timeout=0.5)
                flightrec.record("serve", name, f"kv drain moved {n}")
            except Exception:  # noqa: BLE001 — victim died / timed out
                log_swallowed(logger, "kv drain migration result")
        return True

    def _start_migration(self, name: str, victim) -> bool:
        """Kick off victim -> survivor KV migration: survivor CREATES the
        lane (kv_migrate_in), victim attaches and ships (kv_migrate_out),
        and the routing snapshot learns the affinity rewrite. False when
        there is nothing to migrate to (last replica / none ready)."""
        vkey = victim.actor_id.hex()
        with self._lock:
            t = self._targets.get(name)
            if t is None:
                return False
            fresh = [r for v, r in self._replicas.get(name, [])
                     if v == t.version]
            ready = [r for r in fresh if r.actor_id.hex() in self._ready]
        candidates = [r for r in (ready or fresh)
                      if r.actor_id.hex() != vkey]
        if not candidates:
            return False
        survivor = candidates[0]
        skey = survivor.actor_id.hex()
        lane = f"kvdrain-{name}-{vkey[:12]}"
        try:
            in_ref = survivor.kv_migrate_in.remote(lane)
            out_ref = victim.kv_migrate_out.remote(lane)
        except Exception:  # noqa: BLE001 — either side already dead
            log_swallowed(logger, "kv drain migration start")
            return False
        self._migrations[vkey] = (out_ref, in_ref, time.monotonic())
        with self._lock:
            dm = self._drain_map.setdefault(name, {})
            dm[vkey] = skey
            while len(dm) > 64:  # bounded history; routers refresh fast
                dm.pop(next(iter(dm)))
            self._version += 1  # long-poll: routers must see the rewrite
        flightrec.record("serve", name,
                         f"kv drain {vkey[:12]} -> {skey[:12]}")
        return True


def get_or_create_controller():
    """Singleton via named DETACHED actor (reference: serve's detached
    controller) — the control plane, like the per-node proxy actors,
    outlives the driver that created it (serve.shutdown() kills it)."""
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        cls = ray_tpu.remote(ServeControllerActor)
        return cls.options(name=CONTROLLER_NAME, num_cpus=0,
                           lifetime="detached").remote()
