"""ray_tpu.serve — model serving over the distributed runtime.

Public surface mirrors ``ray.serve``: @deployment/bind/run, DeploymentHandle,
HTTP ingress, autoscaling, batching.
"""

from ray_tpu.serve.api import (
    delete,
    drain_proxy,
    get_deployment_handle,
    grpc_proxy_address,
    proxy_grpc_addresses,
    run,
    run_pipeline,
    shutdown,
    start_proxies,
    status,
)
from ray_tpu.serve.dag_pipeline import PipelineHandle, SequentialPipelineHandle
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.errors import Saturated
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "run",
    "run_pipeline",
    "PipelineHandle",
    "SequentialPipelineHandle",
    "shutdown",
    "status",
    "delete",
    "get_deployment_handle",
    "DeploymentHandle",
    "DeploymentResponse",
    "AutoscalingConfig",
    "DeploymentConfig",
    "Saturated",
    "batch",
    "multiplexed",
    "get_multiplexed_model_id",
    "start_proxies",
    "drain_proxy",
    "proxy_grpc_addresses",
    "grpc_proxy_address",
]
