"""Per-node ingress proxy actors + drain lifecycle.

Analog of the reference's ``python/ray/serve/_private/proxy_state.py``: the
ingress data plane runs as PLACED, DETACHED actors (one per target node),
not a thread of the driver — HTTP availability survives driver exit, and
scale-down drains a proxy (reject new, finish in-flight) before removal.
"""

from __future__ import annotations

from typing import Dict

import ray_tpu
from ray_tpu.core.task_spec import NodeAffinitySchedulingStrategy

PROXY_NAME_PREFIX = "SERVE_PROXY"


class _ProxyActorImpl:
    """Hosts one HttpProxy (and optionally one GrpcProxy) inside a cluster
    worker process — the reference's proxy actor runs both ingress
    protocols in one process the same way
    (``serve/_private/proxy.py:533 gRPCProxy`` beside the HTTP half)."""

    def __init__(self, controller_name: str, port: int = 0,
                 grpc_port: int | None = None):
        from ray_tpu.serve.proxy import HttpProxy

        self._controller = ray_tpu.get_actor(controller_name)
        self._proxy = HttpProxy(self._controller, port=port)
        self._proxy.start()
        self._grpc = None
        if grpc_port is not None:
            from ray_tpu.serve.grpc_proxy import GrpcProxy

            self._grpc = GrpcProxy(self._controller, port=grpc_port)
            self._grpc.start()

    def address(self) -> str:
        # The proxy binds this host; report the interface clients reach the
        # node on (loopback clusters stay loopback).
        host = self._proxy.host
        return f"{host}:{self._proxy.bound_port}"

    def grpc_address(self) -> str | None:
        return self._grpc.address if self._grpc is not None else None

    def ensure_grpc(self, port: int = 0) -> str:
        """Start the gRPC ingress in this (already running) proxy actor if
        it isn't serving yet — the upgrade path for fleets that were
        created HTTP-only."""
        if self._grpc is None:
            from ray_tpu.serve.grpc_proxy import GrpcProxy

            self._grpc = GrpcProxy(self._controller, port=port)
            self._grpc.start()
        return self._grpc.address

    def ready(self) -> bool:
        return self._proxy.bound_port is not None

    def num_in_flight(self) -> int:
        n = self._proxy.num_in_flight
        if self._grpc is not None:
            n += self._grpc.num_in_flight
        return n

    def drain(self, timeout_s: float = 30.0) -> bool:
        # Both protocols stop accepting IMMEDIATELY, then wait on ONE
        # shared deadline (sequential waits would double the caller's
        # timeout under stuck in-flight requests).
        import time as _time

        self._proxy.begin_drain()
        if self._grpc is not None:
            self._grpc.begin_drain()
        deadline = _time.time() + timeout_s
        while _time.time() < deadline:
            if self.num_in_flight() == 0:
                return True
            _time.sleep(0.02)
        return self.num_in_flight() == 0

    def stop(self) -> bool:
        self._proxy.stop()
        if self._grpc is not None:
            self._grpc.stop()
        return True


class ProxyManager:
    """Driver/controller-side view of the proxy fleet.

    ``sync()`` reconciles: one proxy actor per alive node (node-affinity
    placed, detached so it outlives the driver); ``drain_node()`` runs the
    scale-down protocol: drain (reject new, finish in-flight) → stop →
    kill.
    """

    def __init__(self, controller_name: str, port: int = 0,
                 grpc_port: int | None = None):
        self._controller_name = controller_name
        self._port = port
        self._grpc_port = grpc_port
        self._proxies: Dict[str, object] = {}   # node_id -> actor handle
        self._addresses: Dict[str, str] = {}
        self._grpc_addresses: Dict[str, str] = {}

    def sync(self) -> Dict[str, str]:
        """Ensure a proxy on every alive node; returns node_id -> addr."""
        alive = {n["NodeID"]: n for n in ray_tpu.nodes() if n.get("Alive")}
        proxy_cls = ray_tpu.remote(_ProxyActorImpl)
        for node_id in alive:
            if node_id in self._proxies:
                continue
            name = f"{PROXY_NAME_PREFIX}::{node_id[:12]}"
            try:
                handle = ray_tpu.get_actor(name)
            except Exception:  # noqa: BLE001 — not running yet
                handle = proxy_cls.options(
                    name=name,
                    num_cpus=0,
                    lifetime="detached",
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=node_id),
                ).remote(self._controller_name, self._port,
                         grpc_port=self._grpc_port)
            ray_tpu.get(handle.ready.remote(), timeout=60)
            self._proxies[node_id] = handle
            self._addresses[node_id] = ray_tpu.get(handle.address.remote(),
                                                   timeout=30)
            g = ray_tpu.get(handle.grpc_address.remote(), timeout=30)
            if g is None and self._grpc_port is not None:
                # Attached to a pre-existing HTTP-only actor (e.g. started
                # by an earlier driver) while this manager wants gRPC:
                # upgrade it in place instead of silently serving nothing.
                g = ray_tpu.get(handle.ensure_grpc.remote(self._grpc_port),
                                timeout=60)
            if g:
                self._grpc_addresses[node_id] = g
        for node_id in list(self._proxies):
            if node_id not in alive:
                self._proxies.pop(node_id, None)
                self._addresses.pop(node_id, None)
                self._grpc_addresses.pop(node_id, None)
        return dict(self._addresses)

    def addresses(self) -> Dict[str, str]:
        return dict(self._addresses)

    def grpc_addresses(self) -> Dict[str, str]:
        return dict(self._grpc_addresses)

    def enable_grpc(self, grpc_port: int = 0) -> Dict[str, str]:
        """Upgrade an HTTP-only fleet in place: every live proxy actor
        starts its gRPC ingress (``ensure_grpc``); new actors get it at
        spawn. Returns node_id -> gRPC address."""
        self._grpc_port = grpc_port
        for node_id, handle in self._proxies.items():
            self._grpc_addresses[node_id] = ray_tpu.get(
                handle.ensure_grpc.remote(grpc_port), timeout=60)
        return dict(self._grpc_addresses)

    def drain_node(self, node_id: str, timeout_s: float = 30.0) -> bool:
        """Scale-down: no new requests, in-flight finish, then the proxy
        exits. True iff fully drained within the timeout."""
        handle = self._proxies.pop(node_id, None)
        self._addresses.pop(node_id, None)
        self._grpc_addresses.pop(node_id, None)
        if handle is None:
            return True
        drained = ray_tpu.get(handle.drain.remote(timeout_s),
                              timeout=timeout_s + 30)
        try:
            ray_tpu.get(handle.stop.remote(), timeout=30)
        finally:
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        return bool(drained)

    def shutdown(self) -> None:
        for node_id in list(self._proxies):
            self.drain_node(node_id, timeout_s=5.0)

    @staticmethod
    def drain_detached(node_id: str, timeout_s: float = 30.0) -> bool:
        """Drain a proxy THIS process didn't start: resolve the detached
        actor by its well-known name. True if drained or not running."""
        name = f"{PROXY_NAME_PREFIX}::{node_id[:12]}"
        try:
            handle = ray_tpu.get_actor(name)
        except Exception:  # noqa: BLE001 — no proxy on that node
            return True
        drained = ray_tpu.get(handle.drain.remote(timeout_s),
                              timeout=timeout_s + 30)
        try:
            ray_tpu.get(handle.stop.remote(), timeout=30)
        finally:
            try:
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        return bool(drained)
