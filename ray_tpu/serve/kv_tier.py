"""Cluster-wide KV tier: spilled prefix chains as shared cluster objects.

The paged engine's prefix cache (``models/generate.KVBlockManager``) is
engine-private: two replicas never share a block, and a downscaled replica
takes every warm session with it. This module promotes retired chains to
the object plane:

- **Spill** — the engine's retire path extracts a chain's FULL blocks off
  device, wraps them in an immutable content-addressed payload (keyed by
  the chain's ``prefix_head_hash``) and publishes it here: payload into the
  object store, locator into the cluster **prefix directory**
  (``core/gcs_shards.ShardedPrefixDirectory`` on the GCS — digest →
  (object id, token count, replica hint), refcounted per publisher).
- **Fetch** — a prefill whose LOCAL lookup missed probes the directory
  with the prompt's chained digests; a hit pulls the payload back (the
  runtime ``get`` path — striped multi-source pulls on a multiprocess
  cluster) and the engine inserts the blocks into its own pool instead of
  recomputing prefill. A fetch that finds the payload gone (publisher
  died, GCS restarted over a stale snapshot) **drops** the directory entry
  — the self-heal path that keeps the index free of dangling object ids.

Two backends behind one client API, resolved once at first use:

- **runtime** — a live ray_tpu runtime: directory calls go through
  ``get_runtime().gcs.prefix_*`` (works on the in-process AND multiprocess
  runtimes — same facade), payloads ride ``runtime.put`` / ``runtime.get``
  with the publishing client holding the pinning ObjectRef until release.
- **local** — no runtime (bare-engine unit tests, benches): a process-local
  singleton directory + payload dict with the same semantics, so
  same-process engines still share a tier.

Never resolves a backend by *initializing* anything: an engine constructed
before ``ray_tpu.init()`` stays on the local backend for its lifetime.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import config
from ray_tpu.util import flightrec
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger("kv_tier")

__all__ = ["KVTier", "kv_tier_enabled", "reset_local_backend"]


def kv_tier_enabled() -> bool:
    """Master switch (``kv_tier_enabled`` flag): off = engine-private KV
    and sweep-only downscale, byte-identical to pre-tier behavior."""
    try:
        return bool(config().kv_tier_enabled)
    except Exception:  # noqa: BLE001 — config unavailable mid-teardown
        return False


# -- local (runtime-less) backend ---------------------------------------------


class _LocalBackend:
    """Process-local tier: the ShardedPrefixDirectory plus a payload dict,
    shared by every runtime-less engine in this process."""

    def __init__(self):
        from ray_tpu.core.gcs_shards import ShardedPrefixDirectory

        self._lock = threading.Lock()
        self._payloads: Dict[bytes, Any] = {}
        self.directory = ShardedPrefixDirectory(
            1, max_entries=int(config().kv_tier_dir_max_entries),
            ttl_s=float(config().kv_tier_dir_ttl_s), on_free=self._on_free)

    def _on_free(self, digest: bytes, _entry: Dict[str, Any]) -> None:
        with self._lock:
            self._payloads.pop(bytes(digest), None)

    def _apply_bounds(self) -> None:
        self.directory.max_entries = int(config().kv_tier_dir_max_entries)
        self.directory.ttl_s = float(config().kv_tier_dir_ttl_s)

    def prepare(self, payload: Any) -> Any:
        """One payload handle shared by every prefix entry of a chain —
        the local backend stores the object itself."""
        return payload

    def publish(self, digest: bytes, handle: Any, token_count: int,
                n_blocks: int, hint: str) -> bool:
        self._apply_bounds()
        with self._lock:
            self._payloads[bytes(digest)] = handle
        return self.directory.publish(digest, b"local", token_count,
                                      n_blocks, hint=hint)

    def match(self, digests: List[bytes]):
        return self.directory.match(digests)

    def fetch(self, digest: bytes, _entry: Dict[str, Any]):
        with self._lock:
            return self._payloads.get(bytes(digest))

    def release(self, digest: bytes) -> bool:
        return self.directory.release(digest)

    def drop(self, digest: bytes) -> bool:
        return self.directory.drop(digest)

    def stats(self) -> Dict[str, int]:
        st = self.directory.stats()
        with self._lock:
            st["prefix_dir_payloads"] = len(self._payloads)
        return st


_local_lock = threading.Lock()
_local: Optional[_LocalBackend] = None


def _local_backend() -> _LocalBackend:
    global _local
    with _local_lock:
        if _local is None:
            _local = _LocalBackend()
        return _local


def reset_local_backend() -> None:
    """Drop the process-local tier (test isolation between engine runs)."""
    global _local
    with _local_lock:
        _local = None


# -- runtime backend ----------------------------------------------------------


class _RuntimeBackend:
    """Directory on the GCS (``prefix_*`` RPCs), payloads in the object
    store. The PUBLISHER pins its payload with a live ObjectRef; the
    directory entry carries only the 28-byte object id, so a reader
    reconstructs a borrowing ref, pulls, and lets it go."""

    def __init__(self, runtime):
        self._rt = runtime
        self._lock = threading.Lock()
        self._pins: Dict[bytes, Any] = {}  # digest -> pinning ObjectRef

    def prepare(self, payload: Any) -> Any:
        """Put the payload ONCE; every prefix entry of the chain aliases
        the same object (content addressing: the payload's leading blocks
        are the shorter chains)."""
        return self._rt.put(payload)

    def publish(self, digest: bytes, handle: Any, token_count: int,
                n_blocks: int, hint: str) -> bool:
        created = self._rt.gcs.prefix_publish(
            bytes(digest), handle.id.binary(), token_count, n_blocks, hint)
        if created:
            with self._lock:
                self._pins[bytes(digest)] = handle
        # not created: an identical chain is already indexed — our pin on
        # the shared ref is dropped at release and refcounting frees it.
        return created

    def match(self, digests: List[bytes]):
        return self._rt.gcs.prefix_match([bytes(d) for d in digests])

    def fetch(self, digest: bytes, entry: Dict[str, Any]):
        from ray_tpu.core.ids import ObjectID
        from ray_tpu.core.object_ref import ObjectRef

        ref = ObjectRef(ObjectID(bytes(entry["meta"])),
                        owner_hint=entry.get("hint") or None)
        return self._rt.get(ref, timeout=5.0)

    def release(self, digest: bytes) -> bool:
        removed = False
        try:
            removed = bool(self._rt.gcs.prefix_release(bytes(digest)))
        except Exception:  # noqa: BLE001 — GCS mid-restart: drop pin anyway
            log_swallowed(logger, "prefix_release")
        with self._lock:
            self._pins.pop(bytes(digest), None)  # ref dies -> object freed
        return removed

    def drop(self, digest: bytes) -> bool:
        try:
            return bool(self._rt.gcs.prefix_drop(bytes(digest)))
        except Exception:  # noqa: BLE001 — self-heal is best-effort
            log_swallowed(logger, "prefix_drop")
            return False

    def stats(self) -> Dict[str, int]:
        try:
            return dict(self._rt.gcs.prefix_stats())
        except Exception:  # noqa: BLE001 — GCS mid-restart
            return {}


# -- client -------------------------------------------------------------------


class KVTier:
    """One engine's handle on the cluster KV tier.

    Tracks what THIS client published so refcounts drain deterministically:
    each head digest is published at most once per client, and
    :meth:`close` releases every outstanding publish (directory refs and
    object pins both reach zero when every client closes — the
    ``RAY_TPU_LEAK_CHECK_ENABLED`` invariant).
    """

    def __init__(self, deployment: str = ""):
        self.deployment = deployment
        self._lock = threading.Lock()
        # head digest -> n_blocks published (for the spilled-blocks gauge)
        self._published: Dict[bytes, int] = {}
        self._backend = None

    def _resolve(self):
        if self._backend is not None:
            return self._backend
        try:
            from ray_tpu.core.runtime import get_runtime

            rt = get_runtime()  # raises when not initialized — never inits
            if hasattr(rt.gcs, "prefix_publish"):
                self._backend = _RuntimeBackend(rt)
        except Exception:  # noqa: BLE001 — no runtime: local tier
            log_swallowed(logger, "kv tier backend resolve")
        if self._backend is None:
            self._backend = _local_backend()
        return self._backend

    def is_published(self, digest: bytes) -> bool:
        with self._lock:
            return bytes(digest) in self._published

    def publish_chain(self, digests: List[bytes], payload: Any,
                      token_count: int, n_blocks: int) -> bool:
        """Spill one chain: the payload goes to the object plane ONCE, and
        every prefix digest of the chain gets a directory entry aliasing it
        — content addressing means the payload's first ``i + 1`` blocks ARE
        the chain ``digests[i]`` keys, so a prompt that only covers part of
        the spilled chain still matches. Idempotent per client; True when
        this call indexed new content."""
        digests = [bytes(d) for d in digests][:int(n_blocks)]
        if not digests:
            return False
        n_blocks = len(digests)
        head = digests[-1]
        with self._lock:
            if all(d in self._published for d in digests):
                return False
        backend = self._resolve()
        handle = backend.prepare(payload)
        bt = int(token_count) // n_blocks
        created = False
        for i, d in enumerate(digests):
            with self._lock:
                if d in self._published:
                    continue
            if backend.publish(d, handle, (i + 1) * bt, i + 1,
                               self.deployment):
                created = True
            with self._lock:
                # The gauge counts each payload's blocks once — on its
                # head entry; prefix aliases carry no extra device bytes.
                self._published[d] = n_blocks if i == n_blocks - 1 else 0
        flightrec.record("serve", self.deployment or "kv_tier",
                         f"kv spill {n_blocks}b {head.hex()[:12]}")
        return created

    def match(self, digests: List[bytes]) -> Optional[Tuple[int, Dict[str, Any]]]:
        """Longest directory match over the probe chain's digests —
        ``(block_index, entry)`` where ``block_index`` indexes ``digests``
        (entry covers blocks ``0..block_index`` inclusive)."""
        if not digests:
            return None
        return self._resolve().match(digests)

    def fetch(self, digest: bytes, entry: Dict[str, Any]):
        """Pull a matched payload back; a miss DROPS the directory entry
        (self-heal) and returns None."""
        digest = bytes(digest)
        backend = self._resolve()
        try:
            payload = backend.fetch(digest, entry)
        except Exception:  # noqa: BLE001 — object gone / pull timed out
            payload = None
        if payload is None:
            backend.drop(digest)
            flightrec.record("serve", self.deployment or "kv_tier",
                             f"kv fetch MISS drop {digest.hex()[:12]}")
            return None
        flightrec.record("serve", self.deployment or "kv_tier",
                         f"kv fetch {digest.hex()[:12]}")
        return payload

    def release(self, digest: bytes) -> None:
        """Withdraw one of this client's publishes (chain evicted for good,
        or client closing)."""
        digest = bytes(digest)
        with self._lock:
            if self._published.pop(digest, None) is None:
                return
        self._resolve().release(digest)

    def spilled_blocks(self) -> int:
        with self._lock:
            return sum(self._published.values())

    def stats(self) -> Dict[str, int]:
        st = self._resolve().stats()
        st["kv_tier_published_here"] = len(self._published)
        return st

    def close(self) -> None:
        """Release every outstanding publish — directory refs (and the
        runtime backend's object pins) drain to zero."""
        with self._lock:
            digests = list(self._published)
            self._published.clear()
        backend = self._resolve()
        for d in digests:
            try:
                backend.release(d)
            except Exception:  # noqa: BLE001 — teardown best-effort
                log_swallowed(logger, "kv tier release")
