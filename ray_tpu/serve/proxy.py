"""HTTP proxy — the ingress data plane.

Analog of the reference's ``python/ray/serve/_private/proxy.py`` (uvicorn +
starlette there; aiohttp here — what the image ships). Routes by longest
matching ``route_prefix`` from the controller's long-poll snapshot, forwards
to a DeploymentHandle, supports JSON bodies and streaming (chunked) responses
from generator deployments. Runs its own event loop in a daemon thread —
the in-runtime analog of the reference's proxy actor on each ingress node.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from typing import Any, Dict, Optional

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle


class HttpProxy:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 8000):
        self._controller = controller
        self.host = host
        self.port = port          # 0 = ephemeral; see bound_port after start
        self.bound_port: Optional[int] = None
        self._handles: Dict[str, DeploymentHandle] = {}
        self._routes: Dict[str, str] = {}  # prefix -> deployment name
        self._thread: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._runner = None
        # Drain protocol (reference: serve/_private/proxy_state.py): a
        # draining proxy rejects NEW requests (503 + Connection: close) but
        # lets in-flight ones finish before it reports drained.
        self._draining = False
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._serve_forever, daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("HTTP proxy failed to start")

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        if self._thread is not None:
            self._thread.join(timeout=5)

    @property
    def num_in_flight(self) -> int:
        with self._in_flight_lock:
            return self._in_flight

    def begin_drain(self) -> None:
        # Set under the in-flight lock so _handle's check+increment (same
        # lock) can't slip a request past the drain check uncounted.
        with self._in_flight_lock:
            self._draining = True

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Stop accepting new requests; True once no request is in flight."""
        self.begin_drain()
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.num_in_flight == 0:
                return True
            time.sleep(0.02)
        return self.num_in_flight == 0

    def _serve_forever(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", self._handle)
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        socks = getattr(site._server, "sockets", None)
        self.bound_port = socks[0].getsockname()[1] if socks else self.port
        self._runner = runner
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(runner.cleanup())
            from ray_tpu.utils.eventloop import drain_and_close_loop

            drain_and_close_loop(loop, "serve.proxy")

    # -- routing -------------------------------------------------------------
    def _refresh_routes(self) -> None:
        _, table = ray_tpu.get(self._controller.get_snapshot.remote(-2, 0.0))
        routes = {}
        for name, entry in table.items():
            if entry.get("route_prefix"):
                routes[entry["route_prefix"]] = name
        self._routes = routes

    def _match(self, path: str) -> Optional[str]:
        best = None
        for prefix, name in self._routes.items():
            if path == prefix or path.startswith(prefix.rstrip("/") + "/") or prefix == "/":
                if best is None or len(prefix) > len(best[0]):
                    best = (prefix, name)
        return best[1] if best else None

    async def _handle(self, request):
        from aiohttp import web

        with self._in_flight_lock:
            draining = self._draining
            if not draining:
                self._in_flight += 1
        if draining:
            return web.Response(
                status=503, text="proxy draining",
                headers={"Connection": "close"})
        try:
            return await self._handle_inner(request)
        finally:
            with self._in_flight_lock:
                self._in_flight -= 1

    async def _handle_inner(self, request):
        from aiohttp import web

        self._refresh_routes()
        name = self._match(request.path)
        if name is None:
            return web.Response(status=404, text=f"no route for {request.path}")
        if name not in self._handles:
            self._handles[name] = DeploymentHandle(name, self._controller)
        handle = self._handles[name]

        if request.can_read_body:
            raw = await request.read()
            try:
                payload = json.loads(raw) if raw else {}
            except json.JSONDecodeError:
                payload = raw.decode()
        else:
            payload = dict(request.query)

        loop = asyncio.get_event_loop()
        stream = request.headers.get("X-Serve-Stream") == "1"
        if stream:
            gen = handle.options(stream=True).remote(payload)
            resp = web.StreamResponse()
            resp.headers["Content-Type"] = "text/plain"
            await resp.prepare(request)
            it = iter(gen)
            while True:
                item = await loop.run_in_executor(None, lambda: next(it, _SENTINEL))
                if item is _SENTINEL:
                    break
                await resp.write((json.dumps(_jsonable(item)) + "\n").encode())
            await resp.write_eof()
            return resp

        response = handle.remote(payload)
        result = await loop.run_in_executor(None, response.result)
        return web.json_response(_jsonable(result))


_SENTINEL = object()


def _jsonable(x: Any):
    import numpy as np

    if isinstance(x, (np.generic,)):
        return x.item()
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    return x
