"""LLM serving — KV-cache decode engine + Serve deployment factory.

The reference serves LLMs by embedding engines (vLLM) inside replicas;
TPU-native the engine is two jitted XLA programs (``models/generate.py``):
prefill writes the prompt's K/V into a static-shape cache once, decode reads
it per token — O(1) in context length instead of the full-window forward.

Serving adds two things on top of the raw ``Generator``:

- **Prompt bucketing**: prefill compiles per prompt length; real traffic has
  arbitrary lengths. Prompts pad up to a power-of-two bucket, the first-token
  logits are read at the *real* last position, and decode starts at the real
  length (overwriting pad garbage before it ever becomes attendable — the
  causal mask keeps padded K/V invisible until then). One compile per bucket,
  all warmed at replica start so TTFT never pays XLA compilation.
- **A deployment factory** wiring the engine into the Serve data plane
  (streaming responses ride the generator path the router already supports).

Measured v5e TTFT (GPT-2-124M, 16-token prompt): ~5 ms p50 vs ~103 ms for
the round-1 full-window path.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ray_tpu.models.generate import Generator, init_cache
from ray_tpu.models.transformer import TransformerConfig


def _default_buckets(max_len: int) -> List[int]:
    buckets, b = [], 16
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


class LLMEngine:
    """Bucketed prefill + cached decode for one replica.

    Single-sequence decode (batch=1) — concurrency comes from Serve replica
    scaling; in-flight/continuous batching is a later optimization.
    """

    def __init__(self, params, config: TransformerConfig, *,
                 max_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 chunk: int = 8):
        import jax

        self.params = params
        self.config = config
        self.max_len = max_len or config.max_seq_len
        self.buckets = sorted(prompt_buckets or _default_buckets(self.max_len))
        self.chunk = chunk
        self._gen = Generator(params, config, batch=1, max_len=self.max_len)
        self._jax = jax
        self.decode_tokens = 0
        self.decode_seconds = 0.0
        self.finish_reason = "stop"

    def warmup(self) -> None:
        """Compile the fused prefill+decode for every bucket (greedy and
        sampled variants) + the follow-up decode chunk."""
        import jax
        import jax.numpy as jnp

        for sampled in (False, True):
            pre, dec = self._gen.chunked_fns(self.chunk, sampled)
            for b in self.buckets:
                cache = init_cache(self.config, 1, self.max_len)
                toks, last, cache, pos, rng = pre(
                    self.params, cache, jnp.zeros((1, b), jnp.int32),
                    jnp.asarray(b, jnp.int32), jax.random.key(0),
                    jnp.asarray(1.0, jnp.float32))
                if b == self.buckets[0]:
                    toks, last, cache, pos, rng = dec(
                        self.params, cache, last, pos, rng,
                        jnp.asarray(1.0, jnp.float32))
                np.asarray(toks)

    def _bucket_for(self, n: int) -> int:
        # One full decode chunk must fit after the prompt: the fused
        # prefill+decode always runs `chunk` scan steps, and K/V writes past
        # max_len would clamp onto the last slot and corrupt the cache.
        if n + self.chunk > self.max_len:
            raise ValueError(
                f"prompt of {n} tokens leaves no room for a {self.chunk}-token "
                f"decode chunk within max_len {self.max_len}")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds max_len {self.max_len}")

    def stream(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0,
               result: Optional[Dict] = None) -> Iterable[int]:
        """Yield generated token ids, ``chunk`` tokens per device dispatch.

        The sampling loop runs on-device inside a ``lax.scan`` — K tokens
        cost ONE host↔device round trip, which is the whole game on a
        tunneled chip (~100 ms RTT) and still 10-20% on a colocated host.

        ``result``, if given, receives ``{"finish_reason": ...}`` — pass a
        fresh dict per request; the engine-level ``finish_reason`` attribute
        is a convenience for single-stream use and races under concurrency.
        """
        import jax
        import jax.numpy as jnp

        if result is None:
            result = {}
        prompt = np.asarray(prompt_ids, np.int32)
        real_len = int(prompt.shape[0])
        if real_len == 0:
            raise ValueError("empty prompt")
        if max_new_tokens <= 0:
            result["finish_reason"] = self.finish_reason = "stop"
            return
        bucket = self._bucket_for(real_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :real_len] = prompt

        sampled = temperature > 0
        pre, dec = self._gen.chunked_fns(self.chunk, sampled)
        temp = jnp.asarray(temperature if sampled else 1.0, jnp.float32)
        rng = jax.random.key(seed)
        cache = init_cache(self.config, 1, self.max_len)
        toks, last, cache, pos, rng = pre(
            self.params, cache, jnp.asarray(padded),
            jnp.asarray(real_len, jnp.int32), rng, temp)
        emitted = 0
        host_pos = real_len + self.chunk  # device pos mirrors this exactly
        result["finish_reason"] = self.finish_reason = "stop"
        dispatched_at = None  # dispatch time of the chunk in `toks` (dec only)
        while True:
            host_toks = np.asarray(toks)[0]  # sync point: one per chunk
            if dispatched_at is not None:
                # Steady-state gauge: dec chunks only (prefill excluded).
                self.decode_seconds += time.perf_counter() - dispatched_at
                self.decode_tokens += len(host_toks)
            # Dispatch the NEXT chunk before yielding this one: device decode
            # overlaps token delivery (and, on a tunneled chip, the RTT).
            want_more = emitted + len(host_toks) < max_new_tokens
            have_room = host_pos + self.chunk <= self.max_len
            nxt, next_dispatched_at = None, None
            if want_more and have_room:
                next_dispatched_at = time.perf_counter()
                nxt = dec(self.params, cache, last, pos, rng, temp)
                host_pos += self.chunk
            for tok in host_toks:
                yield int(tok)
                emitted += 1
                if emitted >= max_new_tokens:
                    return
            if nxt is None:
                # No room for another full chunk: context-length cap.
                result["finish_reason"] = self.finish_reason = "length_cap"
                return
            toks, last, cache, pos, rng = nxt
            dispatched_at = next_dispatched_at

    def generate(self, prompt_ids: Sequence[int], **kw) -> List[int]:
        return list(self.stream(prompt_ids, **kw))

    def decode_tokens_per_sec(self) -> float:
        if self.decode_seconds == 0:
            return 0.0
        return self.decode_tokens / self.decode_seconds

    def device_metrics(self, *, prompt_len: int = 16, reps: int = 10) -> Dict:
        """Device-side TTFT and decode rate, excluding host↔device RTT.

        Dispatches ``reps`` fused prefill+chunk calls (and decode chunks)
        back-to-back with ONE final sync, so per-call async dispatch overlaps
        and the measurement reflects pure device time — what a request sees
        on a production host with a colocated chip, where the data plane
        adds ~0.2 ms (measured actor RTT), not the tunnel's ~100 ms.
        """
        import jax
        import jax.numpy as jnp

        bucket = self._bucket_for(prompt_len)
        pre, dec = self._gen.chunked_fns(self.chunk, False)
        temp = jnp.asarray(1.0, jnp.float32)
        padded = jnp.zeros((1, bucket), jnp.int32)
        rl = jnp.asarray(prompt_len, jnp.int32)

        # TTFT: prefill + first chunk of tokens, pipelined.
        outs = []
        t0 = time.perf_counter()
        for i in range(reps):
            cache = init_cache(self.config, 1, self.max_len)
            toks, *_ = pre(self.params, cache, padded, rl,
                           jax.random.key(i), temp)
            outs.append(toks)
        jax.block_until_ready(outs)
        ttft_ms = (time.perf_counter() - t0) / reps * 1e3

        # Steady-state decode: chained chunks, single sync at the end.
        # Bounded by cache room — never dispatch past max_len.
        n_chunks = (self.max_len - prompt_len) // self.chunk - 1
        if n_chunks < 1:
            return {"device_ttft_ms": round(ttft_ms, 2),
                    "device_decode_tokens_per_sec": 0.0}
        cache = init_cache(self.config, 1, self.max_len)
        toks, last, cache, pos, rng = pre(
            self.params, cache, padded, rl, jax.random.key(0), temp)
        t0 = time.perf_counter()
        for _ in range(n_chunks):
            toks, last, cache, pos, rng = dec(
                self.params, cache, last, pos, rng, temp)
        jax.block_until_ready(toks)
        dt = time.perf_counter() - t0
        return {
            "device_ttft_ms": round(ttft_ms, 2),
            "device_decode_tokens_per_sec": round(n_chunks * self.chunk / dt, 1),
        }


def llm_deployment(
    config: TransformerConfig,
    params_fn: Callable[[], Dict],
    *,
    name: str = "LLM",
    max_new_tokens_default: int = 32,
    **deployment_kwargs,
):
    """Build a Serve deployment class around an :class:`LLMEngine`.

    ``params_fn`` runs inside the replica (checkpoint load / init) so weights
    never ship through the controller. Request payload::

        {"prompt_ids": [...], "max_new_tokens": n, "temperature": t,
         "seed": s}

    Responses stream ``{"token": id, "index": i, "decode_tps": rate}``
    dicts (call the handle with ``stream=True``); the final item adds
    ``finish_reason`` ("stop" | "length_cap"). Sampled requests without an
    explicit ``seed`` draw a fresh one per request.
    """
    import random as _random

    from ray_tpu import serve

    @serve.deployment(name=name, **deployment_kwargs)
    class LLMServer:
        def __init__(self):
            self.engine = LLMEngine(params_fn(), config)
            self.engine.warmup()

        def __call__(self, payload):
            if "prompt_ids" in payload:
                prompt = payload["prompt_ids"]  # empty list → engine raises
            else:
                prompt = [1] * int(payload.get("prompt_len", 8))
            n = int(payload.get("max_new_tokens", max_new_tokens_default))
            temp = float(payload.get("temperature", 0.0))
            seed = payload.get("seed")
            if seed is None:
                seed = _random.getrandbits(31)
            outcome: dict = {}  # per-request, not the shared engine attr
            stream = self.engine.stream(
                prompt, max_new_tokens=n, temperature=temp, seed=int(seed),
                result=outcome)
            prev: dict | None = None
            for i, tok in enumerate(stream):
                if prev is not None:
                    yield prev
                prev = {"token": tok, "index": i,
                        "decode_tps": round(self.engine.decode_tokens_per_sec(), 1)}
            if prev is not None:
                prev["finish_reason"] = outcome.get("finish_reason", "stop")
                yield prev

    return LLMServer

