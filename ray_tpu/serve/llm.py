"""LLM serving — continuous-batching KV-cache engine + Serve deployment.

The reference serves LLMs by embedding engines (vLLM) inside replicas;
TPU-native the engine is jitted XLA programs (``models/generate.py``) over a
SLOTTED KV cache: S independent sequences share one cache with per-slot
positions, and every decode dispatch advances ALL active slots at once — the
matmuls run at batch S instead of batch 1, which is the difference between
feeding the MXU and starving it.

Scheduling is iteration-level (the vLLM/Orca policy): each engine step

1. retires finished slots (max_new_tokens reached, or no room for another
   chunk before ``max_len`` — ``length_cap``) and immediately
2. admits queued prompts into the free slots, bounded by a prefill token
   budget per step (``serve_llm_prefill_tokens``) so a burst of long
   prompts can't starve in-flight decode, then
3. runs ONE batched decode chunk and distributes each slot's tokens to its
   request's queue.

There is no engine thread: the step loop is driven by whichever request
thread wins a non-blocking try-lock (``drive``), so an idle engine owns no
resources (leak-check clean) and a busy one is stepped exactly as fast as
its consumers read. Admission control sheds with :class:`~ray_tpu.serve.
errors.Saturated` once ``max_queue`` requests are already waiting.

Prompt bucketing is unchanged from the single-sequence engine: prompts pad
to a power-of-two bucket (one prefill compile per bucket, warmed at replica
start), first-token logits are read at the REAL last position, and decode
overwrites pad garbage before the causal mask could ever expose it.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import jax
import numpy as np

from ray_tpu.devtools import jitcheck
from ray_tpu.models.generate import (KVBlockManager, NoFreeBlocks,
                                     PagedGenerator, SlottedGenerator)
from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.serve.errors import Saturated
from ray_tpu.util import tracing
from ray_tpu.utils.logging import get_logger

logger = get_logger("serve.llm")


def _default_buckets(max_len: int) -> List[int]:
    buckets, b = [], 16
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


def _check_token_ids(prompt: np.ndarray, vocab: int, name: str) -> None:
    """Reject out-of-range token ids at admission. Under jit an out-of-range
    embedding gather fills with NaN, and with a SHARED paged pool that NaN
    outlives the offending request (it spills into the trash block and its
    sequence's cached blocks, poisoning masked reads of every later request
    on the pool) — so a bad id must never reach the device."""
    if int(prompt.min()) < 0 or int(prompt.max()) >= vocab:
        raise ValueError(
            f"engine {name}: prompt token ids must be in [0, {vocab})")


def _shed(name: str, depth: int, limit: int, what: str) -> Saturated:
    """Build the engine-queue-full :class:`Saturated` (and bump the shed
    counter): ``retry_after_s`` estimates the queue's drain time at one
    admitted-item service time per waiting request."""
    from ray_tpu.core.config import config as _get_config
    from ray_tpu.core.metrics_export import observe_shed

    observe_shed(name, "saturated")
    try:
        retry = depth * _get_config().serve_retry_after_item_s
    except Exception:  # noqa: BLE001 — hint is advisory, shed regardless
        retry = None
    return Saturated(
        f"engine {name}: {depth} requests {what} "
        f"(serve_admission_queue_limit={limit})",
        retry_after_s=retry)


class _Request:
    """One in-flight generation: its token queue, slot, and counters.

    ``decode_tokens``/``decode_seconds`` live HERE (not on the engine) so the
    per-request ``decode_tps`` the deployment streams is this request's own
    rate — the engine-level attributes these replaced were shared across
    concurrent streams and raced exactly like ``finish_reason`` once did.
    """

    __slots__ = (
        "prompt", "padded", "real_len", "bucket", "max_new", "temperature",
        "seed", "tokens", "cond", "slot", "emitted", "done", "cancelled",
        "error", "finish_reason", "decode_tokens", "decode_seconds",
        "submitted_at", "ttft_s", "trace_ctx", "queued_s", "prefill_s",
        "out_ids", "blocks", "hit_tokens", "preloaded",
    )

    def __init__(self, prompt, padded, real_len, bucket, max_new,
                 temperature, seed, cond):
        self.prompt = prompt
        self.padded = padded
        self.real_len = real_len
        self.bucket = bucket
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.tokens: collections.deque = collections.deque()
        self.cond = cond
        self.slot: Optional[int] = None
        self.emitted = 0
        self.done = False
        self.cancelled = False
        self.error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        self.decode_tokens = 0
        self.decode_seconds = 0.0
        self.submitted_at = time.perf_counter()
        self.ttft_s: Optional[float] = None
        # TTFT decomposition (metrics phase labels): submit→admission and
        # the prefill dispatch, stamped by the scheduler.
        self.queued_s = 0.0
        self.prefill_s = 0.0
        # Every delivered token id, in order — the paged engine registers
        # the finished prompt+output chain in the prefix cache at retire.
        self.out_ids: List[int] = []
        # Paged-engine state: pool blocks pinned for a QUEUED request that
        # already owns them (disaggregation handoff), prefix-cache hit size,
        # and — for handed-off requests — the prefill's last-token logits
        # row (None means prefill runs locally at admission).
        self.blocks: List[int] = []
        self.hit_tokens = 0
        self.preloaded: Optional[np.ndarray] = None
        # Captured at submit time on the request's own thread; engine spans
        # must use THIS explicit context (the step loop runs on whichever
        # thread won the driver election — its ambient context belongs to a
        # different request). None unless the trace sampled in.
        self.trace_ctx = (tracing.current_context()
                          if tracing.is_sampled() else None)

    def decode_tps(self) -> float:
        if self.decode_seconds == 0:
            return 0.0
        return self.decode_tokens / self.decode_seconds


class LLMEngine:
    """Continuous-batching engine: S cache slots, caller-driven stepping.

    The single-sequence surface (``stream``/``generate``/``warmup``/
    ``device_metrics``) is unchanged; concurrency comes from calling
    ``stream`` from many threads — their sequences SHARE the batched decode
    dispatches instead of queueing behind each other.
    """

    def __init__(self, params, config: TransformerConfig, *,
                 max_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 chunk: int = 8,
                 slots: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 name: str = "LLM"):
        from ray_tpu.core.config import config as _get_config

        knobs = _get_config()
        self.params = params
        self.config = config
        self.max_len = max_len or config.max_seq_len
        self.buckets = sorted(prompt_buckets or _default_buckets(self.max_len))
        self.chunk = chunk
        self.slots = int(slots if slots is not None else knobs.serve_llm_slots)
        self.max_queue = int(max_queue if max_queue is not None
                             else knobs.serve_admission_queue_limit)
        self.prefill_budget = int(knobs.serve_llm_prefill_tokens)
        self.name = name
        self._init_device()

        # Lock order: _step_lock (try-acquired, never under others) →
        # _state_lock (request/slot bookkeeping; also every req.cond) →
        # _agg_lock. Device dispatches happen holding only _step_lock.
        self._step_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._agg_lock = threading.Lock()

        self._waiting: collections.deque = collections.deque()
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        self._slot_len = [0] * self.slots  # host mirror of device lengths
        self._active = np.zeros(self.slots, bool)
        self._greedy = np.ones(self.slots, bool)
        self._temps = np.zeros(self.slots, np.float32)

        # Aggregate decode counters (get_metrics / decode_tokens_per_sec);
        # the per-request truth lives on each _Request.
        self.decode_tokens = 0
        self.decode_seconds = 0.0
        self.finish_reason = "stop"  # convenience; races under concurrency

        # Flipped by warmup(): from then on every scheduler step runs under
        # jitcheck.steady_state() — zero new XLA compiles, zero implicit
        # device->host reads (enforced when jitcheck is installed).
        self._steady = False

    # -- device-half hooks (the paged engine overrides these) -----------------
    # The scheduler above them — admission budget, slot bookkeeping, token
    # distribution, the streaming contract — is engine-agnostic; everything
    # cache-layout-specific funnels through this narrow seam.
    def _init_device(self) -> None:
        self._sg = SlottedGenerator(self.params, self.config,
                                    slots=self.slots, max_len=self.max_len)
        self._cache, self._last, self._keys = self._sg.init_state()

    def _reset_device_state(self) -> None:
        self._cache, self._last, self._keys = self._sg.init_state()

    def _admission_cost(self, req: _Request) -> int:
        """Prefill tokens this admission charges against the step budget
        (called under _state_lock)."""
        return req.bucket

    def _dispatch_prefill(self, req: _Request, slot: int) -> None:
        """Run the prompt's prefill into ``slot``. May raise
        :class:`NoFreeBlocks` (paged pool exhausted) — the scheduler requeues
        the request at the head and stops admitting this step."""
        pf = self._sg.prefill_fn(req.bucket)
        self._cache, self._last, self._keys = pf(
            self.params, self._cache, self._last, self._keys,
            req.padded, req.real_len, slot, req.seed)

    def _decode_operands_locked(self):
        """Extra decode operands snapshotted under _state_lock (the paged
        engine's block tables/lengths — mutated by cancel paths, so they
        must be captured atomically with the active mask)."""
        return None

    def _run_decode(self, active, greedy, temps, extra):
        df = self._sg.decode_fn(self.chunk)
        toks, self._cache, self._last, self._keys = df(
            self.params, self._cache, self._last, self._keys,
            active, greedy, temps)
        return toks

    def _slot_result(self, host_toks, slot: int):
        """The step's emitted tokens for ``slot`` plus its device-length
        advance. The base engine always emits exactly ``chunk`` tokens; the
        speculative paged engine emits a variable 1..chunk*(k+1) depending
        on per-step acceptance. Called under _state_lock."""
        return [int(t) for t in host_toks[slot][:self.chunk]], self.chunk

    def _chunk_span_attrs(self, slot: int) -> Optional[Dict]:
        """Extra attrs merged into a sampled request's ``llm.decode_chunk``
        span (the spec engine reports proposed/accepted counts)."""
        return None

    def _release_slot_device(self, slot: int) -> None:
        """Per-slot device-side cleanup when a slot frees (paged: unpin the
        slot's blocks). Called under _state_lock; must be idempotent."""

    def _on_retire_locked(self, req: _Request) -> None:
        """A request finished cleanly ("stop"/"length_cap") and still owns
        its slot (paged: publish its prefix into the reuse cache). Called
        under _state_lock just before the slot frees."""

    def _discard_request_locked(self, req: _Request) -> None:
        """A request is leaving the engine WITHOUT owning a slot (cancelled
        while queued, or poisoned by a device failure) — drop any resources
        it holds directly (paged: pre-attached handoff blocks)."""

    # -- public single-request surface (back-compat) -------------------------
    def warmup(self) -> None:
        """Compile prefill for every bucket + the decode chunk, then reset —
        TTFT never pays XLA compilation. One program per bucket and one per
        chunk size: greedy vs sampled is an operand, not a recompile."""
        with self._step_lock:
            for b in self.buckets:
                pf = self._sg.prefill_fn(b)
                self._cache, self._last, self._keys = pf(
                    self.params, self._cache, self._last, self._keys,
                    np.zeros((1, b), np.int32), b, 0, 0)
            df = self._sg.decode_fn(self.chunk)
            toks, self._cache, self._last, self._keys = df(
                self.params, self._cache, self._last, self._keys,
                np.zeros(self.slots, bool), self._greedy, self._temps)
            np.asarray(toks)
            self._cache, self._last, self._keys = self._sg.init_state()
            self._steady = True

    def _bucket_for(self, n: int) -> int:
        # One full decode chunk must fit after the prompt: decode always
        # advances in `chunk`-token dispatches, and a slot with no room for
        # one retires as length_cap before emitting anything.
        if n + self.chunk > self.max_len:
            raise ValueError(
                f"prompt of {n} tokens leaves no room for a {self.chunk}-token "
                f"decode chunk within max_len {self.max_len}")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds max_len {self.max_len}")

    def stream(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0,
               result: Optional[Dict] = None) -> Iterable[int]:
        """Yield generated token ids for ONE request, decoded in shared
        batched chunks with every other in-flight request.

        ``result``, if given, receives ``{"finish_reason", "decode_tps"}`` —
        per-request values; the engine-level ``finish_reason`` attribute is a
        single-stream convenience and races under concurrency.
        """
        if result is None:
            result = {}
        req = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, seed=seed)

        def run():
            try:
                for tok in self.drive(req):
                    result["decode_tps"] = req.decode_tps()
                    yield tok
            finally:
                result["finish_reason"] = self.finish_reason = (
                    req.finish_reason or "stop")
                if req.ttft_s is not None:
                    result["ttft_s"] = req.ttft_s

        gen = run()
        # The request is submitted EAGERLY (Saturated raises at call time),
        # but an abandoned generator that was never started skips drive()'s
        # cancel-in-finally — close() doesn't enter an unstarted body. The
        # finalizer unqueues it at collection; _cancel is a no-op once done.
        weakref.finalize(gen, self._cancel, req)
        return gen

    def generate(self, prompt_ids: Sequence[int], **kw) -> List[int]:
        return list(self.stream(prompt_ids, **kw))

    # -- request lifecycle ----------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0) -> _Request:
        """Validate + enqueue; raises :class:`Saturated` when ``max_queue``
        requests are already waiting for a slot (0 disables shedding)."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        real_len = int(prompt.shape[0])
        if real_len == 0:
            raise ValueError("empty prompt")
        _check_token_ids(prompt, self.config.vocab_size, self.name)
        bucket = self._bucket_for(real_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :real_len] = prompt
        req = _Request(prompt, padded, real_len, bucket, int(max_new_tokens),
                       float(temperature), int(seed),
                       threading.Condition(self._state_lock))
        if max_new_tokens <= 0:
            req.done = True
            req.finish_reason = "stop"
            return req
        with self._state_lock:
            if self.max_queue and len(self._waiting) >= self.max_queue:
                raise _shed(self.name, len(self._waiting), self.max_queue,
                            "already waiting")
            self._waiting.append(req)
        return req

    def drive(self, req: _Request) -> Iterable[int]:
        """Yield ``req``'s tokens, stepping the engine whenever this thread
        wins the step try-lock (otherwise another request's thread is the
        driver and this one just waits on its queue). Abandoning the
        generator cancels the request and frees its slot."""
        try:
            while True:
                with self._state_lock:
                    out = list(req.tokens)
                    req.tokens.clear()
                    done, err = req.done, req.error
                for tok in out:
                    yield tok
                if err is not None:
                    raise err
                if done:
                    return
                if self._step_lock.acquire(False):
                    try:
                        self._step()
                    finally:
                        self._step_lock.release()
                else:
                    with self._state_lock:
                        if not req.tokens and not req.done:
                            # Timed slice as a safety net only: the exiting
                            # driver hands off via _wake_inflight, and token
                            # arrival notifies directly.
                            # raylint: ignore[blocking-under-lock] — req.cond
                            # wraps _state_lock (Condition(self._state_lock)
                            # in submit), so wait() releases the held lock.
                            req.cond.wait(timeout=0.01)
        finally:
            self._cancel(req)
            # Driver handoff: this thread may have been the stepper — wake
            # every in-flight request so one of them re-elects immediately
            # instead of waiting out a poll slice.
            self._wake_inflight()

    def _wake_inflight(self) -> None:
        with self._state_lock:
            for r in self._slot_req:
                if r is not None:
                    r.cond.notify_all()
            for r in self._waiting:
                r.cond.notify_all()

    def _cancel(self, req: _Request) -> None:
        """No-op on a finished request; otherwise unqueue/mark-cancelled and
        free its slot for the next admission."""
        with self._state_lock:
            if req.done:
                return
            req.cancelled = True
            try:
                self._waiting.remove(req)
            except ValueError:
                pass
            if req.slot is not None:
                self._free_slot_locked(req.slot)
            else:
                self._discard_request_locked(req)
            req.done = True
            if req.finish_reason is None:
                req.finish_reason = "cancelled"
            req.cond.notify_all()

    def _free_slot_locked(self, slot: int) -> None:
        self._release_slot_device(slot)
        r = self._slot_req[slot]
        if r is not None:
            r.slot = None
        self._slot_req[slot] = None
        self._slot_len[slot] = 0
        self._active[slot] = False

    def _finish_locked(self, req: _Request, reason: str) -> None:
        req.finish_reason = reason
        req.done = True
        if req.slot is not None:
            self._on_retire_locked(req)
            self._free_slot_locked(req.slot)
        req.cond.notify_all()

    def _fail_inflight(self, err: BaseException) -> None:
        """A device-dispatch failure poisons every in-flight request: their
        cache state is gone. Reset to a fresh empty engine."""
        with self._state_lock:
            victims = list(self._waiting) + [r for r in self._slot_req
                                             if r is not None]
            for r in self._waiting:
                self._discard_request_locked(r)
            self._waiting.clear()
            for slot in range(self.slots):
                self._free_slot_locked(slot)
            for r in victims:
                r.error = err
                r.done = True
                if r.finish_reason is None:
                    r.finish_reason = "error"
                r.cond.notify_all()
        self._reset_device_state()

    # -- the iteration-level scheduler ----------------------------------------
    def _step(self) -> None:
        # Called holding _step_lock (the elected driver). Post-warmup the
        # step runs under the steady-state contract: any new XLA compile or
        # implicit device->host read is a violation (recorded when jitcheck
        # is installed; steady_state() is a no-op otherwise).
        try:
            if self._steady:
                with jitcheck.steady_state():
                    self._step_inner()
            else:
                self._step_inner()
        except BaseException as err:
            self._fail_inflight(err)
            raise
        self._post_step()

    def _post_step(self) -> None:
        """Post-iteration hook, still under _step_lock (the paged engine
        drains its KV-tier spill queue here — EVERY step runs it, including
        the one that retires the last request, so spill pins never strand
        on an idle engine)."""

    def _step_inner(self) -> None:
        # 1. Retire: a slot whose next chunk would cross max_len ends as
        #    length_cap BEFORE dispatch (no partial chunks — shapes stay
        #    static), and cancelled slots free immediately.
        with self._state_lock:
            for slot in range(self.slots):
                req = self._slot_req[slot]
                if req is None:
                    continue
                if req.cancelled:
                    self._free_slot_locked(slot)
                elif self._slot_len[slot] + self.chunk > self.max_len:
                    self._finish_locked(req, "length_cap")

        # 2. Admit queued prompts into free slots under the prefill budget.
        #    The FIRST admission always goes through — the budget bounds how
        #    much prefill work piles into one step, never progress.
        admitted_tokens = 0
        while True:
            with self._state_lock:
                free = next((s for s in range(self.slots)
                             if self._slot_req[s] is None), None)
                if free is None or not self._waiting:
                    break
                nxt = self._waiting[0]
                cost = self._admission_cost(nxt)
                if admitted_tokens and (
                        admitted_tokens + cost > self.prefill_budget):
                    break
                self._waiting.popleft()
                if nxt.cancelled:
                    continue
                nxt.slot = free
                self._slot_req[free] = nxt
                self._slot_len[free] = nxt.real_len
                self._active[free] = True
                self._greedy[free] = nxt.temperature <= 0
                self._temps[free] = nxt.temperature if nxt.temperature > 0 else 0.0
            t_admit = time.perf_counter()
            try:
                self._dispatch_prefill(nxt, free)
            except NoFreeBlocks:
                # Paged pool exhausted even after cache eviction: put the
                # request back at the head and stop admitting — in-flight
                # retires free blocks, and the first admission of a step is
                # exempt from the budget so progress is guaranteed once
                # blocks return.
                with self._state_lock:
                    self._free_slot_locked(free)
                    if not nxt.cancelled:
                        self._waiting.appendleft(nxt)
                break
            nxt.queued_s = t_admit - nxt.submitted_at
            nxt.prefill_s = time.perf_counter() - t_admit
            if nxt.trace_ctx is not None:
                tracing.emit(
                    "llm.admission_wait", nxt.trace_ctx,
                    duration=nxt.queued_s,
                    attrs={"slot": free, "engine": self.name})
                tracing.emit(
                    "llm.prefill", nxt.trace_ctx,
                    duration=nxt.prefill_s,
                    attrs={"slot": free, "bucket": nxt.bucket,
                           "prompt_len": nxt.real_len,
                           "hit_tokens": nxt.hit_tokens})
            admitted_tokens += cost

        with self._state_lock:
            if not any(r is not None for r in self._slot_req):
                return
            active = self._active.copy()
            greedy = self._greedy.copy()
            temps = self._temps.copy()
            extra = self._decode_operands_locked()

        # 3. One batched decode chunk advancing every active slot.
        t0 = time.perf_counter()
        toks = self._run_decode(active, greedy, temps, extra)
        host_toks = jax.device_get(toks)  # the step's single device sync
        dt = time.perf_counter() - t0
        now = time.perf_counter()

        # 4. Distribute each slot's tokens to its request.
        delivered_total = 0
        ttfts: List[tuple] = []  # (total, queued, prefill) per first token
        batch_size = int(active.sum())
        chunk_spans: List[tuple] = []  # sampled requests' (ctx, slot, ntok)
        with self._state_lock:
            for slot in range(self.slots):
                req = self._slot_req[slot]
                if req is None or not active[slot]:
                    continue
                emitted, adv = self._slot_result(host_toks, slot)
                self._slot_len[slot] += adv
                if req.cancelled:
                    self._free_slot_locked(slot)
                    continue
                upto = min(len(emitted), req.max_new - req.emitted)
                if upto > 0 and req.ttft_s is None:
                    req.ttft_s = now - req.submitted_at
                    ttfts.append((req.ttft_s, req.queued_s, req.prefill_s))
                if req.trace_ctx is not None and upto > 0:
                    chunk_spans.append((req.trace_ctx, slot, upto))
                new_toks = emitted[:upto]
                req.tokens.extend(new_toks)
                req.out_ids.extend(new_toks)
                req.emitted += upto
                req.decode_tokens += upto
                req.decode_seconds += dt
                delivered_total += upto
                if req.emitted >= req.max_new:
                    self._finish_locked(req, "stop")
                else:
                    req.cond.notify_all()
        with self._agg_lock:
            self.decode_tokens += delivered_total
            self.decode_seconds += dt
        # Emitted OUTSIDE _state_lock: span export may take its own locks.
        for ctx, slot, ntok in chunk_spans:
            attrs = {"slot": slot, "tokens": ntok, "batch": batch_size}
            extra_attrs = self._chunk_span_attrs(slot)
            if extra_attrs:
                attrs.update(extra_attrs)
                # Propose + verify run fused in the one spec dispatch, so
                # the spec span's duration IS the step's device time; the
                # attrs carry the per-slot proposed/accepted split.
                tracing.emit("llm.spec", ctx, duration=dt, end_time=None,
                             attrs={"slot": slot, **extra_attrs})
            tracing.emit("llm.decode_chunk", ctx, duration=dt, end_time=None,
                         attrs=attrs)
        self._observe(delivered_total, ttfts)

    def _observe(self, delivered: int, ttfts: List[tuple]) -> None:
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 serve_tokens_total,
                                                 serve_ttft_hist)

        if not metrics_enabled():
            return
        tags = {"deployment": self.name}
        if delivered:
            serve_tokens_total().inc(delivered, tags)
        hist = serve_ttft_hist()
        for total, queued, prefill in ttfts:
            # Phase split: queued (submit→admission), prefill (the prefill
            # dispatch), decode (the remainder — first chunk + distribution).
            hist.observe(total, {**tags, "phase": "total"})
            hist.observe(queued, {**tags, "phase": "queued"})
            hist.observe(prefill, {**tags, "phase": "prefill"})
            hist.observe(max(0.0, total - queued - prefill),
                         {**tags, "phase": "decode"})

    # -- introspection --------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """Slot occupancy + admission queue depth — exported through
        ``ReplicaActor.get_metrics`` for KV-occupancy-aware routing."""
        with self._state_lock:
            busy = sum(1 for r in self._slot_req if r is not None)
            depth = len(self._waiting)
        return {"slots_total": float(self.slots), "slots_busy": float(busy),
                "queue_depth": float(depth)}

    def decode_tokens_per_sec(self) -> float:
        with self._agg_lock:
            if self.decode_seconds == 0:
                return 0.0
            return self.decode_tokens / self.decode_seconds

    def device_metrics(self, *, prompt_len: int = 16, reps: int = 10) -> Dict:
        """Device-side TTFT and decode rate, excluding host↔device RTT.

        Runs on a throwaway slot state (serialized with serving via the step
        lock): TTFT is prefill + first decode chunk; the decode rate chains
        chunks with one final sync so async dispatch overlaps and the number
        reflects pure device time. One slot active — the per-sequence rate
        of the batched program.
        """
        import jax

        bucket = self._bucket_for(prompt_len)
        with self._step_lock:
            pf = self._sg.prefill_fn(bucket)
            df = self._sg.decode_fn(self.chunk)
            padded = np.zeros((1, bucket), np.int32)
            active = np.zeros(self.slots, bool)
            active[0] = True
            greedy = np.ones(self.slots, bool)
            temps = np.zeros(self.slots, np.float32)

            cache, last, keys = self._sg.init_state()
            # Warm both programs before timing.
            cache, last, keys = pf(self.params, cache, last, keys, padded,
                                   prompt_len, 0, 0)
            toks, cache, last, keys = df(self.params, cache, last, keys,
                                         active, greedy, temps)
            np.asarray(toks)

            outs = []
            t0 = time.perf_counter()
            for i in range(reps):
                cache, last, keys = pf(self.params, cache, last, keys,
                                       padded, prompt_len, 0, i)
                toks, cache, last, keys = df(self.params, cache, last, keys,
                                             active, greedy, temps)
                outs.append(toks)
            jax.block_until_ready(outs)
            ttft_ms = (time.perf_counter() - t0) / reps * 1e3

            n_chunks = (self.max_len - prompt_len) // self.chunk - 1
            if n_chunks < 1:
                return {"device_ttft_ms": round(ttft_ms, 2),
                        "device_decode_tokens_per_sec": 0.0}
            cache, last, keys = pf(self.params, cache, last, keys, padded,
                                   prompt_len, 0, 0)
            t0 = time.perf_counter()
            for _ in range(n_chunks):
                toks, cache, last, keys = df(self.params, cache, last, keys,
                                             active, greedy, temps)
            jax.block_until_ready(toks)
            dt = time.perf_counter() - t0
        return {
            "device_ttft_ms": round(ttft_ms, 2),
            "device_decode_tokens_per_sec": round(n_chunks * self.chunk / dt, 1),
        }


class PagedLLMEngine(LLMEngine):
    """Continuous-batching engine over a PAGED KV cache with prefix reuse.

    Same scheduler and streaming contract as :class:`LLMEngine`; the device
    half is a shared pool of ``serve_kv_block_tokens``-sized KV blocks
    (:class:`~ray_tpu.models.generate.PagedGenerator`) addressed through
    per-slot block tables, with a host-side :class:`~ray_tpu.models.generate.
    KVBlockManager` doing refcounts and hash-based prefix reuse:

    - admission looks the prompt up in the block-hash table and prefills
      ONLY the uncached suffix (``start_pos = hit_len``) — a shared system
      prompt or multi-turn history costs its prefill FLOPs once;
    - a hit on a retired sequence's partial tail block is copy-on-write:
      the block is duplicated into a private block before the divergent
      suffix writes into it, full-block hits share by refcount alone;
    - at retire the finished prompt+output chain is registered so the NEXT
      turn of the conversation hits it;
    - pool exhaustion (after LRU-evicting unpinned cached blocks) requeues
      the request rather than failing it.
    """

    def __init__(self, params, config: TransformerConfig, *,
                 block_tokens: Optional[int] = None,
                 pool_blocks: Optional[int] = None,
                 attention_kernel: Optional[str] = None,
                 draft_params=None,
                 draft_config: Optional[TransformerConfig] = None,
                 spec_tokens: Optional[int] = None, **kw):
        from ray_tpu.core.config import config as _get_config

        knobs = _get_config()
        self.block_tokens = int(block_tokens if block_tokens is not None
                                else knobs.serve_kv_block_tokens)
        self._pool_blocks_cfg = int(pool_blocks if pool_blocks is not None
                                    else knobs.serve_kv_pool_blocks)
        self.attention_kernel = str(
            attention_kernel if attention_kernel is not None
            else knobs.serve_paged_attention_kernel)
        self.spec_k = int(spec_tokens if spec_tokens is not None
                          else knobs.serve_spec_tokens)
        if self.spec_k > 0 and draft_params is None:
            raise ValueError(
                "serve_spec_tokens > 0 needs a draft model "
                "(draft_params/draft_config)")
        self._draft_params = draft_params
        self._draft_config = draft_config
        self._spec = self.spec_k > 0
        self._spec_floor = float(knobs.serve_spec_accept_floor)
        self._spec_alpha = float(knobs.serve_spec_accept_alpha)
        super().__init__(params, config, **kw)

    # -- device-half hooks ----------------------------------------------------
    def _init_device(self) -> None:
        self.blocks_per_seq = -(-self.max_len // self.block_tokens)
        # Auto pool size: 2x a full slot set plus the trash block — half the
        # pool can idle as reusable prefix cache under full load.
        num_blocks = self._pool_blocks_cfg or (
            2 * self.slots * self.blocks_per_seq + 1)
        self._pg = PagedGenerator(self.params, self.config, slots=self.slots,
                                  num_blocks=num_blocks,
                                  block_tokens=self.block_tokens,
                                  max_len=self.max_len,
                                  attention_kernel=self.attention_kernel,
                                  draft_params=self._draft_params,
                                  draft_config=self._draft_config)
        self.kv = KVBlockManager(num_blocks, self.block_tokens)
        (self._k_pool, self._v_pool,
         self._last, self._keys) = self._pg.init_state()
        self._slot_table = np.zeros((self.slots, self.blocks_per_seq),
                                    np.int32)
        self._slot_blocks: List[List[int]] = [[] for _ in range(self.slots)]
        self._hit_pending = 0  # hit tokens awaiting metric flush (step thread)
        self._init_tier_state()
        self._init_spec_state()

    def _init_tier_state(self) -> None:
        # Cluster KV tier (serve/kv_tier.py). All tier state is touched
        # under the locks noted inline; with the flag off every field stays
        # empty and every tier branch is dead — exact engine-private
        # behavior.
        from ray_tpu.serve.kv_tier import KVTier, kv_tier_enabled

        self._tier = KVTier(self.name) if kv_tier_enabled() else None
        from ray_tpu.core.config import config as _get_config

        try:
            knobs = _get_config()
            self._tier_min_spill = max(
                1, int(knobs.kv_tier_min_spill_blocks))
        except Exception:  # noqa: BLE001 — config unavailable mid-teardown
            self._tier_min_spill = 1
        # Retired chains pinned for spill: (chain, full_ids, n_full,
        # digests — the chain's full-block hash list).
        # Appended under _state_lock by the step thread's retire phase,
        # drained by _post_step — both inside the _step_lock scope.
        self._tier_spill_q: List[tuple] = []
        # head digest -> (chain tuple, n_real): the drain-migration export
        # set (active sessions' chains). Insertion-ordered LRU, bounded.
        self._tier_chains: "Dict[bytes, tuple]" = {}
        # Digests of chains that arrived via drain migration (ordered-set
        # dict, bounded) — attributes their local hits to source=migrated.
        self._tier_migrated: "Dict[bytes, None]" = {}
        self._tier_hits_pending = {"local": 0, "store": 0, "migrated": 0}
        self._tier_hits_total = {"local": 0, "store": 0, "migrated": 0}
        self._tier_spill_bytes_pending = 0
        self._tier_fetch_bytes_pending = 0

    _TIER_CHAIN_CAP = 512       # migration export set
    _TIER_MIGRATED_CAP = 4096   # migrated-digest attribution set

    def _tier_note_chain_locked(self, head: bytes, chain, n_real: int) -> None:
        # Under _state_lock. LRU re-insert, like the KV manager's cache.
        self._tier_chains.pop(head, None)
        self._tier_chains[head] = (tuple(int(t) for t in chain), int(n_real))
        while len(self._tier_chains) > self._TIER_CHAIN_CAP:
            self._tier_chains.pop(next(iter(self._tier_chains)))

    def _init_spec_state(self) -> None:
        # Speculative-decoding host state — all [S], step-thread-owned
        # except the per-slot resets at admission/release (under
        # _state_lock, which the step thread also holds there).
        if not self._spec:
            return
        self._kd_pool, self._vd_pool = self._pg.init_draft_state()
        self._spec_tail = np.zeros(self.slots, np.int32)
        self._spec_pending = np.zeros(self.slots, np.int32)
        self._spec_use_pending = np.zeros(self.slots, bool)
        self._spec_ewma = np.ones(self.slots, np.float32)
        self._spec_on = np.zeros(self.slots, bool)
        self._last_counts = None        # last spec step's [S, chunk] advances
        self._spec_last_accept = np.zeros(self.slots, np.int64)
        self._spec_last_on = np.zeros(self.slots, bool)
        self._spec_last_dt = 0.0
        self._spec_proposed_pending = 0  # await metric flush (step thread)
        self._spec_accepted_pending = 0
        self._spec_proposed_total = 0
        self._spec_accepted_total = 0

    def _reset_device_state(self) -> None:
        (self._k_pool, self._v_pool,
         self._last, self._keys) = self._pg.init_state()
        # Pool contents are gone — the prefix cache resets with it. Queued
        # spill entries and tracked chains point into the dead pool, so
        # they go too (their pins die with the replaced manager); chains
        # ALREADY published to the tier survive — those payloads are host
        # copies in the object plane, not pool references.
        self.kv = KVBlockManager(self.kv.num_blocks, self.block_tokens)
        self._slot_table[:] = 0
        self._slot_blocks = [[] for _ in range(self.slots)]
        self._tier_spill_q = []
        self._tier_chains = {}
        self._tier_migrated = {}
        self._init_spec_state()

    def warmup(self) -> None:
        with self._step_lock:
            zero_row = np.zeros(self.blocks_per_seq, np.int32)  # all trash
            for b in self.buckets:
                pf = self._pg.prefill_fn(b)
                (self._k_pool, self._v_pool, self._last, self._keys) = pf(
                    self.params, self._k_pool, self._v_pool, self._last,
                    self._keys, zero_row, np.zeros((1, b), np.int32),
                    0, b, 0, 0)
            df = self._pg.decode_fn(self.chunk)
            toks, self._k_pool, self._v_pool, self._last, self._keys = df(
                self.params, self._k_pool, self._v_pool, self._last,
                self._keys, np.zeros((self.slots, self.blocks_per_seq),
                                     np.int32),
                np.zeros(self.slots, np.int32), np.zeros(self.slots, bool),
                self._greedy, self._temps)
            np.asarray(toks)
            cf = self._pg.copy_fn()
            self._k_pool, self._v_pool = cf(self._k_pool, self._v_pool, 0, 0)
            # The handoff attach program (set_last) runs mid-step when a
            # prefilled request lands — compile it here, not on its TTFT.
            sl = self._pg.set_last_fn()
            self._last, self._keys = sl(
                self._last, self._keys,
                np.zeros(self._last.shape[1], np.float32), 0, 0)
            if self._tier is not None:
                # Tier upload/download programs: compile HERE so a cold
                # replica's first store fetch never pays XLA on its TTFT
                # (block 0 is the padding block — inserting zeros is inert).
                zb = np.zeros((self._k_pool.shape[0], 1)
                              + tuple(self._k_pool.shape[2:]),
                              self._k_pool.dtype)
                self._tier_insert_blocks(zb, zb, [0])
                self._tier_extract_blocks([0])
            if self._spec:
                for b in self.buckets:
                    dpf = self._pg.draft_prefill_fn(b)
                    self._kd_pool, self._vd_pool = dpf(
                        self._draft_params, self._kd_pool, self._vd_pool,
                        zero_row, np.zeros((1, b), np.int32), 0, b)
                self._kd_pool, self._vd_pool = cf(self._kd_pool,
                                                  self._vd_pool, 0, 0)
                sf = self._pg.spec_decode_fn(self.chunk, self.spec_k)
                out = sf(self.params, self._draft_params, self._k_pool,
                         self._v_pool, self._kd_pool, self._vd_pool,
                         self._last, self._keys,
                         np.zeros((self.slots, self.blocks_per_seq),
                                  np.int32),
                         np.zeros(self.slots, np.int32),
                         np.zeros(self.slots, bool), self._greedy,
                         self._temps, np.zeros(self.slots, bool),
                         np.zeros(self.slots, np.int32),
                         np.zeros(self.slots, np.int32),
                         np.zeros(self.slots, bool))
                np.asarray(out[0])
                (self._k_pool, self._v_pool, self._kd_pool, self._vd_pool,
                 self._last, self._keys) = out[3:9]
            self._reset_device_state()
            self._steady = True

    def _suffix_bucket(self, n: int) -> int:
        # The suffix prefill's compile bucket — unlike _bucket_for it needs
        # no decode-chunk headroom check (submit already validated the full
        # prompt against max_len).
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _admission_cost(self, req: _Request) -> int:
        if req.preloaded is not None:
            return 0  # prefill already paid on the prefill-side engine
        hit = self.kv.peek_hit_len([int(t) for t in req.prompt])
        return self._suffix_bucket(max(1, req.real_len - hit))

    def _dispatch_prefill(self, req: _Request, slot: int) -> None:
        bt = self.block_tokens
        if req.preloaded is not None:
            self._attach_preloaded(req, slot)
            return
        tokens = [int(t) for t in req.prompt]
        full, tail, hit_len = self.kv.lookup(tokens)
        digests: List[bytes] = []
        fetched = None          # (payload, from_block, to_block)
        if self._tier is not None:
            from ray_tpu.util import blockhash

            cap = len(tokens) - 1
            digests = blockhash.block_hashes(tokens, bt, max_blocks=cap // bt)
            fetched = self._tier_probe(digests, len(full), hit_len)
        try:
            # The table must cover every position this sequence can ever
            # write: the prompt plus whole decode chunks until max_new is
            # reached (decode always writes full chunks; the finishing
            # chunk's spill past max_new still lands in the pool).
            n_chunks = -(-req.max_new // self.chunk)
            max_written = min(self.max_len,
                              req.real_len + n_chunks * self.chunk)
            need = -(-max_written // bt)
            # Full-block hits are shared in place; a tail hit contributes
            # CONTENT only (its copy-on-write destination is a fresh block),
            # so allocation covers everything beyond the full hits.
            fresh = self.kv.alloc(need - len(full))
        except NoFreeBlocks:
            self.kv.release(full + ([tail] if tail is not None else []))
            raise
        ids = list(full)
        local_hit = hit_len
        if fetched is not None:
            # Cluster-tier hit past the local cache: upload the fetched
            # full blocks into fresh pool blocks at their chain positions
            # and prefill from there. The store chain supersedes a local
            # tail hit (full blocks reach further than any partial tail).
            payload, b_from, b_to = fetched
            if tail is not None:
                self.kv.release([tail])
                tail = None
            n_f = b_to - b_from
            fb, fresh = fresh[:n_f], fresh[n_f:]
            k_in = np.ascontiguousarray(payload["k"][:, b_from:b_to])
            v_in = np.ascontiguousarray(payload["v"][:, b_from:b_to])
            self._tier_insert_blocks(k_in, v_in, fb)
            ids.extend(fb)
            hit_len = b_to * bt
            self._tier_fetch_bytes_pending += k_in.nbytes + v_in.nbytes
        if tail is not None:
            dst = fresh.pop(0)
            cf = self._pg.copy_fn()
            self._k_pool, self._v_pool = cf(self._k_pool, self._v_pool,
                                            int(tail), int(dst))
            if self._spec:
                # The draft pool mirrors the block tables, so a COW fork
                # must duplicate the draft-side content of the tail too.
                self._kd_pool, self._vd_pool = cf(
                    self._kd_pool, self._vd_pool, int(tail), int(dst))
            self.kv.note_cow()
            self.kv.release([tail])  # pin the private copy, not the original
            ids.append(dst)
        ids.extend(fresh)
        row = np.zeros(self.blocks_per_seq, np.int32)
        row[:len(ids)] = ids
        req.hit_tokens = hit_len
        req.bucket = self._suffix_bucket(req.real_len - hit_len)

        suffix_len = req.real_len - hit_len
        padded = np.zeros((1, req.bucket), np.int32)
        padded[0, :suffix_len] = req.prompt[hit_len:]
        pf = self._pg.prefill_fn(req.bucket)
        (self._k_pool, self._v_pool, self._last, self._keys) = pf(
            self.params, self._k_pool, self._v_pool, self._last, self._keys,
            row, padded, hit_len, suffix_len, slot, req.seed)
        if self._spec:
            # Warm the draft pool over the same suffix/table so the draft
            # chain starts from draft-KV covering every committed position.
            dpf = self._pg.draft_prefill_fn(req.bucket)
            self._kd_pool, self._vd_pool = dpf(
                self._draft_params, self._kd_pool, self._vd_pool, row,
                padded, hit_len, suffix_len)
        # Commit ATOMICALLY with the cancel path: this runs outside
        # _state_lock, so a concurrent _cancel may have freed the slot
        # mid-dispatch. Attaching first and registering later would let
        # _release_slot_device free blocks the prefix table still points
        # at; attaching after a lost cancel would leak the pins forever.
        # Publishing the prompt's FULL blocks here (their content is final —
        # decode writes only at positions >= real_len) lets a concurrent
        # request with the same prefix hit while this one still decodes.
        n_full_prompt = (req.real_len // bt) * bt
        with self._state_lock:
            if self._slot_req[slot] is not req or req.cancelled:
                self.kv.release(ids)  # slot lost mid-dispatch — drop the pins
                return
            self._slot_table[slot, :] = row
            self._slot_blocks[slot] = ids
            if n_full_prompt:
                self.kv.register_chain(tokens, ids, n_full_prompt)
            self._hit_pending += hit_len
            if self._tier is not None:
                # Hit attribution by source: tokens past the local hit came
                # from the store; local full-block hits on a chain a drain
                # migration shipped in count as migrated.
                store_part = hit_len - local_hit if fetched is not None else 0
                local_part = hit_len - store_part
                src = "local"
                if local_part and any(d in self._tier_migrated
                                      for d in digests[:len(full)]):
                    src = "migrated"
                self._tier_hits_pending[src] += local_part
                self._tier_hits_total[src] += local_part
                self._tier_hits_pending["store"] += store_part
                self._tier_hits_total["store"] += store_part
                if n_full_prompt and digests:
                    nf = min(len(digests), n_full_prompt // bt)
                    self._tier_note_chain_locked(
                        digests[nf - 1], tokens[:nf * bt], nf * bt)
            if self._spec and fetched is not None:
                # Store-fetched blocks carry no draft-side KV (like a
                # disaggregation handoff) — speculation stays off for this
                # request rather than proposing from garbage draft state.
                self._spec_on[slot] = False
                self._spec_ewma[slot] = 0.0
                self._spec_use_pending[slot] = False
            elif self._spec:
                # Fresh speculation state: the draft chain's first forward
                # re-consumes the last prompt token at real_len - 1, so the
                # tail starts as exactly that token. EWMA starts optimistic;
                # the per-step headroom gate and acceptance feedback take it
                # from there.
                self._spec_tail[slot] = tokens[-1]
                self._spec_pending[slot] = 0
                self._spec_use_pending[slot] = False
                self._spec_ewma[slot] = 1.0
                self._spec_on[slot] = True

    def _tier_probe(self, digests: List[bytes], n_local_full: int,
                    hit_len: int):
        """Probe the cluster directory for a chain longer than the local
        hit; returns ``(payload, from_block, to_block)`` or None. Runs on
        the step thread outside _state_lock (the fetch is an object-store
        pull)."""
        if len(digests) <= n_local_full:
            return None      # local cache already covers every full block
        m = self._tier.match(digests)
        if m is None:
            return None
        j, entry = m
        if (j + 1) * self.block_tokens <= hit_len:
            return None      # the local hit reaches at least as far
        payload = self._tier.fetch(digests[j], entry)
        if not isinstance(payload, dict):
            return None
        k = payload.get("k")
        if k is None or k.shape[1] < j + 1:
            return None
        return payload, n_local_full, j + 1

    def _post_step(self) -> None:
        # Drain the spill queue (chains pinned at retire) under _step_lock:
        # extract the full blocks off-device and publish them to the
        # cluster tier, then unpin. Best-effort — a tier failure must never
        # poison serving (the chain stays locally cached either way).
        if self._tier is None or not self._tier_spill_q:
            return
        q, self._tier_spill_q = self._tier_spill_q, []
        for chain, ids, n_full, digests in q:
            try:
                if not self._tier.is_published(digests[-1]):
                    k, v = self._tier_extract_blocks(ids)
                    payload = {"k": k, "v": v,
                               "tokens": list(chain[:n_full
                                                    * self.block_tokens])}
                    self._tier.publish_chain(digests, payload,
                                             n_full * self.block_tokens,
                                             n_full)
                    self._tier_spill_bytes_pending += (
                        payload["k"].nbytes + payload["v"].nbytes)
            except Exception:  # noqa: BLE001 — spill is best-effort
                logger.exception("kv tier spill failed on %s", self.name)
            finally:
                self.kv.release(ids)

    def _attach_preloaded(self, req: _Request, slot: int) -> None:
        """Disaggregation handoff: the prompt's K/V blocks were already
        uploaded into the pool by ``admit_prefilled`` — attach the table row
        and seed the slot's logits/PRNG rows from the handed-off state."""
        ids = list(req.blocks)
        row = np.zeros(self.blocks_per_seq, np.int32)
        row[:len(ids)] = ids
        sl = self._pg.set_last_fn()
        self._last, self._keys = sl(self._last, self._keys,
                                    np.asarray(req.preloaded, np.float32),
                                    slot, req.seed)
        # Same atomic commit as _dispatch_prefill: a cancel that freed the
        # slot mid-attach found _slot_blocks[slot] empty (and, with req.slot
        # set, never took the _discard_request_locked path), so the handoff
        # pins are ours to drop here.
        with self._state_lock:
            req.blocks = []
            if self._slot_req[slot] is not req or req.cancelled:
                self.kv.release(ids)
                return
            self._slot_table[slot, :] = row
            self._slot_blocks[slot] = ids
            self._hit_pending += req.hit_tokens
            if self._spec:
                # Handed-off blocks carry no draft-side KV — the draft
                # never saw this prompt. Speculation stays off for the
                # request; the slot decodes one token per scan step.
                self._spec_on[slot] = False
                self._spec_ewma[slot] = 0.0
                self._spec_use_pending[slot] = False

    def _decode_operands_locked(self):
        base = (self._slot_table.copy(),
                np.asarray(self._slot_len, np.int32))
        if not self._spec:
            return base
        tables, lengths = base
        # Headroom gate: a spec step can write chunk*(k+1) positions ahead,
        # so slots without that much table room degrade to one token per
        # step INSIDE the same program — the base retire rule
        # (slot_len + chunk > max_len → length_cap before dispatch) stays
        # valid either way.
        cap = self.blocks_per_seq * self.block_tokens
        headroom = lengths + self.chunk * (self.spec_k + 1) <= cap
        spec_on = self._spec_on & headroom & self._active
        return base + (spec_on, self._spec_tail.copy(),
                       self._spec_pending.copy(),
                       self._spec_use_pending.copy())

    def _run_decode(self, active, greedy, temps, extra):
        if not self._spec:
            tables, lengths = extra
            df = self._pg.decode_fn(self.chunk)
            (toks, self._k_pool, self._v_pool,
             self._last, self._keys) = df(
                self.params, self._k_pool, self._v_pool, self._last,
                self._keys, tables, lengths, active, greedy, temps)
            return toks
        tables, lengths, spec_on, tail, pending, use_pending = extra
        if not spec_on.any() and not (use_pending & active).any():
            # Every slot degraded (low acceptance / no headroom / handoff)
            # and none still carries a rejection replacement: the plain
            # one-token program is strictly cheaper than a spec step that
            # would force-reject everything. (A just-demoted slot runs one
            # more spec step, which consumes its pending token and clears
            # the carry.)
            df = self._pg.decode_fn(self.chunk)
            (toks, self._k_pool, self._v_pool,
             self._last, self._keys) = df(
                self.params, self._k_pool, self._v_pool, self._last,
                self._keys, tables, lengths, active, greedy, temps)
            self._last_counts = None
            self._spec_last_accept[:] = 0
            self._spec_last_on[:] = False
            return toks
        sf = self._pg.spec_decode_fn(self.chunk, self.spec_k)
        t0 = time.perf_counter()
        (toks, counts, accepted, self._k_pool, self._v_pool, self._kd_pool,
         self._vd_pool, self._last, self._keys, tail_j, pending_j,
         up_j) = sf(
            self.params, self._draft_params, self._k_pool, self._v_pool,
            self._kd_pool, self._vd_pool, self._last, self._keys, tables,
            lengths, active, greedy, temps, spec_on, tail, pending,
            use_pending)
        # One batched fetch syncs the step: counts/accepted plus the spec
        # chain state carried back to host. Safe wholesale: only the step
        # thread writes these between operand snapshot and here, and
        # per-slot admission resets happen before the NEXT step's snapshot.
        (counts_np, accepted_np, tail_np, pending_np, up_np) = \
            jax.device_get((counts, accepted, tail_j, pending_j, up_j))
        self._spec_last_dt = time.perf_counter() - t0
        self._last_counts = counts_np
        # device_get views are read-only; the chain state is mutated
        # in place by slot admission/free, so take writable copies.
        self._spec_tail = np.array(tail_np)
        self._spec_pending = np.array(pending_np)
        self._spec_use_pending = np.array(up_np)
        # Acceptance EWMA feeds next step's gate: slots whose EWMA sinks
        # below the floor stop proposing for the rest of the request (their
        # draft passes would cost more than the accepted tokens buy).
        acc = accepted_np.sum(axis=1)
        self._spec_last_accept = acc
        self._spec_last_on = spec_on
        prop = np.where(spec_on, self.chunk * self.spec_k, 0)
        live = prop > 0
        if live.any():
            rate = np.zeros(self.slots, np.float32)
            rate[live] = acc[live] / prop[live]
            a = self._spec_alpha
            self._spec_ewma[live] = ((1.0 - a) * self._spec_ewma[live]
                                     + a * rate[live])
            self._spec_on[live] = self._spec_ewma[live] >= self._spec_floor
        self._spec_proposed_pending += int(prop.sum())
        self._spec_accepted_pending += int(acc.sum())
        self._spec_proposed_total += int(prop.sum())
        self._spec_accepted_total += int(acc.sum())
        return toks

    def _slot_result(self, host_toks, slot: int):
        if not self._spec or self._last_counts is None:
            return super()._slot_result(host_toks, slot)
        counts = self._last_counts[slot]          # [chunk] advances
        toks = host_toks[slot]                    # [chunk, k+1]
        out: List[int] = []
        for t in range(counts.shape[0]):
            out.extend(int(x) for x in toks[t, :counts[t]])
        return out, int(counts.sum())

    def _chunk_span_attrs(self, slot: int) -> Optional[Dict]:
        if (not self._spec or self._last_counts is None
                or not self._spec_last_on[slot]):
            return None
        return {"spec_proposed": self.chunk * self.spec_k,
                "spec_accepted": int(self._spec_last_accept[slot])}

    def _release_slot_device(self, slot: int) -> None:
        ids = self._slot_blocks[slot]
        if ids:
            self._slot_blocks[slot] = []
            self._slot_table[slot, :] = 0
            self.kv.release(ids)
        if self._spec:
            self._spec_on[slot] = False
            self._spec_use_pending[slot] = False

    def _on_retire_locked(self, req: _Request) -> None:
        ids = self._slot_blocks[req.slot] if req.slot is not None else []
        if not ids:
            return
        # Register the finished prompt+output chain (including a partial
        # tail entry) — the conversation's next turn extends exactly this
        # token sequence. Tokens past `emitted` (final-chunk spill) were
        # written to the pool but are NOT part of the chain, and
        # register_chain only publishes blocks fully covered by n_real.
        chain = [int(t) for t in req.prompt] + req.out_ids[:req.emitted]
        n_real = min(len(chain), len(ids) * self.block_tokens)
        self.kv.register_chain(chain, ids, n_real)
        if self._tier is None:
            return
        # Refcounted publish from the retire path: pin the chain's FULL
        # blocks (their content is final) and queue them for the spill
        # drain in _post_step — LRU eviction can't beat the extract to
        # them, and the pins drop the moment the payload is off-device.
        from ray_tpu.util import blockhash

        bt = self.block_tokens
        n_full = n_real // bt
        if n_full < self._tier_min_spill:
            return
        digests = blockhash.block_hashes(chain, bt, max_blocks=n_full)
        head = digests[-1]
        self._tier_note_chain_locked(head, chain[:n_real], n_real)
        if not self._tier.is_published(head):
            full_ids = list(ids[:n_full])
            self.kv.pin(full_ids)
            self._tier_spill_q.append(
                (list(chain), full_ids, n_full, digests))

    def _discard_request_locked(self, req: _Request) -> None:
        ids, req.blocks = req.blocks, []
        if ids:
            self.kv.release(ids)

    # -- disaggregation halves ------------------------------------------------
    def prefill_to_blocks(self, prompt_ids: Sequence[int], *, seed: int = 0):
        """Prefill-side half of disaggregated serving: run (suffix-)prefill
        for ``prompt_ids`` into pool blocks and return host copies for the
        handoff lane — ``(k [L,nb,bt,H,Dh], v, last_row [V], hit_tokens)``.

        The chain (full blocks AND partial tail — nothing will extend these
        blocks here) is registered in the LOCAL prefix cache before the pins
        drop, so a same-prefix prompt later only prefills its suffix even
        on the prefill side. Uses slot 0 under the step lock; a prefill
        engine serves no decode traffic, so the slot is exclusive.
        """
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        real_len = int(prompt.shape[0])
        if real_len == 0:
            raise ValueError("empty prompt")
        bt = self.block_tokens
        tokens = [int(t) for t in prompt]
        with self._step_lock:
            full, tail, hit_len = self.kv.lookup(tokens)
            try:
                need = -(-real_len // bt)
                fresh = self.kv.alloc(need - len(full))
            except NoFreeBlocks:
                self.kv.release(full + ([tail] if tail is not None else []))
                raise
            ids = list(full)
            if tail is not None:
                dst = fresh.pop(0)
                cf = self._pg.copy_fn()
                self._k_pool, self._v_pool = cf(self._k_pool, self._v_pool,
                                                int(tail), int(dst))
                self.kv.note_cow()
                self.kv.release([tail])
                ids.append(dst)
            ids.extend(fresh)
            row = np.zeros(self.blocks_per_seq, np.int32)
            row[:len(ids)] = ids
            suffix_len = real_len - hit_len
            bucket = self._suffix_bucket(suffix_len)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :suffix_len] = prompt[hit_len:]
            pf = self._pg.prefill_fn(bucket)
            (self._k_pool, self._v_pool, self._last, self._keys) = pf(
                self.params, self._k_pool, self._v_pool, self._last,
                self._keys, row, padded, hit_len, suffix_len, 0, seed)
            ef = self._pg.extract_fn(len(ids))
            k, v = ef(self._k_pool, self._v_pool, np.asarray(ids, np.int32))
            k = np.asarray(k)
            v = np.asarray(v)
            last_row = np.asarray(self._last[0])
            self.kv.register_chain(tokens, ids, real_len)
            self.kv.release(ids)
        return k, v, last_row, hit_len

    def admit_prefilled(self, prompt_ids: Sequence[int],
                        k: np.ndarray, v: np.ndarray, last_row: np.ndarray,
                        *, max_new_tokens: int = 32, temperature: float = 0.0,
                        seed: int = 0, hit_tokens: int = 0,
                        submitted_at: Optional[float] = None,
                        trace_ctx=None, timeout_s: float = 30.0) -> _Request:
        """Decode-side half of disaggregated serving: upload handed-off KV
        blocks into the pool and enqueue a decode-only request (admission
        attaches the table row instead of prefilling). Blocks — briefly —
        until the pool can supply the sequence's block budget.

        The upload is synchronous (``block_until_ready``): on return the
        caller may release the shm views ``k``/``v`` point into.
        """
        import jax

        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        real_len = int(prompt.shape[0])
        if real_len == 0:
            raise ValueError("empty prompt")
        bucket = self._bucket_for(real_len)  # validates decode headroom
        req = _Request(prompt, None, real_len, bucket, int(max_new_tokens),
                       float(temperature), int(seed),
                       threading.Condition(self._state_lock))
        req.trace_ctx = trace_ctx
        if submitted_at is not None:
            req.submitted_at = submitted_at
        if max_new_tokens <= 0:
            req.done = True
            req.finish_reason = "stop"
            return req
        nb_in = int(k.shape[1])
        n_chunks = -(-req.max_new // self.chunk)
        max_written = min(self.max_len, real_len + n_chunks * self.chunk)
        need = max(-(-max_written // self.block_tokens), nb_in)
        deadline = time.monotonic() + timeout_s
        while True:
            try:
                ids = self.kv.alloc(need)
                break
            except NoFreeBlocks:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.002)  # in-flight retires free blocks
        with self._step_lock:
            inf = self._pg.insert_fn(nb_in)
            self._k_pool, self._v_pool = inf(
                self._k_pool, self._v_pool, np.asarray(k), np.asarray(v),
                np.asarray(ids[:nb_in], np.int32))
            jax.block_until_ready(self._k_pool)
        # Publish the prompt's full blocks for LOCAL hits too — a colocated
        # follow-up (or affinity-routed repeat) skips the handoff entirely.
        tokens = [int(t) for t in prompt]
        n_full = (real_len // self.block_tokens) * self.block_tokens
        if n_full:
            self.kv.register_chain(tokens, ids, n_full)
        req.blocks = ids
        req.preloaded = np.asarray(last_row, np.float32)
        req.hit_tokens = int(hit_tokens)
        with self._state_lock:
            self._waiting.append(req)
        return req

    # -- drain migration (cluster KV tier) ------------------------------------
    def kv_export_chains(self) -> List[tuple]:
        """Snapshot the drain-migration export set — ``(tokens, n_real,
        head_digest)`` per tracked chain, least-recently-used first. Tracked
        chains are the active sessions' registered prefixes (noted at
        admission commit and at retire); shipping them to a survivor is what
        makes downscale lossless for warm multi-turn state."""
        with self._state_lock:
            return [(list(chain), n_real, head)
                    for head, (chain, n_real) in self._tier_chains.items()]

    def _tier_insert_blocks(self, k_in, v_in, ids) -> None:
        """Upload fetched/migrated blocks ONE AT A TIME: ``insert_fn(1)``
        is the only insert program (compiled at warmup) — a per-chain-
        length variant would pay XLA compilation on every novel chain
        length, right on the cold-fetch TTFT path."""
        inf = self._pg.insert_fn(1)
        for i, b in enumerate(ids):
            self._k_pool, self._v_pool = inf(
                self._k_pool, self._v_pool,
                np.ascontiguousarray(k_in[:, i:i + 1]),
                np.ascontiguousarray(v_in[:, i:i + 1]),
                np.asarray([b], np.int32))

    def _tier_extract_blocks(self, ids):
        """Gather blocks one at a time (same one-program rationale as
        ``_tier_insert_blocks``; spill/migration extraction runs off the
        decode hot path, so the extra dispatches cost little)."""
        ef = self._pg.extract_fn(1)
        ks, vs = [], []
        for b in ids:
            k, v = ef(self._k_pool, self._v_pool, np.asarray([b], np.int32))
            ks.append(np.asarray(k))
            vs.append(np.asarray(v))
        return np.concatenate(ks, axis=1), np.concatenate(vs, axis=1)

    def kv_export_chain_payload(self, tokens: Sequence[int],
                                n_real: int) -> Optional[dict]:
        """Extract one tracked chain off device for the migration lane —
        ``{"k", "v", "tokens", "n_real"}`` covering as much of the chain as
        the prefix cache still holds (full blocks AND the exact partial
        tail). None when the chain was evicted since being tracked."""
        tokens = [int(t) for t in tokens]
        with self._step_lock:
            ids, covered = self.kv.pin_chain(tokens, int(n_real))
            if not ids:
                return None
            try:
                k, v = self._tier_extract_blocks(ids)
                return {"k": k, "v": v,
                        "tokens": tokens[:covered], "n_real": covered}
            finally:
                self.kv.release(ids)

    def kv_import_chain(self, payload: dict) -> int:
        """Survivor half of drain migration: upload a handed-off chain into
        the pool and register it as CACHED prefix state, so the migrated
        session's next turn hits it exactly like a local retire would.
        Returns the number of tokens now warm (0 if the pool stayed full)."""
        import jax

        tokens = [int(t) for t in payload["tokens"]]
        n_real = int(payload.get("n_real", len(tokens)))
        k = np.asarray(payload["k"])
        v = np.asarray(payload["v"])
        nb = int(k.shape[1])
        if nb == 0 or n_real == 0:
            return 0
        deadline = time.monotonic() + 2.0
        while True:
            try:
                ids = self.kv.alloc(nb)
                break
            except NoFreeBlocks:
                if time.monotonic() > deadline:
                    return 0  # pool saturated — the store tier still covers it
                time.sleep(0.002)  # in-flight retires free blocks
        with self._step_lock:
            self._tier_insert_blocks(k, v, ids)
            jax.block_until_ready(self._k_pool)
        self.kv.register_chain(tokens, ids, n_real)
        self.kv.release(ids)  # ACTIVE -> CACHED: pure prefix-cache state
        from ray_tpu.util import blockhash

        digests = blockhash.block_hashes(tokens, self.block_tokens,
                                         max_blocks=n_real // self.block_tokens)
        with self._state_lock:
            for d in digests:
                self._tier_migrated.pop(d, None)
                self._tier_migrated[d] = None
            while len(self._tier_migrated) > self._TIER_MIGRATED_CAP:
                self._tier_migrated.pop(next(iter(self._tier_migrated)))
            if digests:
                self._tier_note_chain_locked(digests[-1], tokens[:n_real],
                                             n_real)
        return n_real

    def _tier_lane_params(self) -> tuple:
        """(capacity, slots) for a drain-migration lane. Both endpoints
        derive these from the same model config — the shm mapping is sized
        from them, so creator and attacher MUST agree."""
        c = self.config
        bt = self.block_tokens
        itm = np.dtype(c.dtype).itemsize
        block_bytes = c.n_layers * bt * c.n_heads * c.head_dim * itm
        # A chain spans at most one sequence's block budget; size the lane
        # like the disaggregation lane (K+V of a full table row + meta).
        return 2 * self.blocks_per_seq * block_bytes + 65536, 4

    def kv_migrate_out(self, lane_name: str) -> int:
        """Victim half of drain-then-retire: attach to the survivor's named
        handoff lane, ship every tracked chain, send the close pill. Returns
        chains sent; 0 (never raises) when the survivor's lane never appears
        or the drain deadline lapses — the store tier is the fallback."""
        from ray_tpu.core.config import config as _get_config
        from ray_tpu.serve.dag_pipeline import KVHandoffLane
        from ray_tpu.util import flightrec

        try:
            timeout = float(_get_config().kv_tier_drain_timeout_s)
        except Exception:  # noqa: BLE001 — config unavailable mid-teardown
            timeout = 10.0
        deadline = time.monotonic() + timeout
        cap, slots = self._tier_lane_params()
        lane = KVHandoffLane.attach(lane_name, timeout=timeout,
                                    capacity=cap, slots=slots)
        if lane is None:
            return 0  # survivor never opened the lane
        sent = 0
        try:
            for tokens, n_real, _head in self.kv_export_chains():
                if time.monotonic() > deadline:
                    break
                payload = self.kv_export_chain_payload(tokens, n_real)
                if payload is None:
                    continue  # evicted since tracking — store tier covers it
                meta = {"tokens": payload["tokens"],
                        "n_real": payload["n_real"]}
                try:
                    lane.send(meta, payload["k"], payload["v"],
                              timeout=max(0.1, deadline - time.monotonic()))
                except ValueError:
                    continue  # larger than the lane — store tier covers it
                sent += 1
            lane.close()  # pill: tells the survivor the drain is complete
        finally:
            lane.detach()
        flightrec.record("serve", self.name, f"kv migrate out {sent}")
        return sent

    def kv_migrate_in(self, lane_name: str) -> int:
        """Survivor half: CREATE the named handoff lane (the victim retry-
        attaches), import chains until the victim's close pill or the drain
        deadline, registering each as warm prefix state and recording its
        digests for migrated-hit attribution. Returns chains imported."""
        from ray_tpu.core.config import config as _get_config
        from ray_tpu.dag.channel import ChannelClosed
        from ray_tpu.serve.dag_pipeline import KVHandoffLane
        from ray_tpu.util import flightrec

        try:
            timeout = float(_get_config().kv_tier_drain_timeout_s)
        except Exception:  # noqa: BLE001 — config unavailable mid-teardown
            timeout = 10.0
        cap, slots = self._tier_lane_params()
        lane = KVHandoffLane(name=lane_name, capacity=cap, slots=slots)
        got = 0
        deadline = time.monotonic() + timeout
        try:
            while True:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    meta, k, v, tok = lane.recv(timeout=left)
                except (ChannelClosed, TimeoutError):
                    break
                try:
                    if self.kv_import_chain(
                            {"k": k, "v": v, "tokens": meta["tokens"],
                             "n_real": meta["n_real"]}):
                        got += 1
                finally:
                    lane.ack(tok)  # upload landed — slot back to the victim
        finally:
            lane.destroy()
        flightrec.record("serve", self.name, f"kv migrate in {got}")
        return got

    # -- introspection --------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        out = super().stats()
        out.update(self.kv.stats())
        if self._tier is not None:
            out["kv_tier_spilled_blocks"] = float(self._tier.spilled_blocks())
            with self._state_lock:
                for src, n in self._tier_hits_total.items():
                    out[f"kv_tier_hits_{src}"] = float(n)
        if self._spec:
            prop = self._spec_proposed_total
            acc = self._spec_accepted_total
            out["spec_proposed_total"] = float(prop)
            out["spec_accepted_total"] = float(acc)
            out["spec_accept_ratio"] = float(acc) / prop if prop else 0.0
        return out

    def _observe(self, delivered: int, ttfts: List[tuple]) -> None:
        super()._observe(delivered, ttfts)
        hits, self._hit_pending = self._hit_pending, 0
        if self._tier is not None:
            with self._state_lock:
                tier_hits = dict(self._tier_hits_pending)
                for src in self._tier_hits_pending:
                    self._tier_hits_pending[src] = 0
            spill_b, self._tier_spill_bytes_pending = \
                self._tier_spill_bytes_pending, 0
            fetch_b, self._tier_fetch_bytes_pending = \
                self._tier_fetch_bytes_pending, 0
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 serve_kv_block_occupancy,
                                                 serve_kv_hit_tokens_total,
                                                 serve_kv_spilled_blocks,
                                                 serve_kv_tier_fetch_bytes_total,
                                                 serve_kv_tier_hits_total,
                                                 serve_kv_tier_spill_bytes_total,
                                                 serve_spec_accept_ratio,
                                                 serve_spec_accepted_total,
                                                 serve_spec_proposed_total,
                                                 serve_ttft_hist)

        if not metrics_enabled():
            if self._spec:
                self._spec_proposed_pending = 0
                self._spec_accepted_pending = 0
            return
        tags = {"deployment": self.name}
        if hits:
            serve_kv_hit_tokens_total().inc(hits, tags)
        st = self.kv.stats()
        gauge = serve_kv_block_occupancy()
        for state in ("active", "cached", "free"):
            gauge.set(st[f"kv_blocks_{state}"], {**tags, "state": state})
        if self._tier is not None:
            ctr = serve_kv_tier_hits_total()
            for src, n in tier_hits.items():
                if n:
                    ctr.inc(n, {**tags, "source": src})
            if spill_b:
                serve_kv_tier_spill_bytes_total().inc(spill_b, tags)
            if fetch_b:
                serve_kv_tier_fetch_bytes_total().inc(fetch_b, tags)
            serve_kv_spilled_blocks().set(self._tier.spilled_blocks(), tags)
        if self._spec:
            prop, self._spec_proposed_pending = self._spec_proposed_pending, 0
            acc, self._spec_accepted_pending = self._spec_accepted_pending, 0
            if prop:
                serve_spec_proposed_total().inc(prop, tags)
            if acc:
                serve_spec_accepted_total().inc(acc, tags)
            tot_prop = self._spec_proposed_total
            if tot_prop:
                serve_spec_accept_ratio().set(
                    self._spec_accepted_total / tot_prop, tags)
            # The spec dispatch IS the first decode chunk for a first
            # token delivered this step — surface its propose+verify time
            # as its own TTFT phase next to queued/prefill/decode.
            if ttfts and self._last_counts is not None:
                hist = serve_ttft_hist()
                for _ in ttfts:
                    hist.observe(self._spec_last_dt,
                                 {**tags, "phase": "spec"})

    def close(self) -> None:
        """Release this engine's KV-tier publishes — directory refs and
        object pins drain to zero (the leak-check invariant). Idempotent;
        the engine owns no threads to stop."""
        if self._tier is not None:
            self._tier.close()

    def device_metrics(self, *, prompt_len: int = 16, reps: int = 10) -> Dict:
        import jax

        bucket = self._suffix_bucket(prompt_len)
        bps = self.blocks_per_seq
        with self._step_lock:
            pf = self._pg.prefill_fn(bucket)
            df = self._pg.decode_fn(self.chunk)
            padded = np.zeros((1, bucket), np.int32)
            row = np.arange(1, bps + 1, dtype=np.int32)
            tables = np.zeros((self.slots, bps), np.int32)
            tables[0] = row
            lengths = np.zeros(self.slots, np.int32)
            lengths[0] = prompt_len
            active = np.zeros(self.slots, bool)
            active[0] = True
            greedy = np.ones(self.slots, bool)
            temps = np.zeros(self.slots, np.float32)

            kp, vp, last, keys = self._pg.init_state()  # throwaway pool
            kp, vp, last, keys = pf(self.params, kp, vp, last, keys, row,
                                    padded, 0, prompt_len, 0, 0)
            toks, kp, vp, last, keys = df(self.params, kp, vp, last, keys,
                                          tables, lengths, active, greedy,
                                          temps)
            np.asarray(toks)

            outs = []
            t0 = time.perf_counter()
            for i in range(reps):
                kp, vp, last, keys = pf(self.params, kp, vp, last, keys,
                                        row, padded, 0, prompt_len, 0, i)
                toks, kp, vp, last, keys = df(self.params, kp, vp, last,
                                              keys, tables, lengths, active,
                                              greedy, temps)
                outs.append(toks)
            jax.block_until_ready(outs)
            ttft_ms = (time.perf_counter() - t0) / reps * 1e3

            n_chunks = (self.max_len - prompt_len) // self.chunk - 1
            if n_chunks < 1:
                return {"device_ttft_ms": round(ttft_ms, 2),
                        "device_decode_tokens_per_sec": 0.0}
            kp, vp, last, keys = pf(self.params, kp, vp, last, keys, row,
                                    padded, 0, prompt_len, 0, 0)
            t0 = time.perf_counter()
            for _ in range(n_chunks):
                toks, kp, vp, last, keys = df(self.params, kp, vp, last,
                                              keys, tables, lengths, active,
                                              greedy, temps)
            jax.block_until_ready(toks)
            dt = time.perf_counter() - t0
        return {
            "device_ttft_ms": round(ttft_ms, 2),
            "device_decode_tokens_per_sec": round(n_chunks * self.chunk / dt,
                                                  1),
        }


class _DisaggTicket:
    """One request's place in the disaggregated pipeline: queued → prefill
    → lane → decode-engine ``_Request``. Resolution (req or error) is
    signalled through the engine's condition variable."""

    __slots__ = ("prompt", "max_new", "temperature", "seed", "req", "error",
                 "resolved", "cancelled", "trace_ctx", "submitted_at")

    def __init__(self, prompt, max_new, temperature, seed):
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.req: Optional[_Request] = None
        self.error: Optional[BaseException] = None
        self.resolved = False
        self.cancelled = False
        self.submitted_at = time.perf_counter()
        self.trace_ctx = (tracing.current_context()
                          if tracing.is_sampled() else None)


class DisaggregatedLLMEngine:
    """Prefill/decode disaggregation: a prefill-specialized
    :class:`PagedLLMEngine` feeding a decode-specialized one over a
    :class:`~ray_tpu.serve.dag_pipeline.KVHandoffLane`.

    Mixed prefill+decode in one engine serializes heterogeneous work — a
    long prompt's prefill dispatch stalls every in-flight decode chunk
    behind it (the scaling cliff the TPU concurrency-limits paper maps).
    Here decode NEVER runs a prompt prefill: a prefill worker turns prompts
    into KV blocks (with its own prefix cache, so shared prefixes cost
    their FLOPs once), ships them over the lane's deferred-ack shm ring,
    and an ingest worker uploads them into the decode pool (donated
    ``insert_fn``) and enqueues a decode-only request. Streaming contract,
    shedding, and stats match :class:`LLMEngine`; ``close()`` joins the
    workers and destroys the lane (leak-check clean).

    In-process both halves share this object; the same lane protocol works
    cross-process (attach by name, ``create=False``) when prefill and
    decode live in separate replicas.
    """

    def __init__(self, params, config: TransformerConfig, *,
                 max_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 chunk: int = 8, slots: Optional[int] = None,
                 max_queue: Optional[int] = None, name: str = "LLM",
                 prefill_slots: int = 1,
                 block_tokens: Optional[int] = None,
                 pool_blocks: Optional[int] = None,
                 lane_slots: int = 4):
        from ray_tpu.core.config import config as _get_config
        from ray_tpu.serve.dag_pipeline import KVHandoffLane

        knobs = _get_config()
        self.name = name
        self.chunk = chunk
        self.max_queue = int(max_queue if max_queue is not None
                             else knobs.serve_admission_queue_limit)
        # spec_tokens=0: disaggregated decode admits via KV handoff, where
        # draft-side KV never exists — speculation is a colocated-engine
        # feature.
        self.decode = PagedLLMEngine(
            params, config, max_len=max_len, prompt_buckets=prompt_buckets,
            chunk=chunk, slots=slots, max_queue=0, name=name,
            block_tokens=block_tokens, pool_blocks=pool_blocks,
            spec_tokens=0)
        self.prefill = PagedLLMEngine(
            params, config, max_len=max_len, prompt_buckets=prompt_buckets,
            chunk=chunk, slots=max(1, prefill_slots), max_queue=0,
            name=f"{name}-prefill", block_tokens=block_tokens,
            pool_blocks=pool_blocks, spec_tokens=0)
        self.slots = self.decode.slots
        self.finish_reason = "stop"  # single-stream convenience, as LLMEngine

        c = config
        bt = self.decode.block_tokens
        itm = np.dtype(c.dtype).itemsize
        block_bytes = c.n_layers * bt * c.n_heads * c.head_dim * itm
        cap = (2 * self.decode.blocks_per_seq * block_bytes
               + self.decode._pg.logits_dim * 4 + 65536)
        self.lane = KVHandoffLane(capacity=cap, slots=max(2, lane_slots))

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._pq: collections.deque = collections.deque()
        self._lane_fifo: collections.deque = collections.deque()
        self._closed = False
        self._prefill_thread = threading.Thread(
            target=self._prefill_loop, name=f"{name}-disagg-prefill",
            daemon=True)
        self._ingest_thread = threading.Thread(
            target=self._ingest_loop, name=f"{name}-disagg-ingest",
            daemon=True)
        self._prefill_thread.start()
        self._ingest_thread.start()

    # -- pipeline workers -----------------------------------------------------
    def _prefill_loop(self) -> None:
        from ray_tpu.dag.channel import ChannelTimeout

        while True:
            with self._cv:
                while not self._pq and not self._closed:
                    self._cv.wait(timeout=0.1)
                if self._closed:
                    return
                t = self._pq.popleft()
            if t.cancelled:
                self._resolve(t, error=None)
                continue
            try:
                k, v, last_row, hit = self.prefill.prefill_to_blocks(
                    t.prompt, seed=t.seed)
                meta = {"prompt": t.prompt, "max_new": t.max_new,
                        "temperature": t.temperature, "seed": t.seed,
                        "hit_tokens": hit, "last_row": last_row,
                        "submitted_at": t.submitted_at}
                with self._cv:
                    self._lane_fifo.append(t)
                while True:
                    try:
                        self.lane.send(meta, k, v, timeout=1.0)
                        break
                    except ChannelTimeout:  # decode side slow to drain
                        if self._closed:
                            with self._cv:
                                try:
                                    self._lane_fifo.remove(t)
                                except ValueError:
                                    pass
                            self._resolve(
                                t, error=RuntimeError("engine closed"))
                            break
            except BaseException as e:  # noqa: BLE001 — poison one request
                # The ticket may already sit in _lane_fifo (send can fail
                # AFTER the append — channel fault, oversized payload);
                # leaving it there would pair every later handoff with the
                # wrong ticket. Unqueue before resolving.
                with self._cv:
                    try:
                        self._lane_fifo.remove(t)
                    except ValueError:
                        pass
                self._resolve(t, error=e)

    def _ingest_loop(self) -> None:
        from ray_tpu.dag.channel import ChannelClosed, ChannelTimeout

        while True:
            try:
                meta, k, v, token = self.lane.recv(timeout=0.25)
            except ChannelTimeout:
                if self._closed:
                    return
                continue
            except ChannelClosed:
                return
            with self._cv:
                t = self._lane_fifo.popleft() if self._lane_fifo else None
            if t is None:
                # Payload with no waiting ticket (its prefill thread
                # unqueued itself on a send-path error) — drop it and
                # return the ring slot.
                self.lane.ack(token)
                continue
            try:
                req = self.decode.admit_prefilled(
                    meta["prompt"], k, v, meta["last_row"],
                    max_new_tokens=meta["max_new"],
                    temperature=meta["temperature"], seed=meta["seed"],
                    hit_tokens=meta["hit_tokens"],
                    submitted_at=meta["submitted_at"],
                    trace_ctx=t.trace_ctx)
            except BaseException as e:  # noqa: BLE001 — poison one request
                self.lane.ack(token)
                self._resolve(t, error=e)
                continue
            # The upload landed (admit_prefilled syncs) — release the ring
            # slot back to the prefill writer. THE deferred-ack handoff.
            self.lane.ack(token)
            self._resolve(t, req=req)

    def _resolve(self, t: _DisaggTicket, req: Optional[_Request] = None,
                 error: Optional[BaseException] = None) -> None:
        with self._cv:
            t.req = req
            t.error = error
            t.resolved = True
            cancelled = t.cancelled
            self._cv.notify_all()
        if cancelled and req is not None:
            self.decode._cancel(req)

    # -- request surface (LLMEngine contract) ---------------------------------
    def submit(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0) -> _DisaggTicket:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.shape[0] == 0:
            raise ValueError("empty prompt")
        _check_token_ids(prompt, self.decode.config.vocab_size, self.name)
        self.decode._bucket_for(int(prompt.shape[0]))  # validate headroom
        t = _DisaggTicket(prompt, int(max_new_tokens), float(temperature),
                          int(seed))
        if max_new_tokens <= 0:
            t.resolved = True
            return t
        with self._cv:
            if self._closed:
                raise RuntimeError(f"engine {self.name} closed")
            if self.max_queue and len(self._pq) >= self.max_queue:
                raise _shed(self.name, len(self._pq), self.max_queue,
                            "already waiting for prefill")
            self._pq.append(t)
            self._cv.notify_all()
        return t

    def stream(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0,
               result: Optional[Dict] = None) -> Iterable[int]:
        if result is None:
            result = {}
        t = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                        temperature=temperature, seed=seed)

        def run():
            raised = None
            try:
                with self._cv:
                    deadline = time.monotonic() + 120.0
                    while not t.resolved:
                        # raylint: ignore[blocking-under-lock] — _cv wraps
                        # self._lock; wait() releases it.
                        if not self._cv.wait(timeout=0.2) \
                                and time.monotonic() > deadline:
                            raise TimeoutError(
                                "disaggregated prefill stalled")
                if t.error is not None:
                    raise t.error
                if t.req is None:
                    return
                for tok in self.decode.drive(t.req):
                    result["decode_tps"] = t.req.decode_tps()
                    yield tok
            except BaseException as e:
                raised = e
                raise
            finally:
                if t.req is not None:
                    fr = t.req.finish_reason or "stop"
                elif t.error is not None or raised is not None:
                    # The prefill-stall TimeoutError resolves nothing on the
                    # ticket — without tracking the raise this path would
                    # claim a clean "stop" for a generator that blew up.
                    fr = "error"
                elif t.cancelled:
                    fr = "cancelled"
                else:
                    fr = "stop"
                result["finish_reason"] = self.finish_reason = fr
                if t.req is not None and t.req.ttft_s is not None:
                    result["ttft_s"] = t.req.ttft_s

        gen = run()
        weakref.finalize(gen, self._cancel_ticket, t)
        return gen

    def generate(self, prompt_ids: Sequence[int], **kw) -> List[int]:
        return list(self.stream(prompt_ids, **kw))

    def _cancel_ticket(self, t: _DisaggTicket) -> None:
        req = None
        with self._cv:
            t.cancelled = True
            try:
                self._pq.remove(t)
                t.resolved = True  # never entered the pipeline
            except ValueError:
                req = t.req  # mid-pipeline (worker resolves) or decoding
            self._cv.notify_all()
        if req is not None:
            self.decode._cancel(req)

    # -- engine surface delegates ---------------------------------------------
    def warmup(self) -> None:
        self.prefill.warmup()
        self.decode.warmup()

    def stats(self) -> Dict[str, float]:
        out = self.decode.stats()
        with self._cv:
            out["queue_depth"] += float(len(self._pq)
                                        + len(self._lane_fifo))
        pf = self.prefill.kv.stats()
        out["prefill_kv_hit_tokens"] = pf["kv_hit_tokens"]
        out["prefill_kv_blocks_cached"] = pf["kv_blocks_cached"]
        return out

    def decode_tokens_per_sec(self) -> float:
        return self.decode.decode_tokens_per_sec()

    def device_metrics(self, **kw) -> Dict:
        return self.decode.device_metrics(**kw)

    def close(self) -> None:
        """Stop the pipeline workers, poison-pill the lane, destroy it.
        Pending tickets resolve as errors. Idempotent."""
        with self._cv:
            if self._closed:
                return
            self._closed = True
            leftovers = list(self._pq)
            self._pq.clear()
            self._cv.notify_all()
        for t in leftovers:
            self._resolve(t, error=RuntimeError(f"engine {self.name} closed"))
        self._prefill_thread.join(timeout=5.0)
        self.lane.close()  # pill — wakes the ingest loop
        self._ingest_thread.join(timeout=5.0)
        if self._ingest_thread.is_alive():
            # It can be parked in admit_prefilled's alloc retry (bounded by
            # its timeout_s=30) while holding zero-copy views into the ring
            # — wait that bound out before touching the mapping.
            self._ingest_thread.join(timeout=35.0)
        with self._cv:
            stranded = list(self._lane_fifo)
            self._lane_fifo.clear()
        for t in stranded:
            self._resolve(t, error=RuntimeError(f"engine {self.name} closed"))
        if self._ingest_thread.is_alive():
            # Still wedged: destroy() would unmap shm under the thread's
            # live views — leak the lane instead and let channel teardown
            # reclaim it when the views drop.
            return
        self.lane.destroy()


def llm_deployment(
    config: TransformerConfig,
    params_fn: Callable[[], Dict],
    *,
    name: str = "LLM",
    max_new_tokens_default: int = 32,
    slots: Optional[int] = None,
    chunk: int = 8,
    max_queue: Optional[int] = None,
    draft_config: Optional[TransformerConfig] = None,
    draft_params_fn: Optional[Callable[[], Dict]] = None,
    **deployment_kwargs,
):
    """Build a Serve deployment class around a continuous-batching
    :class:`LLMEngine`.

    ``params_fn`` runs inside the replica (checkpoint load / init) so weights
    never ship through the controller. Request payload::

        {"prompt_ids": [...], "max_new_tokens": n, "temperature": t,
         "seed": s}

    Responses stream ``{"token": id, "index": i, "decode_tps": rate}``
    dicts (call the handle with ``stream=True``); the final item adds
    ``finish_reason`` ("stop" | "length_cap"). ``decode_tps`` is THIS
    request's decode rate. Sampled requests without an explicit ``seed``
    draw a fresh one per request.

    The replica runs with ``max_concurrency`` sized to the engine so
    concurrent streams batch INSIDE one engine instead of queueing at the
    actor mailbox; ``get_engine_stats`` feeds slot occupancy and queue depth
    to the controller for KV-occupancy-aware routing.
    """
    import random as _random

    from ray_tpu import serve
    from ray_tpu.core.config import config as _get_config  # `config` is the
    # model's TransformerConfig here

    knobs = _get_config()
    n_slots = int(slots if slots is not None else knobs.serve_llm_slots)
    q_limit = int(max_queue if max_queue is not None
                  else knobs.serve_admission_queue_limit)
    # Streams park threads in the replica: enough actor threads for a full
    # slot set plus a shed-depth of waiters plus control-plane calls.
    deployment_kwargs.setdefault(
        "max_concurrency", n_slots + max(q_limit, 4) + 4)

    @serve.deployment(name=name, **deployment_kwargs)
    class LLMServer:
        def __init__(self):
            # Engine choice re-reads the knobs HERE (replica process): the
            # paged engine is the default; serve_kv_paged_enabled=0 falls
            # back to the PR 8 slotted engine, serve_disaggregation_enabled=1
            # splits prefill from decode over a KV handoff lane.
            eng_knobs = _get_config()
            eng_kw = {}
            if bool(eng_knobs.serve_disaggregation_enabled):
                cls = DisaggregatedLLMEngine
            elif bool(eng_knobs.serve_kv_paged_enabled):
                cls = PagedLLMEngine
                if draft_params_fn is not None:
                    # Draft weights load in-replica like the target's —
                    # speculation turns on when serve_spec_tokens > 0.
                    eng_kw["draft_params"] = draft_params_fn()
                    eng_kw["draft_config"] = draft_config
            else:
                cls = LLMEngine
            self.engine = cls(params_fn(), config, slots=n_slots,
                              chunk=chunk, max_queue=q_limit, name=name,
                              **eng_kw)
            self.engine.warmup()

        def __call__(self, payload):
            if "prompt_ids" in payload:
                prompt = payload["prompt_ids"]  # empty list → engine raises
            else:
                prompt = [1] * int(payload.get("prompt_len", 8))
            n = int(payload.get("max_new_tokens", max_new_tokens_default))
            temp = float(payload.get("temperature", 0.0))
            seed = payload.get("seed")
            if seed is None:
                seed = _random.getrandbits(31)
            outcome: dict = {}  # per-request, not the shared engine attr
            stream = self.engine.stream(
                prompt, max_new_tokens=n, temperature=temp, seed=int(seed),
                result=outcome)
            prev: dict | None = None
            for i, tok in enumerate(stream):
                if prev is not None:
                    yield prev
                prev = {"token": tok, "index": i,
                        "decode_tps": round(outcome.get("decode_tps", 0.0), 1)}
            if prev is not None:
                prev["finish_reason"] = outcome.get("finish_reason", "stop")
                if "ttft_s" in outcome:
                    # Measured submit→first-token latency — lets clients (and
                    # the tracing tests) check the span decomposition against
                    # the engine's own clock.
                    prev["ttft_s"] = outcome["ttft_s"]
                yield prev

        def get_engine_stats(self):
            return self.engine.stats()

        # -- drain migration (controller-driven, cluster KV tier) -------------
        def kv_migrate_out(self, lane_name: str) -> int:
            fn = getattr(self.engine, "kv_migrate_out", None)
            return int(fn(lane_name)) if fn is not None else 0

        def kv_migrate_in(self, lane_name: str) -> int:
            fn = getattr(self.engine, "kv_migrate_in", None)
            return int(fn(lane_name)) if fn is not None else 0

    return LLMServer
