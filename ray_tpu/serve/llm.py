"""LLM serving — continuous-batching KV-cache engine + Serve deployment.

The reference serves LLMs by embedding engines (vLLM) inside replicas;
TPU-native the engine is jitted XLA programs (``models/generate.py``) over a
SLOTTED KV cache: S independent sequences share one cache with per-slot
positions, and every decode dispatch advances ALL active slots at once — the
matmuls run at batch S instead of batch 1, which is the difference between
feeding the MXU and starving it.

Scheduling is iteration-level (the vLLM/Orca policy): each engine step

1. retires finished slots (max_new_tokens reached, or no room for another
   chunk before ``max_len`` — ``length_cap``) and immediately
2. admits queued prompts into the free slots, bounded by a prefill token
   budget per step (``serve_llm_prefill_tokens``) so a burst of long
   prompts can't starve in-flight decode, then
3. runs ONE batched decode chunk and distributes each slot's tokens to its
   request's queue.

There is no engine thread: the step loop is driven by whichever request
thread wins a non-blocking try-lock (``drive``), so an idle engine owns no
resources (leak-check clean) and a busy one is stepped exactly as fast as
its consumers read. Admission control sheds with :class:`~ray_tpu.serve.
errors.Saturated` once ``max_queue`` requests are already waiting.

Prompt bucketing is unchanged from the single-sequence engine: prompts pad
to a power-of-two bucket (one prefill compile per bucket, warmed at replica
start), first-token logits are read at the REAL last position, and decode
overwrites pad garbage before the causal mask could ever expose it.
"""

from __future__ import annotations

import collections
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from ray_tpu.models.generate import SlottedGenerator
from ray_tpu.models.transformer import TransformerConfig
from ray_tpu.serve.errors import Saturated
from ray_tpu.util import tracing


def _default_buckets(max_len: int) -> List[int]:
    buckets, b = [], 16
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


class _Request:
    """One in-flight generation: its token queue, slot, and counters.

    ``decode_tokens``/``decode_seconds`` live HERE (not on the engine) so the
    per-request ``decode_tps`` the deployment streams is this request's own
    rate — the engine-level attributes these replaced were shared across
    concurrent streams and raced exactly like ``finish_reason`` once did.
    """

    __slots__ = (
        "prompt", "padded", "real_len", "bucket", "max_new", "temperature",
        "seed", "tokens", "cond", "slot", "emitted", "done", "cancelled",
        "error", "finish_reason", "decode_tokens", "decode_seconds",
        "submitted_at", "ttft_s", "trace_ctx",
    )

    def __init__(self, prompt, padded, real_len, bucket, max_new,
                 temperature, seed, cond):
        self.prompt = prompt
        self.padded = padded
        self.real_len = real_len
        self.bucket = bucket
        self.max_new = max_new
        self.temperature = temperature
        self.seed = seed
        self.tokens: collections.deque = collections.deque()
        self.cond = cond
        self.slot: Optional[int] = None
        self.emitted = 0
        self.done = False
        self.cancelled = False
        self.error: Optional[BaseException] = None
        self.finish_reason: Optional[str] = None
        self.decode_tokens = 0
        self.decode_seconds = 0.0
        self.submitted_at = time.perf_counter()
        self.ttft_s: Optional[float] = None
        # Captured at submit time on the request's own thread; engine spans
        # must use THIS explicit context (the step loop runs on whichever
        # thread won the driver election — its ambient context belongs to a
        # different request). None unless the trace sampled in.
        self.trace_ctx = (tracing.current_context()
                          if tracing.is_sampled() else None)

    def decode_tps(self) -> float:
        if self.decode_seconds == 0:
            return 0.0
        return self.decode_tokens / self.decode_seconds


class LLMEngine:
    """Continuous-batching engine: S cache slots, caller-driven stepping.

    The single-sequence surface (``stream``/``generate``/``warmup``/
    ``device_metrics``) is unchanged; concurrency comes from calling
    ``stream`` from many threads — their sequences SHARE the batched decode
    dispatches instead of queueing behind each other.
    """

    def __init__(self, params, config: TransformerConfig, *,
                 max_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 chunk: int = 8,
                 slots: Optional[int] = None,
                 max_queue: Optional[int] = None,
                 name: str = "LLM"):
        from ray_tpu.core.config import config as _get_config

        knobs = _get_config()
        self.params = params
        self.config = config
        self.max_len = max_len or config.max_seq_len
        self.buckets = sorted(prompt_buckets or _default_buckets(self.max_len))
        self.chunk = chunk
        self.slots = int(slots if slots is not None else knobs.serve_llm_slots)
        self.max_queue = int(max_queue if max_queue is not None
                             else knobs.serve_admission_queue_limit)
        self.prefill_budget = int(knobs.serve_llm_prefill_tokens)
        self.name = name
        self._sg = SlottedGenerator(params, config, slots=self.slots,
                                    max_len=self.max_len)
        self._cache, self._last, self._keys = self._sg.init_state()

        # Lock order: _step_lock (try-acquired, never under others) →
        # _state_lock (request/slot bookkeeping; also every req.cond) →
        # _agg_lock. Device dispatches happen holding only _step_lock.
        self._step_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._agg_lock = threading.Lock()

        self._waiting: collections.deque = collections.deque()
        self._slot_req: List[Optional[_Request]] = [None] * self.slots
        self._slot_len = [0] * self.slots  # host mirror of device lengths
        self._active = np.zeros(self.slots, bool)
        self._greedy = np.ones(self.slots, bool)
        self._temps = np.zeros(self.slots, np.float32)

        # Aggregate decode counters (get_metrics / decode_tokens_per_sec);
        # the per-request truth lives on each _Request.
        self.decode_tokens = 0
        self.decode_seconds = 0.0
        self.finish_reason = "stop"  # convenience; races under concurrency

    # -- public single-request surface (back-compat) -------------------------
    def warmup(self) -> None:
        """Compile prefill for every bucket + the decode chunk, then reset —
        TTFT never pays XLA compilation. One program per bucket and one per
        chunk size: greedy vs sampled is an operand, not a recompile."""
        with self._step_lock:
            for b in self.buckets:
                pf = self._sg.prefill_fn(b)
                self._cache, self._last, self._keys = pf(
                    self.params, self._cache, self._last, self._keys,
                    np.zeros((1, b), np.int32), b, 0, 0)
            df = self._sg.decode_fn(self.chunk)
            toks, self._cache, self._last, self._keys = df(
                self.params, self._cache, self._last, self._keys,
                np.zeros(self.slots, bool), self._greedy, self._temps)
            np.asarray(toks)
            self._cache, self._last, self._keys = self._sg.init_state()

    def _bucket_for(self, n: int) -> int:
        # One full decode chunk must fit after the prompt: decode always
        # advances in `chunk`-token dispatches, and a slot with no room for
        # one retires as length_cap before emitting anything.
        if n + self.chunk > self.max_len:
            raise ValueError(
                f"prompt of {n} tokens leaves no room for a {self.chunk}-token "
                f"decode chunk within max_len {self.max_len}")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds max_len {self.max_len}")

    def stream(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0,
               result: Optional[Dict] = None) -> Iterable[int]:
        """Yield generated token ids for ONE request, decoded in shared
        batched chunks with every other in-flight request.

        ``result``, if given, receives ``{"finish_reason", "decode_tps"}`` —
        per-request values; the engine-level ``finish_reason`` attribute is a
        single-stream convenience and races under concurrency.
        """
        if result is None:
            result = {}
        req = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          temperature=temperature, seed=seed)

        def run():
            try:
                for tok in self.drive(req):
                    result["decode_tps"] = req.decode_tps()
                    yield tok
            finally:
                result["finish_reason"] = self.finish_reason = (
                    req.finish_reason or "stop")
                if req.ttft_s is not None:
                    result["ttft_s"] = req.ttft_s

        gen = run()
        # The request is submitted EAGERLY (Saturated raises at call time),
        # but an abandoned generator that was never started skips drive()'s
        # cancel-in-finally — close() doesn't enter an unstarted body. The
        # finalizer unqueues it at collection; _cancel is a no-op once done.
        weakref.finalize(gen, self._cancel, req)
        return gen

    def generate(self, prompt_ids: Sequence[int], **kw) -> List[int]:
        return list(self.stream(prompt_ids, **kw))

    # -- request lifecycle ----------------------------------------------------
    def submit(self, prompt_ids: Sequence[int], *, max_new_tokens: int = 32,
               temperature: float = 0.0, seed: int = 0) -> _Request:
        """Validate + enqueue; raises :class:`Saturated` when ``max_queue``
        requests are already waiting for a slot (0 disables shedding)."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        real_len = int(prompt.shape[0])
        if real_len == 0:
            raise ValueError("empty prompt")
        bucket = self._bucket_for(real_len)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :real_len] = prompt
        req = _Request(prompt, padded, real_len, bucket, int(max_new_tokens),
                       float(temperature), int(seed),
                       threading.Condition(self._state_lock))
        if max_new_tokens <= 0:
            req.done = True
            req.finish_reason = "stop"
            return req
        with self._state_lock:
            if self.max_queue and len(self._waiting) >= self.max_queue:
                raise Saturated(
                    f"engine {self.name}: {len(self._waiting)} requests "
                    f"already waiting (serve_admission_queue_limit="
                    f"{self.max_queue})")
            self._waiting.append(req)
        return req

    def drive(self, req: _Request) -> Iterable[int]:
        """Yield ``req``'s tokens, stepping the engine whenever this thread
        wins the step try-lock (otherwise another request's thread is the
        driver and this one just waits on its queue). Abandoning the
        generator cancels the request and frees its slot."""
        try:
            while True:
                with self._state_lock:
                    out = list(req.tokens)
                    req.tokens.clear()
                    done, err = req.done, req.error
                for tok in out:
                    yield tok
                if err is not None:
                    raise err
                if done:
                    return
                if self._step_lock.acquire(False):
                    try:
                        self._step()
                    finally:
                        self._step_lock.release()
                else:
                    with self._state_lock:
                        if not req.tokens and not req.done:
                            # Timed slice as a safety net only: the exiting
                            # driver hands off via _wake_inflight, and token
                            # arrival notifies directly.
                            # raylint: ignore[blocking-under-lock] — req.cond
                            # wraps _state_lock (Condition(self._state_lock)
                            # in submit), so wait() releases the held lock.
                            req.cond.wait(timeout=0.01)
        finally:
            self._cancel(req)
            # Driver handoff: this thread may have been the stepper — wake
            # every in-flight request so one of them re-elects immediately
            # instead of waiting out a poll slice.
            self._wake_inflight()

    def _wake_inflight(self) -> None:
        with self._state_lock:
            for r in self._slot_req:
                if r is not None:
                    r.cond.notify_all()
            for r in self._waiting:
                r.cond.notify_all()

    def _cancel(self, req: _Request) -> None:
        """No-op on a finished request; otherwise unqueue/mark-cancelled and
        free its slot for the next admission."""
        with self._state_lock:
            if req.done:
                return
            req.cancelled = True
            try:
                self._waiting.remove(req)
            except ValueError:
                pass
            if req.slot is not None:
                self._free_slot_locked(req.slot)
            req.done = True
            if req.finish_reason is None:
                req.finish_reason = "cancelled"
            req.cond.notify_all()

    def _free_slot_locked(self, slot: int) -> None:
        r = self._slot_req[slot]
        if r is not None:
            r.slot = None
        self._slot_req[slot] = None
        self._slot_len[slot] = 0
        self._active[slot] = False

    def _finish_locked(self, req: _Request, reason: str) -> None:
        req.finish_reason = reason
        req.done = True
        if req.slot is not None:
            self._free_slot_locked(req.slot)
        req.cond.notify_all()

    def _fail_inflight(self, err: BaseException) -> None:
        """A device-dispatch failure poisons every in-flight request: their
        cache state is gone. Reset to a fresh empty engine."""
        with self._state_lock:
            victims = list(self._waiting) + [r for r in self._slot_req
                                             if r is not None]
            self._waiting.clear()
            for slot in range(self.slots):
                self._free_slot_locked(slot)
            for r in victims:
                r.error = err
                r.done = True
                if r.finish_reason is None:
                    r.finish_reason = "error"
                r.cond.notify_all()
        self._cache, self._last, self._keys = self._sg.init_state()

    # -- the iteration-level scheduler ----------------------------------------
    def _step(self) -> None:
        # Called holding _step_lock (the elected driver).
        try:
            self._step_inner()
        except BaseException as err:
            self._fail_inflight(err)
            raise

    def _step_inner(self) -> None:
        # 1. Retire: a slot whose next chunk would cross max_len ends as
        #    length_cap BEFORE dispatch (no partial chunks — shapes stay
        #    static), and cancelled slots free immediately.
        with self._state_lock:
            for slot in range(self.slots):
                req = self._slot_req[slot]
                if req is None:
                    continue
                if req.cancelled:
                    self._free_slot_locked(slot)
                elif self._slot_len[slot] + self.chunk > self.max_len:
                    self._finish_locked(req, "length_cap")

        # 2. Admit queued prompts into free slots under the prefill budget.
        #    The FIRST admission always goes through — the budget bounds how
        #    much prefill work piles into one step, never progress.
        admitted_tokens = 0
        while True:
            with self._state_lock:
                free = next((s for s in range(self.slots)
                             if self._slot_req[s] is None), None)
                if free is None or not self._waiting:
                    break
                nxt = self._waiting[0]
                if admitted_tokens and (
                        admitted_tokens + nxt.bucket > self.prefill_budget):
                    break
                self._waiting.popleft()
                if nxt.cancelled:
                    continue
                nxt.slot = free
                self._slot_req[free] = nxt
                self._slot_len[free] = nxt.real_len
                self._active[free] = True
                self._greedy[free] = nxt.temperature <= 0
                self._temps[free] = nxt.temperature if nxt.temperature > 0 else 0.0
            t_admit = time.perf_counter()
            if nxt.trace_ctx is not None:
                tracing.emit(
                    "llm.admission_wait", nxt.trace_ctx,
                    duration=t_admit - nxt.submitted_at,
                    attrs={"slot": free, "engine": self.name})
            pf = self._sg.prefill_fn(nxt.bucket)
            self._cache, self._last, self._keys = pf(
                self.params, self._cache, self._last, self._keys,
                nxt.padded, nxt.real_len, free, nxt.seed)
            if nxt.trace_ctx is not None:
                tracing.emit(
                    "llm.prefill", nxt.trace_ctx,
                    duration=time.perf_counter() - t_admit,
                    attrs={"slot": free, "bucket": nxt.bucket,
                           "prompt_len": nxt.real_len})
            admitted_tokens += nxt.bucket

        with self._state_lock:
            if not any(r is not None for r in self._slot_req):
                return
            active = self._active.copy()
            greedy = self._greedy.copy()
            temps = self._temps.copy()

        # 3. One batched decode chunk advancing every active slot.
        df = self._sg.decode_fn(self.chunk)
        t0 = time.perf_counter()
        toks, self._cache, self._last, self._keys = df(
            self.params, self._cache, self._last, self._keys,
            active, greedy, temps)
        host_toks = np.asarray(toks)  # the step's single device sync
        dt = time.perf_counter() - t0
        now = time.perf_counter()

        # 4. Distribute each slot's tokens to its request.
        delivered_total = 0
        ttfts: List[float] = []
        batch_size = int(active.sum())
        chunk_spans: List[tuple] = []  # sampled requests' (ctx, slot, ntok)
        with self._state_lock:
            for slot in range(self.slots):
                req = self._slot_req[slot]
                if req is None or not active[slot]:
                    continue
                self._slot_len[slot] += self.chunk
                if req.cancelled:
                    self._free_slot_locked(slot)
                    continue
                upto = min(self.chunk, req.max_new - req.emitted)
                if upto > 0 and req.ttft_s is None:
                    req.ttft_s = now - req.submitted_at
                    ttfts.append(req.ttft_s)
                if req.trace_ctx is not None and upto > 0:
                    chunk_spans.append((req.trace_ctx, slot, upto))
                req.tokens.extend(int(t) for t in host_toks[slot][:upto])
                req.emitted += upto
                req.decode_tokens += upto
                req.decode_seconds += dt
                delivered_total += upto
                if req.emitted >= req.max_new:
                    self._finish_locked(req, "stop")
                else:
                    req.cond.notify_all()
        with self._agg_lock:
            self.decode_tokens += delivered_total
            self.decode_seconds += dt
        # Emitted OUTSIDE _state_lock: span export may take its own locks.
        for ctx, slot, ntok in chunk_spans:
            tracing.emit("llm.decode_chunk", ctx, duration=dt, end_time=None,
                         attrs={"slot": slot, "tokens": ntok,
                                "batch": batch_size})
        self._observe(delivered_total, ttfts)

    def _observe(self, delivered: int, ttfts: List[float]) -> None:
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 serve_tokens_total,
                                                 serve_ttft_hist)

        if not metrics_enabled():
            return
        tags = {"deployment": self.name}
        if delivered:
            serve_tokens_total().inc(delivered, tags)
        for t in ttfts:
            serve_ttft_hist().observe(t, tags)

    # -- introspection --------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """Slot occupancy + admission queue depth — exported through
        ``ReplicaActor.get_metrics`` for KV-occupancy-aware routing."""
        with self._state_lock:
            busy = sum(1 for r in self._slot_req if r is not None)
            depth = len(self._waiting)
        return {"slots_total": float(self.slots), "slots_busy": float(busy),
                "queue_depth": float(depth)}

    def decode_tokens_per_sec(self) -> float:
        with self._agg_lock:
            if self.decode_seconds == 0:
                return 0.0
            return self.decode_tokens / self.decode_seconds

    def device_metrics(self, *, prompt_len: int = 16, reps: int = 10) -> Dict:
        """Device-side TTFT and decode rate, excluding host↔device RTT.

        Runs on a throwaway slot state (serialized with serving via the step
        lock): TTFT is prefill + first decode chunk; the decode rate chains
        chunks with one final sync so async dispatch overlaps and the number
        reflects pure device time. One slot active — the per-sequence rate
        of the batched program.
        """
        import jax

        bucket = self._bucket_for(prompt_len)
        with self._step_lock:
            pf = self._sg.prefill_fn(bucket)
            df = self._sg.decode_fn(self.chunk)
            padded = np.zeros((1, bucket), np.int32)
            active = np.zeros(self.slots, bool)
            active[0] = True
            greedy = np.ones(self.slots, bool)
            temps = np.zeros(self.slots, np.float32)

            cache, last, keys = self._sg.init_state()
            # Warm both programs before timing.
            cache, last, keys = pf(self.params, cache, last, keys, padded,
                                   prompt_len, 0, 0)
            toks, cache, last, keys = df(self.params, cache, last, keys,
                                         active, greedy, temps)
            np.asarray(toks)

            outs = []
            t0 = time.perf_counter()
            for i in range(reps):
                cache, last, keys = pf(self.params, cache, last, keys,
                                       padded, prompt_len, 0, i)
                toks, cache, last, keys = df(self.params, cache, last, keys,
                                             active, greedy, temps)
                outs.append(toks)
            jax.block_until_ready(outs)
            ttft_ms = (time.perf_counter() - t0) / reps * 1e3

            n_chunks = (self.max_len - prompt_len) // self.chunk - 1
            if n_chunks < 1:
                return {"device_ttft_ms": round(ttft_ms, 2),
                        "device_decode_tokens_per_sec": 0.0}
            cache, last, keys = pf(self.params, cache, last, keys, padded,
                                   prompt_len, 0, 0)
            t0 = time.perf_counter()
            for _ in range(n_chunks):
                toks, cache, last, keys = df(self.params, cache, last, keys,
                                             active, greedy, temps)
            jax.block_until_ready(toks)
            dt = time.perf_counter() - t0
        return {
            "device_ttft_ms": round(ttft_ms, 2),
            "device_decode_tokens_per_sec": round(n_chunks * self.chunk / dt, 1),
        }


def llm_deployment(
    config: TransformerConfig,
    params_fn: Callable[[], Dict],
    *,
    name: str = "LLM",
    max_new_tokens_default: int = 32,
    slots: Optional[int] = None,
    chunk: int = 8,
    max_queue: Optional[int] = None,
    **deployment_kwargs,
):
    """Build a Serve deployment class around a continuous-batching
    :class:`LLMEngine`.

    ``params_fn`` runs inside the replica (checkpoint load / init) so weights
    never ship through the controller. Request payload::

        {"prompt_ids": [...], "max_new_tokens": n, "temperature": t,
         "seed": s}

    Responses stream ``{"token": id, "index": i, "decode_tps": rate}``
    dicts (call the handle with ``stream=True``); the final item adds
    ``finish_reason`` ("stop" | "length_cap"). ``decode_tps`` is THIS
    request's decode rate. Sampled requests without an explicit ``seed``
    draw a fresh one per request.

    The replica runs with ``max_concurrency`` sized to the engine so
    concurrent streams batch INSIDE one engine instead of queueing at the
    actor mailbox; ``get_engine_stats`` feeds slot occupancy and queue depth
    to the controller for KV-occupancy-aware routing.
    """
    import random as _random

    from ray_tpu import serve
    from ray_tpu.core.config import config as _get_config  # `config` is the
    # model's TransformerConfig here

    knobs = _get_config()
    n_slots = int(slots if slots is not None else knobs.serve_llm_slots)
    q_limit = int(max_queue if max_queue is not None
                  else knobs.serve_admission_queue_limit)
    # Streams park threads in the replica: enough actor threads for a full
    # slot set plus a shed-depth of waiters plus control-plane calls.
    deployment_kwargs.setdefault(
        "max_concurrency", n_slots + max(q_limit, 4) + 4)

    @serve.deployment(name=name, **deployment_kwargs)
    class LLMServer:
        def __init__(self):
            self.engine = LLMEngine(params_fn(), config, slots=n_slots,
                                    chunk=chunk, max_queue=q_limit, name=name)
            self.engine.warmup()

        def __call__(self, payload):
            if "prompt_ids" in payload:
                prompt = payload["prompt_ids"]  # empty list → engine raises
            else:
                prompt = [1] * int(payload.get("prompt_len", 8))
            n = int(payload.get("max_new_tokens", max_new_tokens_default))
            temp = float(payload.get("temperature", 0.0))
            seed = payload.get("seed")
            if seed is None:
                seed = _random.getrandbits(31)
            outcome: dict = {}  # per-request, not the shared engine attr
            stream = self.engine.stream(
                prompt, max_new_tokens=n, temperature=temp, seed=int(seed),
                result=outcome)
            prev: dict | None = None
            for i, tok in enumerate(stream):
                if prev is not None:
                    yield prev
                prev = {"token": tok, "index": i,
                        "decode_tps": round(outcome.get("decode_tps", 0.0), 1)}
            if prev is not None:
                prev["finish_reason"] = outcome.get("finish_reason", "stop")
                if "ttft_s" in outcome:
                    # Measured submit→first-token latency — lets clients (and
                    # the tracing tests) check the span decomposition against
                    # the engine's own clock.
                    prev["ttft_s"] = outcome["ttft_s"]
                yield prev

        def get_engine_stats(self):
            return self.engine.stats()

    return LLMServer
