"""Precompiled serve pipelines — resident compiled DAGs over replicas.

The µs-scale serving path: for a LINEAR chain of deployments
(preprocess → model → postprocess), ``serve.run_pipeline(..., compiled=True)``
precompiles the call chain into resident compiled-DAG lanes. Each lane
parks one replica of every stage in a ``dag_call`` loop over mutable
channels (``ray_tpu.dag``), so a steady-state request costs one channel
write + one read per edge instead of a full per-stage actor RPC
(spec encode → lease → push → seal). The ROADMAP's "compiled DAGs as the
execution substrate for serve replicas", and the host-side analog of the
throughput-per-chip framing in the Gemma-on-TPU serving comparison
(PAPERS.md) — control-plane overhead off the per-token path.

Trade-off (documented in README "Compiled DAG performance"): a replica
parked in a pipeline lane is DEDICATED — the resident loop occupies its
execution thread, so it no longer serves routed ``handle_request`` traffic,
and autoscaling/redeploys must not touch lane members mid-flight. Lanes are
therefore built from a fixed replica snapshot at build time; tear the
pipeline down (``PipelineHandle.shutdown``) before redeploying its stages.

``compiled=False`` builds the same chain over per-call DeploymentHandles —
the A/B baseline ``benches/dag_tick.py`` measures against.
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, List, Optional

import ray_tpu
from ray_tpu.dag.dag_node import InputNode
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger("serve_pipeline")


class PipelineResponse:
    """Future-like response (same surface as DeploymentResponse.result)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = 30.0):
        return self._ref.get(timeout=timeout_s)


class PipelineHandle:
    """Ingress handle of a COMPILED pipeline: requests round-robin over the
    precompiled lanes; each lane pipelines several in-flight requests
    through its multi-slot ring edges."""

    def __init__(self, stage_names: List[str], lanes: List[Any]):
        self.stage_names = list(stage_names)
        self._lanes = list(lanes)
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self._shut = False
        # serve.run_pipeline registers the handle here so serve.shutdown()
        # can tear down forgotten pipelines; a direct shutdown() call
        # deregisters so repeatedly-rebuilt pipelines don't accrete.
        self._registry: Optional[list] = None

    @property
    def num_lanes(self) -> int:
        return len(self._lanes)

    def remote(self, value: Any) -> PipelineResponse:
        if self._shut:
            raise RuntimeError("pipeline was shut down")
        lane = self._lanes[next(self._rr) % len(self._lanes)]
        return PipelineResponse(lane.execute(value))

    def shutdown(self) -> None:
        """Tear down every lane (close pills propagate, loops exit, the
        driver unlinks the channels). The stage replicas come back to life
        as ordinary routed replicas afterwards. Idempotent."""
        with self._lock:
            if self._shut:
                return
            self._shut = True
            for lane in self._lanes:
                try:
                    lane.teardown()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    log_swallowed(logger, "pipeline lane teardown")
        if self._registry is not None:
            try:
                self._registry.remove(self)
            except ValueError:
                pass  # serve.shutdown already popped us


class SequentialPipelineHandle:
    """Per-call baseline: the same chain walked with one routed actor RPC
    per stage per request (what ``compiled=True`` collapses)."""

    def __init__(self, stage_names: List[str], handles: List[Any]):
        self.stage_names = list(stage_names)
        self._handles = list(handles)

    def remote(self, value: Any) -> "_SequentialResponse":
        return _SequentialResponse(self._handles, value)

    def shutdown(self) -> None:
        pass  # nothing resident to tear down


class _SequentialResponse:
    def __init__(self, handles, value):
        self._handles = handles
        self._value = value
        self._done = False

    def result(self, timeout_s: Optional[float] = 30.0):
        if not self._done:
            v = self._value
            for h in self._handles:
                v = h.remote(v).result(timeout_s=timeout_s)
            self._value = v
            self._done = True
        return self._value


def build_compiled_pipeline(controller, stage_names: List[str], *,
                            channel_type: str = "auto",
                            channel_capacity: int = 4 * 1024 * 1024,
                            channel_slots: Optional[int] = None,
                            lanes: Optional[int] = None) -> PipelineHandle:
    """Compile ``lanes`` parallel resident DAG lanes over the current
    replica fleet of ``stage_names`` (in chain order). Each lane uses a
    DISTINCT replica per stage (a resident loop occupies the replica), so
    the lane count is capped by the smallest stage's replica count."""
    _version, table = ray_tpu.get(
        controller.get_snapshot.remote(-1, 0.0))
    replica_sets = []
    for name in stage_names:
        entry = table.get(name)
        if not entry or not entry["replicas"]:
            raise RuntimeError(
                f"deployment {name!r} has no live replicas to compile")
        replica_sets.append(list(entry["replicas"]))
    max_lanes = min(len(rs) for rs in replica_sets)
    n_lanes = min(lanes, max_lanes) if lanes else max_lanes
    compiled_lanes = []
    try:
        for lane in range(n_lanes):
            node = InputNode()
            for rs in replica_sets:
                node = rs[lane].dag_call.bind(node)
            compiled_lanes.append(node.experimental_compile(
                channel_type=channel_type,
                channel_capacity=channel_capacity,
                channel_slots=channel_slots))
    except BaseException:
        for built in compiled_lanes:
            try:
                built.teardown()
            except Exception:  # noqa: BLE001 — unwind is best-effort
                log_swallowed(logger, "pipeline build unwind")
        raise
    return PipelineHandle(stage_names, compiled_lanes)
