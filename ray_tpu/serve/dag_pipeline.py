"""Precompiled serve pipelines — resident compiled DAGs over replicas.

The µs-scale serving path: for a LINEAR chain of deployments
(preprocess → model → postprocess), ``serve.run_pipeline(..., compiled=True)``
precompiles the call chain into resident compiled-DAG lanes. Each lane
parks one replica of every stage in a ``dag_call`` loop over mutable
channels (``ray_tpu.dag``), so a steady-state request costs one channel
write + one read per edge instead of a full per-stage actor RPC
(spec encode → lease → push → seal). The ROADMAP's "compiled DAGs as the
execution substrate for serve replicas", and the host-side analog of the
throughput-per-chip framing in the Gemma-on-TPU serving comparison
(PAPERS.md) — control-plane overhead off the per-token path.

Trade-off (documented in README "Compiled DAG performance"): a replica
parked in a pipeline lane is DEDICATED — the resident loop occupies its
execution thread, so it no longer serves routed ``handle_request`` traffic,
and autoscaling/redeploys must not touch lane members mid-flight. Lanes are
therefore built from a fixed replica snapshot at build time; tear the
pipeline down (``PipelineHandle.shutdown``) before redeploying its stages.

``compiled=False`` builds the same chain over per-call DeploymentHandles —
the A/B baseline ``benches/dag_tick.py`` measures against.
"""

from __future__ import annotations

import itertools
import struct
import threading
import time
from typing import Any, List, Optional, Tuple

import numpy as np

import ray_tpu
from ray_tpu.dag.dag_node import InputNode
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger("serve_pipeline")


class PipelineResponse:
    """Future-like response (same surface as DeploymentResponse.result)."""

    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout_s: Optional[float] = 30.0):
        return self._ref.get(timeout=timeout_s)


class PipelineHandle:
    """Ingress handle of a COMPILED pipeline: requests round-robin over the
    precompiled lanes; each lane pipelines several in-flight requests
    through its multi-slot ring edges."""

    def __init__(self, stage_names: List[str], lanes: List[Any]):
        self.stage_names = list(stage_names)
        self._lanes = list(lanes)
        self._rr = itertools.count()
        self._lock = threading.Lock()
        self._shut = False
        # serve.run_pipeline registers the handle here so serve.shutdown()
        # can tear down forgotten pipelines; a direct shutdown() call
        # deregisters so repeatedly-rebuilt pipelines don't accrete.
        self._registry: Optional[list] = None

    @property
    def num_lanes(self) -> int:
        return len(self._lanes)

    def remote(self, value: Any) -> PipelineResponse:
        if self._shut:
            raise RuntimeError("pipeline was shut down")
        lane = self._lanes[next(self._rr) % len(self._lanes)]
        return PipelineResponse(lane.execute(value))

    def shutdown(self) -> None:
        """Tear down every lane (close pills propagate, loops exit, the
        driver unlinks the channels). The stage replicas come back to life
        as ordinary routed replicas afterwards. Idempotent."""
        with self._lock:
            if self._shut:
                return
            self._shut = True
            for lane in self._lanes:
                try:
                    lane.teardown()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    log_swallowed(logger, "pipeline lane teardown")
        if self._registry is not None:
            try:
                self._registry.remove(self)
            except ValueError:
                pass  # serve.shutdown already popped us


class SequentialPipelineHandle:
    """Per-call baseline: the same chain walked with one routed actor RPC
    per stage per request (what ``compiled=True`` collapses)."""

    def __init__(self, stage_names: List[str], handles: List[Any]):
        self.stage_names = list(stage_names)
        self._handles = list(handles)

    def remote(self, value: Any) -> "_SequentialResponse":
        return _SequentialResponse(self._handles, value)

    def shutdown(self) -> None:
        pass  # nothing resident to tear down


class _SequentialResponse:
    def __init__(self, handles, value):
        self._handles = handles
        self._value = value
        self._done = False

    def result(self, timeout_s: Optional[float] = 30.0):
        if not self._done:
            v = self._value
            for h in self._handles:
                v = h.remote(v).result(timeout_s=timeout_s)
            self._value = v
            self._done = True
        return self._value


def build_compiled_pipeline(controller, stage_names: List[str], *,
                            channel_type: str = "auto",
                            channel_capacity: int = 4 * 1024 * 1024,
                            channel_slots: Optional[int] = None,
                            lanes: Optional[int] = None) -> PipelineHandle:
    """Compile ``lanes`` parallel resident DAG lanes over the current
    replica fleet of ``stage_names`` (in chain order). Each lane uses a
    DISTINCT replica per stage (a resident loop occupies the replica), so
    the lane count is capped by the smallest stage's replica count."""
    _version, table = ray_tpu.get(
        controller.get_snapshot.remote(-1, 0.0))
    replica_sets = []
    for name in stage_names:
        entry = table.get(name)
        if not entry or not entry["replicas"]:
            raise RuntimeError(
                f"deployment {name!r} has no live replicas to compile")
        replica_sets.append(list(entry["replicas"]))
    max_lanes = min(len(rs) for rs in replica_sets)
    n_lanes = min(lanes, max_lanes) if lanes else max_lanes
    compiled_lanes = []
    try:
        for lane in range(n_lanes):
            node = InputNode()
            for rs in replica_sets:
                node = rs[lane].dag_call.bind(node)
            compiled_lanes.append(node.experimental_compile(
                channel_type=channel_type,
                channel_capacity=channel_capacity,
                channel_slots=channel_slots))
    except BaseException:
        for built in compiled_lanes:
            try:
                built.teardown()
            except Exception:  # noqa: BLE001 — unwind is best-effort
                log_swallowed(logger, "pipeline build unwind")
        raise
    return PipelineHandle(stage_names, compiled_lanes)


class KVHandoffLane:
    """Prefill→decode KV-block transport over one multi-slot shm
    :class:`~ray_tpu.dag.channel.Channel` — the disaggregated-serving lane.

    A finished prefill's pool blocks travel as one framed payload::

        [meta_len, k_len, v_len : <QQQ>] [pickled meta] [raw K] [raw V]

    where meta carries the request (prompt, sampling params, last-token
    logits row) and the K/V dtype+shape needed to reinterpret the raw bytes.
    ``send`` lands the arrays DIRECTLY in the ring slot via the channel's
    ``_wait_writable``/``_publish`` split (no intermediate buffer), and
    ``recv`` returns zero-copy ``np.frombuffer`` views into the slot plus an
    ack token: the DEFERRED-ACK protocol (``_consume_view``/``_ack``) built
    for DMA in PR 7 — the decode engine uploads the views into its own pool
    (a donated ``insert_fn`` dispatch), blocks until the transfer lands,
    and only then releases the slot back to the prefill writer. Up to
    ``slots`` handoffs ride in flight, so prefill keeps producing while
    decode drains.

    Single-writer (prefill side) / single-reader (decode side), in- or
    cross-process: a remote decode replica attaches by ``name`` with
    ``create=False``, same as every other channel endpoint.
    """

    _HDR = struct.Struct("<QQQ")

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 8 * 1024 * 1024,
                 slots: Optional[int] = None, create: bool = True):
        from ray_tpu.dag.channel import Channel

        self.chan = Channel(name=name, capacity=capacity, create=create,
                            slots=slots)
        self.name = self.chan.name

    @classmethod
    def attach(cls, name: str, timeout: float = 10.0,
               capacity: int = 8 * 1024 * 1024,
               slots: Optional[int] = None) -> Optional["KVHandoffLane"]:
        """Attach to a lane some OTHER endpoint creates, retrying until it
        appears or ``timeout`` lapses (None on timeout). The KV-tier drain
        path races lane creation against attachment — the survivor creates,
        the retiring victim attaches — so the attach side polls instead of
        requiring create-before-attach ordering. ``capacity``/``slots``
        must MATCH the creator's (the shm mapping is sized from them; both
        drain endpoints derive them from the same model config)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                return cls(name=name, capacity=capacity, slots=slots,
                           create=False)
            except Exception:  # noqa: BLE001 — shm segment not there yet
                if time.monotonic() > deadline:
                    return None
                time.sleep(0.01)

    # -- writer half (prefill engine) -----------------------------------------
    def send(self, meta: dict, k: np.ndarray, v: np.ndarray,
             timeout: Optional[float] = 30.0) -> None:
        from ray_tpu.core import serialization

        k = np.ascontiguousarray(k)
        v = np.ascontiguousarray(v)
        meta = dict(meta)
        meta["dtype"] = str(k.dtype)
        meta["shape"] = tuple(int(d) for d in k.shape)
        blob = serialization.dumps(meta)
        total = self._HDR.size + len(blob) + k.nbytes + v.nbytes
        if total > self.chan.capacity:
            raise ValueError(
                f"KV handoff of {total} bytes exceeds lane capacity "
                f"{self.chan.capacity}")
        self.chan._wait_writable(timeout)
        mm = self.chan._mm
        off = self.chan._wpayload_off
        self._HDR.pack_into(mm, off, len(blob), k.nbytes, v.nbytes)
        off += self._HDR.size
        mm[off:off + len(blob)] = blob
        off += len(blob)
        np.frombuffer(mm, np.uint8, k.nbytes, off)[:] = \
            k.reshape(-1).view(np.uint8)
        off += k.nbytes
        np.frombuffer(mm, np.uint8, v.nbytes, off)[:] = \
            v.reshape(-1).view(np.uint8)
        self.chan._publish(total)

    # -- reader half (decode engine) ------------------------------------------
    def recv(self, timeout: Optional[float] = 30.0
             ) -> Tuple[dict, np.ndarray, np.ndarray, Tuple[int, int]]:
        """Return ``(meta, k, v, ack_token)``. ``k``/``v`` are views into
        the ring slot — they stay valid (the writer cannot reuse the slot)
        until ``ack(ack_token)``; copy or upload them first."""
        from ray_tpu.core import serialization
        from ray_tpu.dag.channel import _CLOSE, ChannelClosed

        view, length, slot, seq = self.chan._consume_view(timeout)
        if length == len(_CLOSE) and bytes(view[:length]) == _CLOSE:
            self.chan._ack(slot, seq)
            raise ChannelClosed(self.name)
        meta_len, k_len, v_len = self._HDR.unpack_from(view, 0)
        off = self._HDR.size
        meta = serialization.loads(bytes(view[off:off + meta_len]))
        off += meta_len
        dt = np.dtype(meta["dtype"])
        shape = tuple(meta["shape"])
        k = np.frombuffer(view, dt, k_len // dt.itemsize, off).reshape(shape)
        off += k_len
        v = np.frombuffer(view, dt, v_len // dt.itemsize, off).reshape(shape)
        return meta, k, v, (slot, seq)

    def ack(self, token: Tuple[int, int]) -> None:
        self.chan._ack(*token)

    # -- lifecycle ------------------------------------------------------------
    def close(self) -> None:
        self.chan.close()

    def detach(self) -> None:
        self.chan.detach()

    def destroy(self) -> None:
        self.chan.destroy()
