"""Model multiplexing — many models per deployment, routed to warm replicas.

Analog of the reference's ``python/ray/serve/_private/multiplex.py``
(``_ModelMultiplexWrapper``) and the pow-2 scheduler's model-aware routing
(``replica_scheduler/pow_2_scheduler.py:127-135``): a replica method
decorated with ``@serve.multiplexed(max_num_models_per_replica=N)`` loads
models on demand into a per-replica LRU; each loaded set is reported to the
controller, and the router prefers replicas that already hold the requested
``multiplexed_model_id`` — cold replicas only see a model id when every warm
one is saturated, so the cluster converges to a stable model↔replica
assignment without any central planner.

TPU note: "model" here is typically a params pytree already resident in
device HBM — the LRU bound is the HBM budget, and routing-to-warm avoids
re-uploading weights through the host for every request.
"""

from __future__ import annotations

import contextvars
import threading
from collections import OrderedDict
from typing import Any, Callable, List, Optional

_current_model_id: contextvars.ContextVar[str] = contextvars.ContextVar(
    "ray_tpu_multiplexed_model_id", default="")

# Per-process registry of wrappers so the hosting replica can report its
# loaded model ids (one replica process hosts at most one deployment).
_wrappers: List["_ModelMultiplexWrapper"] = []
_wrappers_lock = threading.Lock()


def get_multiplexed_model_id() -> str:
    """Inside a replica: the model id of the CURRENT request (reference:
    ``serve.get_multiplexed_model_id``)."""
    return _current_model_id.get()


class _LoadGate:
    """One load ATTEMPT: waiters park on ``event``; if the loader raised,
    ``error`` carries the exception to every waiter of THIS attempt (a later
    request starts a fresh attempt — transient failures stay retryable)."""

    __slots__ = ("event", "error")

    def __init__(self):
        self.event = threading.Event()
        self.error: Optional[BaseException] = None


def _wait_slice() -> float:
    """internal_wait_timeout_s, with its default as the fallback."""
    try:
        from ray_tpu.core.config import config

        return config().internal_wait_timeout_s
    except Exception:  # noqa: BLE001 — config unavailable mid-teardown
        return 60.0


class _ModelMultiplexWrapper:
    """LRU of loaded models keyed by model id."""

    def __init__(self, loader: Callable[[Any, str], Any],
                 max_num_models: int):
        self._loader = loader
        self._max = max_num_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._lock = threading.Lock()
        # Per-model load gates: concurrent cold requests for the SAME model
        # must not each run the loader (two HBM weight uploads, transient 2x
        # memory). One thread loads; the rest wait on its gate.
        self._loading: dict = {}
        with _wrappers_lock:
            _wrappers.append(self)

    def loaded_model_ids(self) -> List[str]:
        with self._lock:
            return list(self._models.keys())

    def load(self, instance, model_id: str) -> Any:
        while True:
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                gate = self._loading.get(model_id)
                if gate is None:
                    gate = _LoadGate()
                    self._loading[model_id] = gate
                    break  # this thread loads
            # Timed slices (not one magic 600s park): a loader thread lost
            # to a kill mid-load wakes the waiters at the internal cadence
            # to re-check instead of stranding them.
            gate.event.wait(timeout=_wait_slice())
            if gate.event.is_set() and gate.error is not None:
                # THIS attempt failed: every parked waiter gets the loader's
                # exception instead of serially re-running a failing loader.
                raise gate.error
            # loaded (or still loading / loader died) — re-check the cache
        try:
            model = self._loader(instance, model_id)
        except BaseException as e:  # noqa: BLE001 — propagate to waiters
            with self._lock:
                self._loading.pop(model_id, None)
            gate.error = e
            gate.event.set()
            raise
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            if len(self._models) > self._max:
                self._models.popitem(last=False)  # LRU eviction
            self._loading.pop(model_id, None)
        gate.event.set()
        return model


def multiplexed(max_num_models_per_replica: int = 3):
    """Decorator for a replica's model-loader method::

        @serve.deployment
        class Models:
            @serve.multiplexed(max_num_models_per_replica=4)
            def get_model(self, model_id: str):
                return load_params(model_id)   # cached per replica, LRU

            def __call__(self, payload):
                model = self.get_model(serve.get_multiplexed_model_id())
                return infer(model, payload)

    Callers pick the model with
    ``handle.options(multiplexed_model_id="m1").remote(...)``.
    """

    def decorate(loader: Callable) -> Callable:
        wrapper = _ModelMultiplexWrapper(loader, max_num_models_per_replica)

        def bound(self, model_id: Optional[str] = None):
            mid = model_id if model_id is not None else get_multiplexed_model_id()
            if not mid:
                raise ValueError(
                    "no model id: pass one explicitly or set "
                    "handle.options(multiplexed_model_id=...) on the caller")
            return wrapper.load(self, mid)

        bound.__name__ = getattr(loader, "__name__", "get_model")
        bound._multiplex_wrapper = wrapper
        return bound

    return decorate


def loaded_model_ids() -> List[str]:
    """All model ids loaded in this process (union over wrappers)."""
    with _wrappers_lock:
        wrappers = list(_wrappers)
    out: List[str] = []
    for w in wrappers:
        out.extend(w.loaded_model_ids())
    return out


def set_current_model_id(model_id: str):
    return _current_model_id.set(model_id)


def reset_current_model_id(token) -> None:
    _current_model_id.reset(token)
