"""Serve data-plane errors.

Analog of the reference's ``python/ray/serve/exceptions.py``: typed errors
the router/engine raise so callers can distinguish "back off and retry"
(:class:`Saturated`) from a real failure.
"""

from __future__ import annotations


class Saturated(RuntimeError):
    """Admission control shed: every candidate replica's admission queue is
    over ``serve_admission_queue_limit`` (or this engine's ``max_queue``).

    Raised FAST — instead of queueing unboundedly — so the caller can apply
    its own backpressure (retry with jitter, shed upstream, scale out). The
    request was NOT started; retrying is always safe.
    """
