"""Serve data-plane errors.

Analog of the reference's ``python/ray/serve/exceptions.py``: typed errors
the router/engine raise so callers can distinguish "back off and retry"
(:class:`Saturated`) from a real failure.
"""

from __future__ import annotations

from typing import Optional


class Saturated(RuntimeError):
    """Admission control shed: the request was refused FAST instead of
    queueing unboundedly, so the caller can apply its own backpressure
    (retry with jitter, shed upstream, scale out). The request was NOT
    started; retrying is always safe.

    ``reason`` distinguishes the shed classes:

    - ``"saturated"`` — every candidate replica's admission queue is over
      ``serve_admission_queue_limit`` (or this engine's ``max_queue``).
    - ``"quota"`` — the request's tenant is over its per-tenant admission
      quota (``DeploymentConfig.tenant_quotas``); other tenants still have
      capacity.

    ``retry_after_s``, when set, is a backoff hint computed from the
    observed queue depth (how long the shedding queue likely needs to
    drain below the limit) — advisory, never a guarantee of admission.
    """

    def __init__(self, message: str = "", *, reason: str = "saturated",
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.reason = reason
        self.retry_after_s = retry_after_s

    def __reduce__(self):
        # Exception pickling replays only positional ``args``; the shed
        # class and backoff hint must survive the replica → client hop.
        return (_rebuild_saturated,
                (str(self), self.reason, self.retry_after_s))


def _rebuild_saturated(message, reason, retry_after_s) -> Saturated:
    return Saturated(message, reason=reason, retry_after_s=retry_after_s)
