"""SLO-driven serve autoscaling policy.

Analog of the reference's ``serve/_private/autoscaling_policy.py`` +
``autoscaling_state.py``, extended past ongoing-requests tracking into a
latency-objective control loop. The controller feeds each deployment's
:class:`SLOPolicy` a :class:`DeploymentSignals` snapshot built from the
replica ``get_state`` poll (ongoing / queue depth / engine slots / KV
blocks) plus the cluster metrics rollup's TTFT histogram, and the policy
returns the desired replica count.

Design properties the tests pin down:

- **Pure + injected time.** ``desired(current, sig, now)`` has no clocks or
  globals; unit tests drive it deterministically with synthetic timestamps.
- **Target tracking on max-pressure.** Pressure is the worst of the
  per-replica ratios (ongoing, queue depth, engine-slot / KV occupancy) vs
  their targets; desired = ceil(current * pressure), clamped to
  [min_replicas, max_replicas].
- **TTFT-violation override.** When the rollup p99 TTFT breaches
  ``ttft_p99_slo_s``, scale up by at least one replica even if utilization
  looks fine — latency is the objective, the ratios only its proxy.
- **Hysteresis + cooldown, no flapping.** A dead-band around pressure 1.0
  plus up/downscale delays: upscale waits ``upscale_delay_s`` since the
  last resize, downscale requires the low-pressure condition to HOLD for
  ``downscale_delay_s`` (a single quiet sample never kills a replica).
- **Scale-to-min on idle.** Fully idle for ``idle_timeout_s`` jumps
  straight to ``min_replicas`` instead of stepping down one at a time.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Dict, Optional

from ray_tpu.core.config import config
from ray_tpu.serve.config import AutoscalingConfig
from ray_tpu.util import flightrec
from ray_tpu.utils.logging import get_logger

__all__ = ["DeploymentSignals", "GangPreemption", "SLOPolicy", "TTFTRollup"]

logger = get_logger("serve_autoscaling")

# Serve's preemption class: placement groups created with a lower
# ``gang_priority`` (RL/Tune training gangs default to 0) may be revoked
# when a latency-SLO breach needs replica capacity the cluster can't place.
SERVE_GANG_PRIORITY = 100


@dataclass
class DeploymentSignals:
    """One deployment's load snapshot, as the controller sees it.

    ``ongoing`` is the handle-side EWMA of in-flight requests;
    ``queue_depth`` / ``slots_busy`` / ``slots_total`` / ``kv_*`` come from
    the replica ``get_state`` poll (engine ``stats()``); ``ttft_p99_s`` is
    the windowed cluster-rollup quantile (None when no traffic landed in
    the window or metrics are disabled).
    """

    replicas: int
    ongoing: float = 0.0
    queue_depth: float = 0.0
    slots_busy: float = 0.0
    slots_total: float = 0.0
    kv_active: float = 0.0
    kv_total: float = 0.0
    ttft_p99_s: Optional[float] = None

    def idle(self) -> bool:
        return (self.ongoing <= 0.0 and self.queue_depth <= 0.0
                and self.slots_busy <= 0.0)


class SLOPolicy:
    """Per-deployment scaling decision state machine (see module docs)."""

    def __init__(self, config: AutoscalingConfig):
        self.config = config
        self._last_resize_t: float = float("-inf")
        # When the downscale condition FIRST became continuously true;
        # None while pressure is normal/high.
        self._low_since: Optional[float] = None
        # When the deployment FIRST became continuously idle.
        self._idle_since: Optional[float] = None
        # Drain-by-migration pacing (cluster KV tier): retire at most ONE
        # replica per downscale decision so each victim gets a migration
        # target and the controller never drains two replicas into each
        # other. Set by the controller when the tier is on; the
        # downscale_delay_s cooldown then paces the steps.
        self.drain_single_step: bool = False

    # -- signal math ----------------------------------------------------------

    def pressure(self, sig: DeploymentSignals) -> float:
        """Worst per-replica load ratio vs its target. 1.0 = exactly at
        target; >1 wants more replicas, <1 wants fewer."""
        c = self.config
        n = max(1, sig.replicas)
        ratios = [sig.ongoing / (n * c.target_ongoing_requests)]
        if c.target_queue_depth > 0:
            ratios.append(sig.queue_depth / (n * c.target_queue_depth))
        if sig.slots_total > 0:
            ratios.append(
                (sig.slots_busy / sig.slots_total) / c.target_kv_utilization)
        if sig.kv_total > 0:
            ratios.append(
                (sig.kv_active / sig.kv_total) / c.target_kv_utilization)
        return max(ratios)

    def ttft_violated(self, sig: DeploymentSignals) -> bool:
        c = self.config
        return (c.ttft_p99_slo_s is not None
                and sig.ttft_p99_s is not None
                and sig.ttft_p99_s > c.ttft_p99_slo_s)

    # -- decision -------------------------------------------------------------

    def desired(self, current: int, sig: DeploymentSignals,
                now: Optional[float] = None) -> int:
        """Desired replica count for this evaluation. Stateful only in the
        cooldown/hold timers; everything else derives from ``sig``."""
        if now is None:
            now = time.monotonic()
        c = self.config
        lo, hi = c.min_replicas, c.max_replicas
        current = max(lo, min(hi, current))

        # Idle tracking: fully quiet for idle_timeout_s -> min_replicas.
        if sig.idle():
            if self._idle_since is None:
                self._idle_since = now
            if (now - self._idle_since >= c.idle_timeout_s
                    and current > lo):
                self._low_since = None
                self._last_resize_t = now
                if self.drain_single_step:
                    return max(lo, current - 1)
                return lo
        else:
            self._idle_since = None

        p = self.pressure(sig)
        violated = self.ttft_violated(sig)

        if p > 1.0 + c.hysteresis or violated:
            self._low_since = None
            if now - self._last_resize_t < c.upscale_delay_s:
                return current
            target = min(hi, max(1, math.ceil(current * p)))
            if violated:
                # Latency breach: grow by at least one even when the
                # utilization ratios sit inside the dead-band.
                target = max(target, current + 1)
            target = min(hi, target)
            if target > current:
                self._last_resize_t = now
                return target
            return current

        if p < 1.0 - c.hysteresis and current > lo:
            # Low pressure must HOLD for downscale_delay_s before a replica
            # is retired, and resizes themselves are rate-limited.
            if self._low_since is None:
                self._low_since = now
            held = now - self._low_since >= c.downscale_delay_s
            cooled = now - self._last_resize_t >= c.downscale_delay_s
            if held and cooled:
                target = max(lo, min(current, math.ceil(current * p)))
                if target == current:
                    target = current - 1
                if self.drain_single_step:
                    target = max(target, current - 1)
                target = max(lo, target)
                if target < current:
                    self._last_resize_t = now
                    self._low_since = now
                    return target
            return current

        # Dead-band: inside the hysteresis window, hold steady.
        self._low_since = None
        return current


class GangPreemption:
    """SLO-pressure capacity reclaim: when the policy wants replicas the
    cluster may not be able to place, revoke lower-class gangs through the
    control plane's block-revocation path (``preempt_gangs``).

    Pure decision state like :class:`SLOPolicy` — injected time, injected
    ``preempt`` callable (the runtime RPC in production, a stub in tests).
    Rate-limited per deployment so one sustained breach doesn't strip every
    training gang in the cluster on consecutive control ticks; gated by
    ``gang_preemption_enabled``.
    """

    def __init__(self, preempt, priority: int = SERVE_GANG_PRIORITY,
                 min_interval_s: float = 5.0):
        self.preempt = preempt  # (resources, count, min_priority) -> int
        self.priority = priority
        self.min_interval_s = min_interval_s
        self._last: Dict[str, float] = {}

    def maybe_reclaim(self, deployment: str, shape: Dict[str, float],
                      count: int, now: Optional[float] = None) -> int:
        if count <= 0 or self.preempt is None:
            return 0
        if not config().gang_preemption_enabled:
            return 0
        if now is None:
            now = time.monotonic()
        if now - self._last.get(deployment, float("-inf")) < self.min_interval_s:
            return 0
        self._last[deployment] = now
        try:
            n = int(self.preempt(dict(shape), int(count), self.priority))
        except Exception:  # noqa: BLE001 — reclaim is advisory, never fatal
            logger.exception("gang preemption call failed for %s", deployment)
            return 0
        if n:
            flightrec.record("serve", deployment,
                             f"gang.preempt reclaimed {n} gang(s) "
                             f"for {count} x {shape}")
            logger.warning(
                "SLO pressure on %s: preempted %d lower-priority gang(s) "
                "to place %d replica(s) of %s", deployment, n, count, shape)
        return n


class TTFTRollup:
    """Rate-limited, delta-windowed p99 reader over the cluster metrics
    rollup's cumulative TTFT histogram.

    The exporter ships CUMULATIVE bucket counts; a raw quantile over them
    answers "p99 since process start", which never recovers after one bad
    burst. This reader keeps the previous snapshot per deployment and
    computes the quantile over the bucket DELTAS — p99 of the last window
    only — re-reading the rollup at most every ``min_interval_s``.
    """

    def __init__(self, min_interval_s: float = 1.0):
        self.min_interval_s = min_interval_s
        # deployment -> (read_time, buckets, count)
        self._prev: Dict[str, tuple] = {}
        self._value: Dict[str, Optional[float]] = {}

    def p99(self, deployment: str,
            now: Optional[float] = None) -> Optional[float]:
        if now is None:
            now = time.monotonic()
        prev = self._prev.get(deployment)
        if prev is not None and now - prev[0] < self.min_interval_s:
            return self._value.get(deployment)

        from ray_tpu.core.metrics_export import cluster_histogram
        from ray_tpu.util.metrics import histogram_quantile

        snap = cluster_histogram(
            "ray_tpu_serve_ttft_s",
            {"deployment": deployment, "phase": "total"})
        if snap is None:
            self._prev[deployment] = (now, None, 0)
            self._value[deployment] = None
            return None

        buckets, count = list(snap["buckets"]), int(snap["count"])
        if prev is not None and prev[1] is not None \
                and len(prev[1]) == len(buckets) and count >= prev[2]:
            delta = [max(0, b - pb) for b, pb in zip(buckets, prev[1])]
        else:
            # First read (or exporter restart reset the counters): the
            # cumulative histogram IS the window.
            delta = buckets
        self._prev[deployment] = (now, buckets, count)
        self._value[deployment] = histogram_quantile(
            0.99, snap["bounds"], delta)
        return self._value[deployment]
