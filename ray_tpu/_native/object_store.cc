// Shared-memory object store — the plasma analog (reference:
// src/ray/object_manager/plasma/{store.cc,dlmalloc.cc}: a per-node
// shared-memory arena at /dev/shm so worker processes exchange large buffers
// zero-copy; reference mounts it the same way, raylet main.cc:84).
//
// Design: one POSIX shm segment = [StoreHeader | ObjectEntry table | data
// arena]. Allocation is first-fit over an in-shm free list with coalescing on
// free (the role dlmalloc plays in the reference, sized down to what a
// host-RAM object plane needs). All state lives IN the segment, guarded by a
// process-shared mutex, so any process that shm_open()s the segment is a
// full peer (create/seal/get/release/delete) with no daemon in the loop.
//
// C ABI only — consumed from Python via ctypes (no pybind11 in the image).

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <pthread.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x3252415954505553ULL;  // "SUPTYAR2" (v2 layout)
constexpr uint32_t kIdSize = 20;                  // ObjectID bytes (reference id.h)

// used: 0 = never occupied (ends a probe chain), 1 = live,
//       2 = tombstone (deleted; probes continue past, inserts may reuse)
struct ObjectEntry {
  uint8_t id[kIdSize];
  uint64_t offset;    // data offset from arena base
  uint64_t size;
  int64_t refcount;   // get/release pins; delete only when 0
  uint8_t sealed;     // visible to get() only when sealed
  uint8_t used;
};

struct FreeBlock {
  uint64_t offset;
  uint64_t size;
  int64_t next;  // index into free_blocks, -1 = end
};

struct StoreHeader {
  uint64_t magic;
  uint64_t capacity;        // arena bytes
  uint64_t arena_offset;    // from segment base
  uint32_t max_entries;
  uint32_t max_free_blocks;
  int64_t free_head;        // index into free block table
  uint64_t bytes_in_use;
  uint64_t num_objects;
  pthread_mutex_t mutex;
};

struct Store {
  int fd;
  void* base;
  uint64_t total_size;
  StoreHeader* hdr;
  ObjectEntry* entries;
  FreeBlock* free_blocks;
  uint8_t* arena;
};

uint64_t align8(uint64_t v) { return (v + 7) & ~7ULL; }

// The entry table is an open-addressed hash over the 20-byte id (linear
// probing). Typical find/insert is O(1) instead of the v1 linear scan of
// the whole table per op. Deletions leave tombstones that inserts reuse;
// there is deliberately NO compaction pass — rehashing in place would
// violate the crash-recovery invariant that the entry table is always a
// consistent source of truth (a peer dying mid-rehash with the mutex held
// would lose live entries). Worst case (every slot 1 or 2) degrades to the
// old full-scan behavior, never below it.
uint32_t id_hash(const uint8_t* id) {
  uint32_t h = 2166136261u;  // FNV-1a
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 16777619u;
  }
  return h;
}

ObjectEntry* find_entry(Store* s, const uint8_t* id) {
  const uint32_t max = s->hdr->max_entries;
  const uint32_t h = id_hash(id) % max;
  for (uint32_t k = 0; k < max; k++) {
    ObjectEntry* e = &s->entries[(h + k) % max];
    if (e->used == 0) return nullptr;  // end of probe chain
    if (e->used == 1 && memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return nullptr;
}

// Insert slot for a new id: first reusable slot (empty or tombstone) in the
// probe chain, provided the id is not already present. Null if the id
// exists or the table is full.
ObjectEntry* probe_insert(Store* s, const uint8_t* id) {
  const uint32_t max = s->hdr->max_entries;
  const uint32_t h = id_hash(id) % max;
  ObjectEntry* slot = nullptr;
  for (uint32_t k = 0; k < max; k++) {
    ObjectEntry* e = &s->entries[(h + k) % max];
    if (e->used == 1) {
      if (memcmp(e->id, id, kIdSize) == 0) return nullptr;  // exists
    } else {
      if (!slot) slot = e;
      if (e->used == 0) break;  // chain ends: id cannot exist beyond here
    }
  }
  return slot;
}

// First-fit allocation from the free list. Minimum allocation is 8 bytes so
// every object occupies a distinct arena range — zero-size objects would
// otherwise share an offset with their successor, which breaks crash
// recovery's entry-table walk (and offset-keyed invariants generally).
int64_t arena_alloc(Store* s, uint64_t size, uint64_t* out_offset);
int arena_free(Store* s, uint64_t offset, uint64_t size);

int64_t arena_alloc(Store* s, uint64_t size, uint64_t* out_offset) {
  size = align8(size ? size : 1);
  int64_t* prev_link = &s->hdr->free_head;
  int64_t idx = s->hdr->free_head;
  while (idx >= 0) {
    FreeBlock* b = &s->free_blocks[idx];
    if (b->size >= size) {
      *out_offset = b->offset;
      if (b->size == size) {
        *prev_link = b->next;
        b->size = 0;  // slot free for reuse
      } else {
        b->offset += size;
        b->size -= size;
      }
      s->hdr->bytes_in_use += size;
      return 0;
    }
    prev_link = &b->next;
    idx = b->next;
  }
  return -1;  // out of memory
}

// Returns 0 on success, -1 when the free-block table is exhausted — the
// caller must then rebuild_free_list() (the coalesced gap set between live
// entries always fits: gaps <= num_objects + 1 <= max_entries < max_free_
// blocks). No state is mutated on failure, so the rebuild sees a
// consistent entry table.
int arena_free(Store* s, uint64_t offset, uint64_t size) {
  size = align8(size ? size : 1);  // must mirror arena_alloc's minimum
  // walk the offset-sorted free list to the insertion point
  int64_t prev = -1;
  int64_t idx = s->hdr->free_head;
  while (idx >= 0 && s->free_blocks[idx].offset < offset) {
    prev = idx;
    idx = s->free_blocks[idx].next;
  }
  bool merge_next = (idx >= 0 && offset + size == s->free_blocks[idx].offset);
  bool merge_prev =
      (prev >= 0 && s->free_blocks[prev].offset + s->free_blocks[prev].size == offset);
  if (merge_prev && merge_next) {
    s->free_blocks[prev].size += size + s->free_blocks[idx].size;
    s->free_blocks[prev].next = s->free_blocks[idx].next;
    s->free_blocks[idx].size = 0;
    s->hdr->bytes_in_use -= size;
    return 0;
  }
  if (merge_prev) {
    s->free_blocks[prev].size += size;
    s->hdr->bytes_in_use -= size;
    return 0;
  }
  if (merge_next) {
    s->free_blocks[idx].offset = offset;
    s->free_blocks[idx].size += size;
    s->hdr->bytes_in_use -= size;
    return 0;
  }
  // new free block in the first empty slot
  for (uint32_t i = 0; i < s->hdr->max_free_blocks; i++) {
    if (s->free_blocks[i].size == 0) {
      s->free_blocks[i].offset = offset;
      s->free_blocks[i].size = size;
      s->free_blocks[i].next = idx;
      if (prev >= 0) {
        s->free_blocks[prev].next = i;
      } else {
        s->hdr->free_head = i;
      }
      s->hdr->bytes_in_use -= size;
      return 0;
    }
  }
  return -1;  // table exhausted; caller rebuilds from the entry table
}

// Rebuild all allocator metadata from the entry table. Used after a peer
// died mid-mutation (EOWNERDEAD): the free list may be half-spliced, but the
// entry table is the source of truth — every used entry's [offset, size) is
// live, everything else in the arena is free. Entries from a death between
// arena_alloc and `used = 1` are reclaimed (the object was never visible).
void rebuild_free_list(Store* s) {
  StoreHeader* h = s->hdr;
  // Selection-order walk over used entries by offset; O(n^2) but only runs
  // on the rare crash-recovery path (max_entries is a few thousand).
  memset(s->free_blocks, 0, sizeof(FreeBlock) * h->max_free_blocks);
  uint64_t prev_end = 0;
  uint64_t in_use = 0;
  uint64_t num_objects = 0;
  uint64_t last_offset = 0;
  int64_t last_index = -1;
  int64_t tail = -1;  // last free block written
  uint32_t slot = 0;
  h->free_head = -1;
  for (;;) {
    // Next used entry in (offset, table index) order — the index tiebreak
    // makes the walk robust even if two entries ever shared an offset.
    ObjectEntry* best = nullptr;
    int64_t best_index = -1;
    for (uint32_t i = 0; i < h->max_entries; i++) {
      ObjectEntry* e = &s->entries[i];
      if (e->used != 1) continue;
      if (e->offset < last_offset ||
          (e->offset == last_offset && (int64_t)i <= last_index)) {
        continue;
      }
      if (!best || e->offset < best->offset) {
        best = e;
        best_index = i;
      }
    }
    uint64_t gap_end = best ? best->offset : h->capacity;
    if (gap_end > prev_end && slot < h->max_free_blocks) {
      s->free_blocks[slot] = {prev_end, gap_end - prev_end, -1};
      if (tail >= 0) {
        s->free_blocks[tail].next = slot;
      } else {
        h->free_head = slot;
      }
      tail = slot;
      slot++;
    }
    if (!best) break;
    last_offset = best->offset;
    last_index = best_index;
    uint64_t span = align8(best->size ? best->size : 1);
    uint64_t end = best->offset + span;
    if (end > prev_end) prev_end = end;
    in_use += span;
    num_objects++;
  }
  h->bytes_in_use = in_use;
  h->num_objects = num_objects;
}

class Lock {
 public:
  explicit Lock(Store* s) : s_(s) {
    int rc = pthread_mutex_lock(&s_->hdr->mutex);
    if (rc == EOWNERDEAD) {
      // A peer died holding the lock; the robust mutex hands it to us in an
      // inconsistent state. The multi-step free-list splices in
      // arena_alloc/arena_free are NOT idempotent, so rebuild the allocator
      // metadata from the entry table (the source of truth) before marking
      // the mutex consistent.
      rebuild_free_list(s_);
      pthread_mutex_consistent(&s_->hdr->mutex);
    }
  }
  ~Lock() { pthread_mutex_unlock(&s_->hdr->mutex); }

 private:
  Store* s_;
};

}  // namespace

extern "C" {

// Create a new store segment. Returns handle or null.
void* rt_store_create(const char* name, uint64_t capacity, uint32_t max_entries) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;

  uint32_t max_free = max_entries * 2;
  uint64_t entries_off = align8(sizeof(StoreHeader));
  uint64_t free_off = align8(entries_off + sizeof(ObjectEntry) * max_entries);
  uint64_t arena_off = align8(free_off + sizeof(FreeBlock) * max_free);
  uint64_t total = arena_off + capacity;
  if (ftruncate(fd, total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }

  Store* s = new Store();
  s->fd = fd;
  s->base = base;
  s->total_size = total;
  s->hdr = static_cast<StoreHeader*>(base);
  s->entries = reinterpret_cast<ObjectEntry*>(static_cast<char*>(base) + entries_off);
  s->free_blocks = reinterpret_cast<FreeBlock*>(static_cast<char*>(base) + free_off);
  s->arena = reinterpret_cast<uint8_t*>(base) + arena_off;

  memset(s->hdr, 0, arena_off);
  s->hdr->magic = kMagic;
  s->hdr->capacity = capacity;
  s->hdr->arena_offset = arena_off;
  s->hdr->max_entries = max_entries;
  s->hdr->max_free_blocks = max_free;
  // one big free block
  s->free_blocks[0] = {0, capacity, -1};
  s->hdr->free_head = 0;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&s->hdr->mutex, &attr);
  return s;
}

// Open an existing segment (peer process).
void* rt_store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  StoreHeader* hdr = static_cast<StoreHeader*>(base);
  if (hdr->magic != kMagic) {
    munmap(base, st.st_size);
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->fd = fd;
  s->base = base;
  s->total_size = st.st_size;
  s->hdr = hdr;
  uint64_t entries_off = align8(sizeof(StoreHeader));
  uint64_t free_off = align8(entries_off + sizeof(ObjectEntry) * hdr->max_entries);
  s->entries = reinterpret_cast<ObjectEntry*>(static_cast<char*>(base) + entries_off);
  s->free_blocks = reinterpret_cast<FreeBlock*>(static_cast<char*>(base) + free_off);
  s->arena = reinterpret_cast<uint8_t*>(base) + hdr->arena_offset;
  return s;
}

// Allocate an object buffer (unsealed). Returns pointer to data or null.
// (reference: plasma Create — two-phase create/seal)
void* rt_store_create_object(void* handle, const uint8_t* id, uint64_t size) {
  Store* s = static_cast<Store*>(handle);
  uint64_t offset;
  {
    Lock lock(s);
    ObjectEntry* e = probe_insert(s, id);  // null: exists or table full
    if (!e) return nullptr;
    if (arena_alloc(s, size, &offset) != 0) return nullptr;
    memcpy(e->id, id, kIdSize);
    e->offset = offset;
    e->size = size;
    e->refcount = 1;  // creator holds a pin until seal+release
    e->sealed = 0;
    e->used = 1;
    s->hdr->num_objects++;
  }
  uint8_t* data = s->arena + offset;
  if (size >= (1u << 20)) {
    // Populate the extent's pages in one kernel walk instead of one minor
    // fault per 4 KiB during the producer's copy (~2x on fresh mappings).
    // Outside the store mutex: a multi-MB populate must not block peers.
    // Page-align the range; best-effort (older kernels: ENOSYS/EINVAL).
    uintptr_t lo = reinterpret_cast<uintptr_t>(data) & ~4095ULL;
    uintptr_t hi = reinterpret_cast<uintptr_t>(data) + size;
#ifdef MADV_POPULATE_WRITE
    madvise(reinterpret_cast<void*>(lo), hi - lo, MADV_POPULATE_WRITE);
#endif
  }
  return data;
}

int rt_store_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Lock lock(s);
  ObjectEntry* e = find_entry(s, id);
  if (!e) return -1;
  e->sealed = 1;
  return 0;
}

// Get a sealed object: returns data pointer, fills size; pins the object.
void* rt_store_get(void* handle, const uint8_t* id, uint64_t* size_out) {
  Store* s = static_cast<Store*>(handle);
  Lock lock(s);
  ObjectEntry* e = find_entry(s, id);
  if (!e || !e->sealed) return nullptr;
  e->refcount++;
  *size_out = e->size;
  return s->arena + e->offset;
}

int rt_store_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Lock lock(s);
  ObjectEntry* e = find_entry(s, id);
  if (!e) return -1;
  if (e->refcount > 0) e->refcount--;
  return 0;
}

int rt_store_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Lock lock(s);
  ObjectEntry* e = find_entry(s, id);
  return (e && e->sealed) ? 1 : 0;
}

// Delete when refcount==0 (reference: eviction only of unpinned objects).
int rt_store_delete(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Lock lock(s);
  ObjectEntry* e = find_entry(s, id);
  if (!e) return -1;
  if (e->refcount > 0) return -2;  // pinned
  uint64_t off = e->offset, sz = e->size;
  e->used = 2;  // tombstone BEFORE freeing: a crash here loses no space
  s->hdr->num_objects--;
  if (arena_free(s, off, sz) != 0) {
    // Free-block table exhausted (v1 silently leaked here): rebuild the
    // whole allocator from the entry table — the coalesced gap set always
    // fits, and the rebuild also recomputes bytes_in_use/num_objects.
    rebuild_free_list(s);
  }
  return 0;
}

uint64_t rt_store_bytes_in_use(void* handle) {
  Store* s = static_cast<Store*>(handle);
  Lock lock(s);
  return s->hdr->bytes_in_use;
}

uint64_t rt_store_num_objects(void* handle) {
  Store* s = static_cast<Store*>(handle);
  Lock lock(s);
  return s->hdr->num_objects;
}

uint64_t rt_store_capacity(void* handle) {
  Store* s = static_cast<Store*>(handle);
  return s->hdr->capacity;
}

void rt_store_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  munmap(s->base, s->total_size);
  close(s->fd);
  delete s;
}

int rt_store_destroy(const char* name) { return shm_unlink(name); }

// Pre-fault the arena's pages so first puts don't pay kernel page
// population at transfer time (observed ~10x write slowdown on fresh shm
// pages under memory ballooning). Content-preserving: an atomic |= 0
// dirties each page without changing bytes, so it is safe to run while
// objects are live. chunk_bytes per burst, sleep_us between bursts keeps
// it off the critical path on small machines.
void rt_store_prefault(void* handle, uint64_t chunk_bytes, uint32_t sleep_us,
                       uint64_t max_bytes) {
  Store* s = static_cast<Store*>(handle);
  const uint64_t kPage = 4096;
  uint64_t cap = s->hdr->capacity;
  if (max_bytes && max_bytes < cap) cap = max_bytes;
  // Drop this (dedicated) thread to SCHED_IDLE: page population is pure
  // opportunistic background work, and on small hosts an arena-sized fault
  // storm at normal priority starves the very puts it exists to speed up
  // (observed: boot prefault of 4 co-hosted daemons stretching a 1.1s walk
  // into minutes on one core while bench puts ran at 1/30th speed).
  struct sched_param sp = {};
  pthread_setschedparam(pthread_self(), SCHED_IDLE, &sp);
  volatile uint8_t* base = reinterpret_cast<volatile uint8_t*>(s->arena);
  for (uint64_t off = 0; off < cap; off += chunk_bytes) {
    uint64_t end = off + chunk_bytes < cap ? off + chunk_bytes : cap;
    for (uint64_t p = off; p < end; p += kPage) {
      __atomic_fetch_or(const_cast<uint8_t*>(base + p), 0, __ATOMIC_RELAXED);
    }
    if (sleep_us) usleep(sleep_us);
  }
}

// -- test hook (crash-recovery tests) ---------------------------------------
// Simulates a peer dying mid-splice: acquires the mutex, trashes the
// allocator metadata, and returns WITHOUT unlocking. The caller then exits,
// so the next locker observes EOWNERDEAD with inconsistent metadata and must
// rebuild from the entry table. Compiled ONLY into the test library
// (libray_tpu_store_test.so) — never exported from the production .so.
#ifdef RT_STORE_TEST_HOOKS
int rt_store_test_corrupt_and_hold(void* handle) {
  Store* s = static_cast<Store*>(handle);
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    rebuild_free_list(s);
    pthread_mutex_consistent(&s->hdr->mutex);
  }
  s->hdr->free_head = -1;  // dangling: no free space reachable
  s->hdr->bytes_in_use = s->hdr->capacity;
  s->hdr->num_objects += 17;
  return 0;
}
#endif  // RT_STORE_TEST_HOOKS

}  // extern "C"
