// Sanitizer stress harness for the shm object store (the reference runs
// its stores under asan/tsan in CI — ci/ray_ci/tester.py:137-144; this is
// that job for the plasma analog). N threads hammer one arena with
// create/write/seal/get/release/delete cycles plus random aborts, so the
// open-addressed entry table, free-list splices, tombstone reuse, and the
// rebuild path all run under the sanitizer. Exit 0 = clean.
//
// Build: make asan (or tsan); run ./stress_store_asan [seconds]

#include "object_store.cc"

#include <atomic>
#include <cstdlib>
#include <ctime>
#include <thread>
#include <vector>

namespace {

std::atomic<uint64_t> g_ops{0};
std::atomic<bool> g_stop{false};

void worker(void* store, unsigned seed) {
  unsigned state = seed;
  auto rnd = [&state]() {
    state = state * 1103515245u + 12345u;
    return state >> 16;
  };
  while (!g_stop.load(std::memory_order_relaxed)) {
    uint8_t id[20];
    for (int i = 0; i < 20; i++) id[i] = static_cast<uint8_t>(rnd());
    uint64_t size = 64 + rnd() % 65536;
    void* data = rt_store_create_object(store, id, size);
    if (data == nullptr) continue;  // full / collision
    if (rnd() % 8 == 0) {
      // abandoned create (abort path): release + delete unsealed
      rt_store_release(store, id);
      rt_store_delete(store, id);
      continue;
    }
    memset(data, static_cast<int>(rnd() % 251), size);
    rt_store_seal(store, id);
    rt_store_release(store, id);
    uint64_t got = 0;
    void* back = rt_store_get(store, id, &got);
    if (back != nullptr) {
      volatile uint8_t sink = static_cast<uint8_t*>(back)[got - 1];
      (void)sink;
      rt_store_release(store, id);
    }
    rt_store_delete(store, id);
    g_ops.fetch_add(1, std::memory_order_relaxed);
  }
}

}  // namespace

int main(int argc, char** argv) {
  int seconds = argc > 1 ? atoi(argv[1]) : 5;
  const char* name = "/rtpu-stress";
  rt_store_destroy(name);
  void* store = rt_store_create(name, 64ull * 1024 * 1024, 512);
  if (store == nullptr) {
    fprintf(stderr, "create failed\n");
    return 1;
  }
  std::vector<std::thread> threads;
  for (int i = 0; i < 8; i++) {
    threads.emplace_back(worker, store, 0x9e3779b9u * (i + 1));
  }
  struct timespec ts = {seconds, 0};
  nanosleep(&ts, nullptr);
  g_stop.store(true);
  for (auto& t : threads) t.join();
  uint64_t in_use = rt_store_bytes_in_use(store);
  uint64_t objects = rt_store_num_objects(store);
  printf("ops=%llu leftover_objects=%llu bytes_in_use=%llu\n",
         static_cast<unsigned long long>(g_ops.load()),
         static_cast<unsigned long long>(objects),
         static_cast<unsigned long long>(in_use));
  rt_store_close(store);
  rt_store_destroy(name);
  // Every thread deletes what it created: a leak here is an allocator bug.
  return (objects == 0 && in_use == 0) ? 0 : 2;
}
