"""APPO — asynchronous PPO on the IMPALA machinery.

Analog of the reference's ``rllib/algorithms/appo/appo.py`` (which
subclasses IMPALA exactly this way): the async sample/aggregate/update
pipeline, v-trace off-policy correction, and learner-group path all come
from :class:`IMPALA`; the policy update swaps the plain policy gradient
for PPO's CLIPPED SURROGATE over the v-trace advantages — stable learning
at higher sample staleness than raw IMPALA tolerates.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict

import jax.numpy as jnp

from ray_tpu.rllib.impala import IMPALA, ImpalaConfig, ImpalaLearner


class APPOLearner(ImpalaLearner):
    """V-trace targets + PPO clipped surrogate (appo_torch_policy's loss)."""

    def _pg_loss(self, logp, behavior_logp, adv, w):
        clip = self.config.get("clip_param", 0.2)
        ratio = jnp.exp(logp - behavior_logp)
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        return -jnp.sum(surr * w)


@dataclass
class APPOConfig(ImpalaConfig):
    clip_param: float = 0.2

    def build(self) -> "APPO":
        return APPO(self)


class APPO(IMPALA):
    _LEARNER_CLS = APPOLearner

    def _learner_config(self, config) -> Dict[str, Any]:
        cfg = super()._learner_config(config)
        cfg["clip_param"] = config.clip_param
        return cfg
