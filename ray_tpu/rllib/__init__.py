"""ray_tpu.rllib — reinforcement learning on the new-stack shape.

Public surface mirrors the reference's new API stack (SURVEY §2.3: RLModule /
Learner / LearnerGroup / EnvRunner; old Policy/RolloutWorker stack explicitly
not ported — SURVEY §7 "do NOT port").
"""

from ray_tpu.rllib.appo import APPO, APPOConfig, APPOLearner
from ray_tpu.rllib.cql import CQL, CQLConfig, CQLLearner
from ray_tpu.rllib.dqn import DQN, DQNConfig, DQNLearner, ReplayBuffer
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.envs import SyntheticAtariEnv, make_atari
from ray_tpu.rllib.impala import IMPALA, AggregatorActor, ImpalaConfig, ImpalaLearner, vtrace
from ray_tpu.rllib.inference import InferenceActor, InferencePool
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.llm_rl import LLMRL, LLMRLConfig, LLMRLLearner
from ray_tpu.rllib.rollout_lanes import RolloutLanes
from ray_tpu.rllib.multi_agent import (
    MultiAgentEnvRunner,
    MultiAgentPPO,
    MultiAgentPPOConfig,
)
from ray_tpu.rllib.offline import BC, MARWIL, BCConfig, MARWILConfig, episodes_to_dataset
from ray_tpu.rllib.ppo import PPO, PPOConfig, PPOLearner, compute_gae
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, nstep_columns
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec, spec_for_env
from ray_tpu.rllib.sac import SAC, SACConfig, SACLearner, SACModule

__all__ = [
    "RLModule",
    "RLModuleSpec",
    "spec_for_env",
    "SingleAgentEnvRunner",
    "SyntheticAtariEnv",
    "make_atari",
    "Learner",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "PPOLearner",
    "compute_gae",
    "IMPALA",
    "ImpalaConfig",
    "ImpalaLearner",
    "AggregatorActor",
    "vtrace",
    "DQN",
    "DQNConfig",
    "DQNLearner",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "nstep_columns",
    "SAC",
    "SACConfig",
    "SACLearner",
    "SACModule",
    "BC",
    "MARWIL",
    "BCConfig",
    "MARWILConfig",
    "episodes_to_dataset",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "APPO",
    "APPOConfig",
    "APPOLearner",
    "InferenceActor",
    "InferencePool",
    "RolloutLanes",
    "LLMRL",
    "LLMRLConfig",
    "LLMRLLearner",
    "CQL",
    "CQLConfig",
    "CQLLearner",
]
