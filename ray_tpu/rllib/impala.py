"""IMPALA — asynchronous actor-learner with V-trace off-policy correction.

Analog of the reference's ``rllib/algorithms/impala/impala.py`` (async
``training_step`` :620-667 with aggregator workers and in-flight request
management). The shape:

- EnvRunner actors sample continuously under a (slightly stale) policy; the
  driver keeps ``max_requests_in_flight`` sample calls outstanding per
  runner and consumes whichever finishes first (``ray_tpu.wait``).
- Optional **aggregator actors** (``impala.py:620-630``) concatenate several
  rollout fragments into one learner-sized batch off the driver's critical
  path — fragments travel by ObjectRef, so pixel batches ride the shm object
  plane, not the driver.
- The Learner applies **V-trace** (Espeholt et al. 2018): importance-clipped
  off-policy returns computed INSIDE the jitted loss with the current
  policy's log-probs, exactly as the reference's torch learner does.
- Weights broadcast to runners every ``broadcast_interval`` updates (the
  staleness knob that buys the async throughput).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.core.config import config as _get_config
from ray_tpu.core.exceptions import ActorError
from ray_tpu.rllib.algorithm_config import AlgorithmConfigBase
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import spec_for_env
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger(__name__)


def vtrace(
    behavior_logp,   # [T, N]
    target_logp,     # [T, N]
    rewards,         # [T, N]
    values,          # [T, N]  V(x_t) under the CURRENT policy's critic
    bootstrap_value,  # [N]    V(x_T)
    terminateds,     # [T, N]  1.0 where the episode truly ended at t
    *,
    gamma: float,
    rho_bar: float = 1.0,
    c_bar: float = 1.0,
    valids=None,     # [T, N]  0 on autoreset (junk) steps — see compute_gae
):
    """V-trace targets vs_t and policy-gradient advantages (jax, scan).

    vs_t = V_t + Σ_{k≥t} γ^{k-t} (Π_{i<k} c_i) δ_k with clipped importance
    weights ρ, c (Espeholt et al. 2018 eq. 1); computed right-to-left via
    ``lax.scan``. Discounts are cut at terminations. ``valids`` zeros the
    accumulator at autoreset steps (same trick as ``compute_gae``): the
    junk step's vs collapses to V_t, so the PRECEDING step's delta
    bootstraps through V(final obs) — the truncation bootstrap — and
    nothing leaks across the episode boundary.
    """
    rho = jnp.minimum(rho_bar, jnp.exp(target_logp - behavior_logp))
    c = jnp.minimum(c_bar, jnp.exp(target_logp - behavior_logp))
    discount = gamma * (1.0 - terminateds)
    next_values = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)    # [T, N]
    deltas = rho * (rewards + discount * next_values - values)
    if valids is None:
        valids = jnp.ones_like(rewards)

    def backward(acc, xs):
        delta_t, disc_t, c_t, valid_t = xs
        acc = (delta_t + disc_t * c_t * acc) * valid_t
        return acc, acc

    _, vs_minus_v = jax.lax.scan(
        backward, jnp.zeros_like(bootstrap_value),
        (deltas, discount, c, valids), reverse=True)
    vs = vs_minus_v + values
    # PG advantage uses vs_{t+1} (bootstrap for the final step).
    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho * (rewards + discount * vs_next - values)
    return vs, pg_adv


class ImpalaLearner(Learner):
    """V-trace actor-critic loss over [T, N] trajectory batches."""

    def loss_fn(self, params, batch):
        cfg = self.config
        T, N = batch["rewards"].shape
        obs = batch["obs"].reshape((T * N,) + batch["obs"].shape[2:])
        actions = batch["actions"].reshape((T * N,) + batch["actions"].shape[2:])
        logp, entropy, values = self.module.logp_and_entropy(
            params, obs, actions)
        logp = logp.reshape(T, N)
        values = values.reshape(T, N)
        entropy = entropy.reshape(T, N)
        bootstrap = self.module.forward_train(
            params, batch["bootstrap_obs"])["vf_preds"]
        valids = batch.get("valids")
        if valids is None:
            valids = jnp.ones_like(logp)
        vs, pg_adv = vtrace(
            batch["logp"], logp, batch["rewards"],
            jax.lax.stop_gradient(values),
            jax.lax.stop_gradient(bootstrap),
            batch["terminateds"],
            gamma=cfg["gamma"],
            rho_bar=cfg.get("rho_bar", 1.0),
            c_bar=cfg.get("c_bar", 1.0),
            valids=valids,
        )
        w = valids / jnp.maximum(valids.sum(), 1.0)
        pg_loss = self._pg_loss(logp, batch["logp"],
                                jax.lax.stop_gradient(pg_adv), w)
        vf_loss = 0.5 * jnp.sum((values - jax.lax.stop_gradient(vs)) ** 2 * w)
        ent = jnp.sum(entropy * w)
        return (pg_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                - cfg.get("entropy_coeff", 0.01) * ent)

    def _pg_loss(self, logp, behavior_logp, adv, w):
        """Plain policy gradient over the v-trace advantages; APPO swaps
        in the clipped surrogate."""
        return -jnp.sum(logp * adv * w)


class AggregatorActor:
    """Concatenates rollout fragments into learner batches
    (reference: ``impala.py:620-630`` AggregatorWorker)."""

    def aggregate(self, *fragments: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for key in fragments[0]:
            if key == "bootstrap_value":
                out[key] = np.concatenate([f[key] for f in fragments], axis=0)
            elif key == "bootstrap_obs":
                out[key] = np.concatenate([f[key] for f in fragments], axis=0)
            else:
                # [T, N, ...] fragments concat on the env axis.
                out[key] = np.concatenate([f[key] for f in fragments], axis=1)
        return out


@dataclass
class ImpalaConfig(AlgorithmConfigBase):
    env: Optional[Callable[[], Any]] = None
    num_env_runners: int = 2
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 64
    num_aggregators: int = 0
    # >0: updates run on a LearnerGroup of remote learner actors with
    # ring-allreduced gradients (reference: impala.py:667 drives its
    # learner group) instead of a driver-local learner.
    num_learners: int = 0
    max_requests_in_flight: int = 2
    broadcast_interval: int = 1          # updates between weight broadcasts
    train_batch_fragments: int = 1       # fragments per learner update
    # Sebulba split (rllib/inference.py): >0 moves action selection into a
    # shared pool of this many batching InferenceActors; 0 keeps
    # runner-local params (the Anakin/colocated mode).
    num_inference_actors: int = 0
    # Rollout transport: None defers to the rollout_lanes_enabled system
    # flag; True/False force the compiled-DAG lane / task path per-algo.
    rollout_lanes: Optional[bool] = None
    # Ticks kept in flight on the lane (the max_requests_in_flight analog;
    # also the weight-broadcast staleness in lane mode).
    lane_depth: int = 2
    # Bound on waiting for any one fragment (task-path wait and lane fetch)
    # before the driver declares the sampler lost.
    sample_timeout_s: float = 120.0
    gamma: float = 0.99
    lr: float = 5e-4
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    rho_bar: float = 1.0
    c_bar: float = 1.0
    grad_clip: float = 40.0
    seed: int = 0
    hidden: Optional[tuple] = None

    def build(self) -> "IMPALA":
        return IMPALA(self)


class IMPALA:
    """Async actor-learner algorithm (Tune-compatible train() contract)."""

    # Subclasses (APPO) swap the learner while reusing the async
    # sample/aggregate/update machinery (the reference's APPO subclasses
    # IMPALA the same way, rllib/algorithms/appo/appo.py).
    _LEARNER_CLS = ImpalaLearner

    def _learner_config(self, config) -> Dict[str, Any]:
        return {
            "lr": config.lr, "gamma": config.gamma,
            "vf_loss_coeff": config.vf_loss_coeff,
            "entropy_coeff": config.entropy_coeff,
            "rho_bar": config.rho_bar, "c_bar": config.c_bar,
            "grad_clip": config.grad_clip,
        }

    def __init__(self, config: ImpalaConfig):
        assert config.env is not None, "config.environment(env_creator) required"
        self.config = config
        probe = config.env()
        self.spec = spec_for_env(probe)
        if config.hidden and not self.spec.conv:
            import dataclasses

            self.spec = dataclasses.replace(self.spec,
                                            hidden=tuple(config.hidden))
        probe.close()

        learner_cfg = self._learner_config(config)
        if config.num_learners > 0:
            import uuid

            from ray_tpu.rllib.learner import LearnerGroup

            # [T, N] trajectory columns shard on the ENV axis so each
            # learner sees whole time series; [N, ...] bootstrap rows on 0.
            self.learner = LearnerGroup(
                type(self)._LEARNER_CLS, self.spec, learner_cfg,
                num_learners=config.num_learners,
                group_name=f"impala-learners-{uuid.uuid4().hex[:8]}",
                seed=config.seed,
                shard_axes={"obs": 1, "actions": 1, "logp": 1, "values": 1,
                            "rewards": 1, "terminateds": 1, "valids": 1,
                            "bootstrap_obs": 0, "bootstrap_value": 0},
            )
        else:
            self.learner = type(self)._LEARNER_CLS(self.spec, learner_cfg,
                                                   seed=config.seed)

        flags = _get_config()
        self._use_lanes = (bool(flags.rollout_lanes_enabled)
                           if config.rollout_lanes is None
                           else bool(config.rollout_lanes))
        if config.num_inference_actors > 0:
            from ray_tpu.rllib.inference import InferencePool

            self._pool = InferencePool(
                config.num_inference_actors, self.spec, seed=config.seed,
                num_clients=max(1, config.num_env_runners))
        else:
            self._pool = None
        self._runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self._runners = [self._make_runner(i)
                         for i in range(max(1, config.num_env_runners))]
        self._lanes = None  # built on first lane-mode train()
        self._pending_weights = None  # lane mode: ride the next tick payload
        if config.num_aggregators > 0:
            agg_cls = ray_tpu.remote(AggregatorActor)
            self._aggregators = [agg_cls.remote()
                                 for _ in range(config.num_aggregators)]
        else:
            self._aggregators = []
        self._agg_rr = 0
        self._inflight: Dict[Any, Any] = {}  # sample ref -> runner
        self._pending_frags: List[Any] = []  # carried across train() calls
        self._updates = 0
        self._iteration = 0
        self._timesteps = 0
        self._broadcast()

    def _make_runner(self, i: int):
        kwargs: Dict[str, Any] = dict(
            num_envs=self.config.num_envs_per_runner,
            seed=self.config.seed + 1000 * i, spec=self.spec)
        if self._pool is not None:
            kwargs["inference"] = self._pool.handle_for(i)
        return self._runner_cls.remote(self.config.env, **kwargs)

    def _broadcast(self):
        weights = self.learner.get_weights()
        if self._pool is not None:
            # Sebulba: K inference actors hold the only sampling params —
            # the broadcast never touches the N runners.
            self._pool.set_weights(weights)
        elif self._lanes is not None:
            # Lane-parked runners can't serve set_weights calls; the
            # weights ride the next tick's input payload instead.
            self._pending_weights = weights
        else:
            # Per-runner ack: one dead runner must not abort the whole
            # broadcast (its respawn reloads current weights anyway).
            refs = [(r, r.set_weights.remote(weights))
                    for r in list(self._runners)]
            for runner, ref in refs:
                try:
                    ray_tpu.get(ref)
                except ActorError:
                    self._respawn_runner(runner)

    def _launch(self, runner):
        ref = runner.sample.remote(self.config.rollout_fragment_length)
        self._inflight[ref] = runner

    def _respawn_runner(self, runner):
        """A runner died (ActorError from sample/get_metrics): replace it
        in place with current weights and relaunch its in-flight quota so
        training continues at full sampling width."""
        i = self._runners.index(runner)
        logger.warning("env runner %d died; respawning", i)
        for ref in [r for r, w in list(self._inflight.items())
                    if w is runner]:
            del self._inflight[ref]
        new = self._make_runner(i)
        if self._pool is None:
            ray_tpu.get(new.set_weights.remote(self.learner.get_weights()))
        self._runners[i] = new
        if not self._use_lanes:
            for _ in range(self.config.max_requests_in_flight):
                self._launch(new)
        return new

    # -- lane mode -----------------------------------------------------------
    def _ensure_lanes(self):
        if self._lanes is None:
            from ray_tpu.rllib.rollout_lanes import RolloutLanes

            self._lanes = RolloutLanes(
                self._runners, self.config.rollout_fragment_length,
                depth=self.config.lane_depth,
                execute_timeout_s=self.config.sample_timeout_s)
        return self._lanes

    def _recover_lanes(self, err: BaseException) -> None:
        """A lane tick failed (stage error or a dead runner starving the
        gather): tear the lane down, respawn whoever doesn't answer a ping,
        and let the next tick rebuild it."""
        logger.warning("rollout lane failed (%s); rebuilding", err)
        try:
            self._lanes.teardown()
        except Exception:  # noqa: BLE001
            log_swallowed(logger, "rollout lane teardown")
        self._lanes = None
        for runner in list(self._runners):
            try:
                ray_tpu.get(runner.ping.remote(), timeout=10.0)
            except Exception:  # noqa: BLE001 — dead or wedged either way
                self._respawn_runner(runner)

    def _to_train_batch(self, sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        batch = dict(sample)
        # V-trace bootstraps through V(x_T) of the CURRENT policy — ship the
        # final obs, drop the runner's stale value estimate.
        batch.pop("bootstrap_value", None)
        return batch

    def _observe_idle(self, idle: float) -> None:
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 rl_learner_idle_hist)

        if metrics_enabled():
            rl_learner_idle_hist().observe(idle)

    def train(self) -> Dict[str, Any]:
        """One iteration: consume ``num_env_runners`` fragments worth of
        experience asynchronously, updating as results land."""
        cfg = self.config
        t0 = time.perf_counter()
        target_fragments = max(len(self._runners), cfg.train_batch_fragments)
        if self._iteration >= 1:
            # Iteration 1 compiled every program (update fn, broadcast
            # fetch); from here on the driver-side loop is steady state —
            # a new XLA compile or an implicit device->host read is a
            # regression (recorded when jitcheck is installed).
            from ray_tpu.devtools import jitcheck

            with jitcheck.steady_state():
                if self._use_lanes:
                    stats = self._train_lanes(target_fragments)
                else:
                    stats = self._train_tasks(target_fragments)
        elif self._use_lanes:
            stats = self._train_lanes(target_fragments)
        else:
            stats = self._train_tasks(target_fragments)
        sampled_steps, losses, returns, idle_s = stats

        self._timesteps += sampled_steps
        self._iteration += 1
        dt = time.perf_counter() - t0
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 rl_env_steps_total)

        if metrics_enabled():
            rl_env_steps_total().inc(sampled_steps)
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps,
            "episode_return_mean": float(np.mean(returns)) if returns else float("nan"),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "env_steps_per_sec": sampled_steps / dt,
            "num_updates": self._updates,
            "learner_idle_s": idle_s,
            "time_total_s": dt,
        }

    def _update_from_fragments(self, frags: List[Dict[str, np.ndarray]]):
        """One learner step from materialized fragments (lane mode and the
        driver-side task-path fallback share this)."""
        batch = (self._to_train_batch(AggregatorActor().aggregate(*frags))
                 if len(frags) > 1 else self._to_train_batch(dict(frags[0])))
        loss = self.learner.update(batch)["loss"]
        self._updates += 1
        if self._updates % self.config.broadcast_interval == 0:
            self._broadcast()
        return loss

    def _train_tasks(self, target_fragments: int):
        """The per-fragment task path (``rollout_lanes_enabled=0``): keep
        ``max_requests_in_flight`` sample calls outstanding per runner and
        consume whichever lands first via ``ray_tpu.wait``."""
        cfg = self.config
        for runner in self._runners:
            while sum(1 for r, w in self._inflight.items() if w is runner) \
                    < cfg.max_requests_in_flight:
                self._launch(runner)

        consumed = 0
        losses = []
        sampled_steps = 0
        idle_s = 0.0
        # Every fragment trains exactly once: leftovers persist on self so
        # aggregation never discards experience, and the loop runs until at
        # least one update landed (fragment targets not divisible by
        # train_batch_fragments would otherwise yield loss=nan iterations).
        while consumed < target_fragments or not losses:
            w0 = time.perf_counter()
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=cfg.sample_timeout_s)
            idle = time.perf_counter() - w0
            idle_s += idle
            self._observe_idle(idle)
            if not ready:
                raise TimeoutError(
                    f"no sample fragment arrived in {cfg.sample_timeout_s}s")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            try:
                # Probe before counting: a runner death surfaces here (and
                # the object-store get is a cache hit for the batch below).
                ray_tpu.get(ref)
            except ActorError:
                self._respawn_runner(runner)
                continue
            self._launch(runner)  # keep the pipeline full
            consumed += 1
            T, N = cfg.rollout_fragment_length, cfg.num_envs_per_runner
            sampled_steps += T * N
            if cfg.train_batch_fragments > 1:
                self._pending_frags.append(ref)
                if len(self._pending_frags) < cfg.train_batch_fragments:
                    continue
                if self._aggregators:
                    agg = self._aggregators[self._agg_rr % len(self._aggregators)]
                    self._agg_rr += 1
                    batch_ref = agg.aggregate.remote(*self._pending_frags)
                    batch = self._to_train_batch(ray_tpu.get(batch_ref))
                else:
                    # No aggregator actors: concatenate on the driver so the
                    # configured batch size still holds.
                    frags = ray_tpu.get(self._pending_frags)
                    batch = self._to_train_batch(
                        AggregatorActor().aggregate(*frags))
                self._pending_frags = []
            else:
                batch = self._to_train_batch(ray_tpu.get(ref))
            losses.append(self.learner.update(batch)["loss"])
            self._updates += 1
            if self._updates % cfg.broadcast_interval == 0:
                self._broadcast()

        returns = []
        metric_refs = [(r, r.get_metrics.remote()) for r in list(self._runners)]
        for runner, mref in metric_refs:
            try:
                m = ray_tpu.get(mref, timeout=cfg.sample_timeout_s)
            except ActorError:
                self._respawn_runner(runner)
                continue
            if m["num_episodes"] > 0:
                returns.append(m["episode_return_mean"])
        return sampled_steps, losses, returns, idle_s

    def _train_lanes(self, target_fragments: int):
        """The compiled-DAG lane path: fragments stream over multi-slot shm
        channels, gathered a full tick (one fragment per runner) at a time.
        Episode metrics ride each fragment; weight broadcasts ride the next
        tick's payload."""
        cfg = self.config
        consumed = 0
        losses = []
        sampled_steps = 0
        idle_s = 0.0
        returns = []
        while consumed < target_fragments or not losses:
            lanes = self._ensure_lanes()
            w0 = time.perf_counter()
            try:
                weights, self._pending_weights = self._pending_weights, None
                lanes.fill(weights)
                frags = lanes.next(timeout=cfg.sample_timeout_s)
            except Exception as err:  # noqa: BLE001 — lane fetch/stage loss
                self._recover_lanes(err)
                continue
            idle = time.perf_counter() - w0
            idle_s += idle
            self._observe_idle(idle)
            for frag in frags:
                frag = dict(frag)
                m = frag.pop("metrics", None)
                if m and m.get("num_episodes", 0) > 0:
                    returns.append(m["episode_return_mean"])
                consumed += 1
                sampled_steps += (cfg.rollout_fragment_length
                                  * cfg.num_envs_per_runner)
                # Leftovers persist across ticks/iterations so aggregation
                # never discards experience (same contract as the task path).
                self._pending_frags.append(frag)
                if len(self._pending_frags) >= max(1, cfg.train_batch_fragments):
                    pend, self._pending_frags = self._pending_frags, []
                    losses.append(self._update_from_fragments(pend))
        return sampled_steps, losses, returns, idle_s

    def save(self, path: str) -> str:
        from ray_tpu.train.checkpoint import save_pytree

        save_pytree({"params": self.learner.get_state()["params"],
                     "iteration": self._iteration,
                     "timesteps": self._timesteps}, path)
        return path

    def restore(self, path: str) -> None:
        from ray_tpu.train.checkpoint import load_pytree

        data = load_pytree(path)
        state = self.learner.get_state()
        state["params"] = data["params"]
        self.learner.set_state(state)
        self._iteration = int(data["iteration"])
        self._timesteps = int(data["timesteps"])
        self._broadcast()

    def stop(self) -> None:
        self._inflight.clear()
        if self._lanes is not None:
            try:
                self._lanes.teardown()
            except Exception:  # noqa: BLE001
                log_swallowed(logger, "rollout lane teardown")
            self._lanes = None
        if hasattr(self.learner, "shutdown"):
            self.learner.shutdown()
        if self._pool is not None:
            self._pool.stop()
        for r in self._runners + self._aggregators:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001 — already-dead actor at teardown
                log_swallowed(logger, "actor kill during IMPALA.stop")
