"""RLModule — the policy/value network abstraction (JAX).

Analog of the reference's new-stack ``rllib/core/rl_module/rl_module.py``:
an RLModule owns the network and exposes ``forward_inference`` /
``forward_exploration`` / ``forward_train``. The JAX implementation keeps
params as an explicit pytree (functional — the module is stateless math, the
Learner owns the params), so the same module runs in env-runner actors (CPU,
small batch) and learners (TPU mesh, big batch) without code changes.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class RLModuleSpec:
    """Reference: ``rl_module.RLModuleSpec`` — how to build a module."""

    observation_dim: int
    action_dim: int
    hidden: Tuple[int, ...] = (64, 64)
    discrete: bool = True
    free_log_std: bool = True  # Box spaces: state-independent log-std
    # Pixel observations: raw [H, W, C] shape + a Nature-CNN torso
    # (reference: rllib/models/catalog defaults for Atari).
    obs_shape: Optional[Tuple[int, ...]] = None
    conv: bool = False


class RLModule:
    """Functional actor-critic MLP."""

    def __init__(self, spec: RLModuleSpec):
        self.spec = spec

    # Nature-CNN filter spec: (out_channels, kernel, stride) per layer
    _CONV_LAYERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))

    # -- params --------------------------------------------------------------
    def init_params(self, key: jax.Array) -> Dict:
        s = self.spec
        if s.conv:
            return self._init_conv_params(key)
        dims = (s.observation_dim,) + s.hidden
        keys = jax.random.split(key, len(dims) + 2)
        torso = []
        for i, (a, b) in enumerate(zip(dims[:-1], dims[1:])):
            w = jax.random.normal(keys[i], (a, b)) * np.sqrt(2.0 / a)
            torso.append({"w": w, "b": jnp.zeros((b,))})
        out_dim = s.action_dim if s.discrete else s.action_dim
        params = {
            "torso": torso,
            "pi": {
                "w": jax.random.normal(keys[-2], (dims[-1], out_dim)) * 0.01,
                "b": jnp.zeros((out_dim,)),
            },
            "vf": {
                "w": jax.random.normal(keys[-1], (dims[-1], 1)) * 1.0,
                "b": jnp.zeros((1,)),
            },
        }
        if not s.discrete and s.free_log_std:
            params["log_std"] = jnp.zeros((s.action_dim,))
        return params

    def _init_conv_params(self, key: jax.Array) -> Dict:
        """Nature-CNN torso (Mnih 2015): conv 32×8s4, 64×4s2, 64×3s1 →
        dense(hidden[-1] or 512). Pixel math maps straight onto the MXU —
        ``lax.conv_general_dilated`` in NHWC with f32 accumulation."""
        s = self.spec
        assert s.obs_shape is not None and len(s.obs_shape) == 3, s.obs_shape
        keys = jax.random.split(key, len(self._CONV_LAYERS) + 3)
        convs = []
        c_in = s.obs_shape[-1]
        hh, ww = s.obs_shape[0], s.obs_shape[1]
        for i, (c_out, k, stride) in enumerate(self._CONV_LAYERS):
            fan_in = k * k * c_in
            convs.append({
                "w": jax.random.normal(keys[i], (k, k, c_in, c_out))
                * np.sqrt(2.0 / fan_in),
                "b": jnp.zeros((c_out,)),
            })
            hh = (hh - k) // stride + 1
            ww = (ww - k) // stride + 1
            c_in = c_out
        flat = hh * ww * c_in
        dense_out = s.hidden[-1] if s.hidden else 512
        params = {
            "convs": convs,
            "dense": {
                "w": jax.random.normal(keys[-3], (flat, dense_out))
                * np.sqrt(2.0 / flat),
                "b": jnp.zeros((dense_out,)),
            },
            "pi": {
                "w": jax.random.normal(keys[-2], (dense_out, s.action_dim)) * 0.01,
                "b": jnp.zeros((s.action_dim,)),
            },
            "vf": {
                "w": jax.random.normal(keys[-1], (dense_out, 1)),
                "b": jnp.zeros((1,)),
            },
        }
        return params

    # -- forward passes ------------------------------------------------------
    def _torso(self, params: Dict, obs: jax.Array) -> jax.Array:
        if self.spec.conv:
            # uint8 pixels [B, H, W, C] (or pre-flattened) → [0, 1] floats.
            s = self.spec
            x = obs.reshape((-1,) + tuple(s.obs_shape)).astype(jnp.float32) / 255.0
            for i, (_, _, stride) in enumerate(self._CONV_LAYERS):
                layer = params["convs"][i]
                x = jax.lax.conv_general_dilated(
                    x, layer["w"], (stride, stride), "VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                ) + layer["b"]
                x = jax.nn.relu(x)
            x = x.reshape(x.shape[0], -1)
            return jax.nn.relu(x @ params["dense"]["w"] + params["dense"]["b"])
        h = obs
        for layer in params["torso"]:
            h = jnp.tanh(h @ layer["w"] + layer["b"])
        return h

    def forward_train(self, params: Dict, obs: jax.Array) -> Dict[str, jax.Array]:
        """Returns action-dist inputs + value estimates."""
        h = self._torso(params, obs)
        logits = h @ params["pi"]["w"] + params["pi"]["b"]
        value = (h @ params["vf"]["w"] + params["vf"]["b"])[..., 0]
        out = {"action_dist_inputs": logits, "vf_preds": value}
        if not self.spec.discrete and self.spec.free_log_std:
            out["log_std"] = jnp.broadcast_to(params["log_std"], logits.shape)
        return out

    forward_inference = forward_train
    forward_exploration = forward_train

    # -- distributions -------------------------------------------------------
    def sample_action(
        self, params: Dict, obs: jax.Array, key: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """(action, logp, value) under the exploration policy."""
        out = self.forward_exploration(params, obs)
        logits = out["action_dist_inputs"]
        if self.spec.discrete:
            action = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), action
            ]
        else:
            std = jnp.exp(out["log_std"])
            noise = jax.random.normal(key, logits.shape)
            action = logits + std * noise
            logp = jnp.sum(
                -0.5 * (noise**2) - out["log_std"] - 0.5 * jnp.log(2 * jnp.pi), axis=-1
            )
        return action, logp, out["vf_preds"]

    def logp_and_entropy(
        self, params: Dict, obs: jax.Array, actions: jax.Array
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        out = self.forward_train(params, obs)
        logits = out["action_dist_inputs"]
        if self.spec.discrete:
            logp_all = jax.nn.log_softmax(logits)
            logp = logp_all[jnp.arange(logits.shape[0]), actions.astype(jnp.int32)]
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1)
        else:
            std = jnp.exp(out["log_std"])
            logp = jnp.sum(
                -0.5 * ((actions - logits) / std) ** 2
                - out["log_std"]
                - 0.5 * jnp.log(2 * jnp.pi),
                axis=-1,
            )
            entropy = jnp.sum(out["log_std"] + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)
        return logp, entropy, out["vf_preds"]


def spec_for_env(env) -> RLModuleSpec:
    """Build a spec from a gymnasium env's spaces. 3-D uint8 observation
    spaces (Atari-style pixel stacks) get the conv torso automatically."""
    import gymnasium as gym

    obs_space = env.observation_space
    act_space = env.action_space
    if hasattr(obs_space, "shape") and obs_space.shape:
        obs_dim = int(np.prod(obs_space.shape))
    else:
        obs_dim = obs_space.n
    conv = (getattr(obs_space, "shape", None) is not None
            and len(obs_space.shape) == 3
            and getattr(obs_space, "dtype", None) == np.uint8)
    obs_shape = tuple(obs_space.shape) if conv else None
    if isinstance(act_space, gym.spaces.Discrete):
        return RLModuleSpec(observation_dim=obs_dim, action_dim=int(act_space.n),
                            discrete=True, conv=conv, obs_shape=obs_shape,
                            hidden=(512,) if conv else (64, 64))
    return RLModuleSpec(
        observation_dim=obs_dim, action_dim=int(np.prod(act_space.shape)),
        discrete=False, conv=conv, obs_shape=obs_shape,
        hidden=(512,) if conv else (64, 64),
    )
