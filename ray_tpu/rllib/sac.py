"""SAC — soft actor-critic, the off-policy continuous-control family.

Analog of the reference's ``rllib/algorithms/sac/sac.py`` (which subclasses
DQN — ``sac.py:419``; here SAC shares DQN's machinery the same way: the
prioritized replay buffer and n-step preprocessing from
``ray_tpu.rllib.replay``, the env-runner actors, and the Tune-compatible
``train()`` contract). Haarnoja et al. 2018: tanh-squashed Gaussian policy,
twin Q networks with min-clipping, entropy temperature α auto-tuned against
a target entropy. TPU-native shape: the WHOLE update (critic + actor + α +
polyak target blend) is one jitted program; the replay/priority bookkeeping
stays host-side numpy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu.rllib.algorithm_config import AlgorithmConfigBase
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.replay import PrioritizedReplayBuffer, nstep_columns
from ray_tpu.rllib.rl_module import RLModuleSpec, spec_for_env

_LOG_STD_MIN, _LOG_STD_MAX = -20.0, 2.0


def _mlp_init(key, dims):
    keys = jax.random.split(key, len(dims) - 1)
    return [{"w": jax.random.normal(k, (a, b)) * np.sqrt(2.0 / a),
             "b": jnp.zeros((b,))}
            for k, (a, b) in zip(keys, zip(dims[:-1], dims[1:]))]


def _mlp(params, x):
    for layer in params[:-1]:
        x = jax.nn.relu(x @ layer["w"] + layer["b"])
    last = params[-1]
    return x @ last["w"] + last["b"]


class SACModule:
    """Policy + twin Q. Satisfies the env-runner module contract
    (``init_params`` / ``sample_action`` / ``forward_inference``)."""

    def __init__(self, spec: RLModuleSpec,
                 action_low: np.ndarray, action_high: np.ndarray,
                 hidden: Tuple[int, ...] = (256, 256)):
        assert not spec.discrete, "SAC requires a continuous action space"
        self.spec = spec
        self.hidden = hidden
        self._scale = jnp.asarray((action_high - action_low) / 2.0)
        self._center = jnp.asarray((action_high + action_low) / 2.0)

    def init_params(self, key: jax.Array) -> Dict:
        s = self.spec
        kp, k1, k2 = jax.random.split(key, 3)
        return {
            "pi": _mlp_init(kp, (s.observation_dim,) + self.hidden
                            + (2 * s.action_dim,)),
            "q1": _mlp_init(k1, (s.observation_dim + s.action_dim,)
                            + self.hidden + (1,)),
            "q2": _mlp_init(k2, (s.observation_dim + s.action_dim,)
                            + self.hidden + (1,)),
        }

    # -- policy ---------------------------------------------------------------

    def _pi_dist(self, pi_params, obs):
        out = _mlp(pi_params, obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, _LOG_STD_MIN, _LOG_STD_MAX)
        return mean, log_std

    def pi_sample(self, pi_params, obs, key):
        """(env_action, logp, squashed_unit_action) — reparameterized."""
        mean, log_std = self._pi_dist(pi_params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        a = jnp.tanh(pre)
        # logp under the squashed distribution (tanh change of variables).
        logp = jnp.sum(
            -0.5 * eps**2 - log_std - 0.5 * jnp.log(2 * jnp.pi)
            - jnp.log(1.0 - a**2 + 1e-6),
            axis=-1)
        return a * self._scale + self._center, logp, a

    def q_value(self, q_params, obs, env_action):
        # Q nets see UNIT actions: normalize the env-scaled input.
        a = (env_action - self._center) / self._scale
        return _mlp(q_params, jnp.concatenate([obs, a], axis=-1))[..., 0]

    # -- env-runner contract --------------------------------------------------

    def sample_action(self, params, obs, key):
        act, logp, _ = self.pi_sample(params["pi"], obs, key)
        return act, logp, jnp.zeros(obs.shape[0])

    def forward_inference(self, params, obs):
        mean, _ = self._pi_dist(params["pi"], obs)
        return {"action_dist_inputs": mean,
                "vf_preds": jnp.zeros(obs.shape[0])}

    forward_train = forward_inference


class SACLearner:
    """One jitted program per update: critic → actor → α → polyak."""

    def __init__(self, module: SACModule, config: Dict[str, Any],
                 seed: int = 0):
        self.module = module
        self.config = dict(config)
        self.device = jax.local_devices(backend="cpu")[0]
        key = jax.random.key(seed)
        self.params = jax.device_put(module.init_params(key), self.device)
        self.target_q = jax.device_put(
            {"q1": self.params["q1"], "q2": self.params["q2"]}, self.device)
        self.log_alpha = jnp.asarray(
            float(np.log(self.config.get("initial_alpha", 1.0))))
        act_dim = module.spec.action_dim
        self.target_entropy = float(
            self.config.get("target_entropy", -act_dim))

        lr = self.config.get("lr", 3e-4)
        self.pi_opt = optax.adam(self.config.get("actor_lr", lr))
        self.q_opt = optax.adam(self.config.get("critic_lr", lr))
        self.a_opt = optax.adam(self.config.get("alpha_lr", lr))
        self.pi_state = self.pi_opt.init(self.params["pi"])
        self.q_state = self.q_opt.init(
            {"q1": self.params["q1"], "q2": self.params["q2"]})
        self.a_state = self.a_opt.init(self.log_alpha)
        self._key = jax.random.key(seed + 1)
        self._step_fn = jax.jit(self._step)
        self._updates = 0

    def _conservative_penalty(self, qp, params, batch, key):
        """0 for plain SAC; CQL overrides with the logsumexp penalty."""
        return 0.0

    def _step(self, params, target_q, log_alpha, pi_state, q_state, a_state,
              batch, key):
        m = self.module
        tau = self.config.get("tau", 0.005)
        alpha = jnp.exp(log_alpha)
        k1, k2 = jax.random.split(key)

        # -- critic: y = r + γ^s (1-d) [min Q_t(s', a') - α log π(a'|s')]
        a2, logp2, _ = m.pi_sample(params["pi"], batch["next_obs"], k1)
        qt = jnp.minimum(m.q_value(target_q["q1"], batch["next_obs"], a2),
                         m.q_value(target_q["q2"], batch["next_obs"], a2))
        y = (batch["rewards"]
             + batch["discounts"] * (1.0 - batch["terminateds"])
             * (qt - alpha * logp2))
        y = jax.lax.stop_gradient(y)

        def q_loss_fn(qp):
            q1 = m.q_value(qp["q1"], batch["obs"], batch["actions"])
            q2 = m.q_value(qp["q2"], batch["obs"], batch["actions"])
            w = batch["weights"]
            loss = jnp.mean(w * ((q1 - y) ** 2 + (q2 - y) ** 2))
            # Subclass hook (CQL): conservative regularizer on OOD actions.
            loss = loss + self._conservative_penalty(qp, params, batch, k1)
            return loss, q1 - y

        qp = {"q1": params["q1"], "q2": params["q2"]}
        (q_loss, td_err), q_grads = jax.value_and_grad(
            q_loss_fn, has_aux=True)(qp)
        q_upd, q_state = self.q_opt.update(q_grads, q_state, qp)
        qp = optax.apply_updates(qp, q_upd)
        params = dict(params, q1=qp["q1"], q2=qp["q2"])

        # -- actor: max E[min Q(s, a_π) - α log π]
        def pi_loss_fn(pp):
            a_pi, logp_pi, _ = m.pi_sample(pp, batch["obs"], k2)
            q_pi = jnp.minimum(m.q_value(params["q1"], batch["obs"], a_pi),
                               m.q_value(params["q2"], batch["obs"], a_pi))
            return jnp.mean(alpha * logp_pi - q_pi), logp_pi

        (pi_loss, logp_pi), pi_grads = jax.value_and_grad(
            pi_loss_fn, has_aux=True)(params["pi"])
        pi_upd, pi_state = self.pi_opt.update(pi_grads, pi_state,
                                              params["pi"])
        params = dict(params, pi=optax.apply_updates(params["pi"], pi_upd))

        # -- temperature: drive E[log π] toward -target_entropy
        def a_loss_fn(la):
            return -jnp.mean(
                la * (jax.lax.stop_gradient(logp_pi) + self.target_entropy))

        a_loss, a_grad = jax.value_and_grad(a_loss_fn)(log_alpha)
        a_upd, a_state = self.a_opt.update(a_grad, a_state, log_alpha)
        log_alpha = optax.apply_updates(log_alpha, a_upd)

        # -- polyak target blend
        target_q = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o,
                                target_q,
                                {"q1": params["q1"], "q2": params["q2"]})
        metrics = {"q_loss": q_loss, "pi_loss": pi_loss,
                   "alpha": jnp.exp(log_alpha),
                   "entropy": -jnp.mean(logp_pi)}
        return params, target_q, log_alpha, pi_state, q_state, a_state, \
            td_err, metrics

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        n = len(batch["rewards"])
        jbatch = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.float32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "next_obs": jnp.asarray(batch["next_obs"], jnp.float32),
            "terminateds": jnp.asarray(batch["terminateds"], jnp.float32),
            "discounts": jnp.asarray(batch.get(
                "discounts",
                np.full(n, self.config.get("gamma", 0.99), np.float32))),
            "weights": jnp.asarray(batch.get(
                "weights", np.ones(n, np.float32))),
        }
        self._key, sub = jax.random.split(self._key)
        (self.params, self.target_q, self.log_alpha, self.pi_state,
         self.q_state, self.a_state, td_err, metrics) = self._step_fn(
            self.params, self.target_q, self.log_alpha, self.pi_state,
            self.q_state, self.a_state, jbatch, sub)
        self._updates += 1
        out = {k: float(v) for k, v in metrics.items()}
        out["loss"] = out["q_loss"]
        out["td_errors"] = np.asarray(td_err)
        return out

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    @staticmethod
    def _np_tree(tree):
        return jax.tree.map(
            lambda x: np.asarray(x) if isinstance(x, (jax.Array, np.ndarray))
            else x, tree)

    @staticmethod
    def _jnp_tree(tree):
        return jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
            tree)

    def get_state(self) -> Dict:
        # Full continuation state: all three optimizer moments + the policy
        # RNG key — restore must resume the run, not just the weights
        # (same contract as Learner.get_state, learner.py:78).
        return {
            "params": self._np_tree(self.params),
            "target_q": self._np_tree(self.target_q),
            "log_alpha": np.asarray(self.log_alpha),
            "pi_state": self._np_tree(self.pi_state),
            "q_state": self._np_tree(self.q_state),
            "a_state": self._np_tree(self.a_state),
            "rng_key": np.asarray(jax.random.key_data(self._key)),
            "updates": self._updates,
        }

    def set_state(self, state: Dict) -> bool:
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.target_q = jax.tree.map(jnp.asarray, state["target_q"])
        self.log_alpha = jnp.asarray(state["log_alpha"])
        if "pi_state" in state:
            self.pi_state = self._jnp_tree(state["pi_state"])
            self.q_state = self._jnp_tree(state["q_state"])
            self.a_state = self._jnp_tree(state["a_state"])
        if "rng_key" in state:
            self._key = jax.random.wrap_key_data(
                jnp.asarray(state["rng_key"]))
        self._updates = int(state.get("updates", 0))
        return True


@dataclass
class SACConfig(AlgorithmConfigBase):
    env: Optional[Callable[[], Any]] = None
    num_env_runners: int = 1
    num_envs_per_runner: int = 1
    rollout_fragment_length: int = 64
    buffer_capacity: int = 100_000
    train_batch_size: int = 256
    num_steps_sampled_before_learning: int = 1_000
    updates_per_iteration: int = 64
    gamma: float = 0.99
    lr: float = 3e-4
    tau: float = 0.005
    initial_alpha: float = 1.0
    target_entropy: Optional[float] = None  # default -action_dim
    replay: str = "prioritized"
    per_alpha: float = 0.6
    per_beta: float = 0.4
    n_step: int = 1
    hidden: Tuple[int, ...] = (256, 256)
    seed: int = 0

    def build(self) -> "SAC":
        return SAC(self)


class SAC:
    """Tune-compatible train() contract (mirrors DQN — the reference's SAC
    subclasses DQN for exactly this shared shape)."""

    def __init__(self, config: SACConfig):
        assert config.env is not None, "config.environment(env_creator) required"
        self.config = config
        probe = config.env()
        self.spec = spec_for_env(probe)
        low = np.asarray(probe.action_space.low, np.float32)
        high = np.asarray(probe.action_space.high, np.float32)
        probe.close()
        assert not self.spec.discrete, "SAC requires a continuous action space"

        factory = lambda spec: SACModule(spec, low, high,
                                         hidden=tuple(config.hidden))
        self.module = factory(self.spec)
        lcfg = {"lr": config.lr, "gamma": config.gamma, "tau": config.tau,
                "initial_alpha": config.initial_alpha}
        if config.target_entropy is not None:
            lcfg["target_entropy"] = config.target_entropy
        self.learner = SACLearner(self.module, lcfg, seed=config.seed)

        if config.replay == "prioritized":
            self.buffer = PrioritizedReplayBuffer(
                config.buffer_capacity, alpha=config.per_alpha,
                beta=config.per_beta, seed=config.seed)
        else:
            from ray_tpu.rllib.dqn import ReplayBuffer

            self.buffer = ReplayBuffer(config.buffer_capacity,
                                       seed=config.seed)

        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self._runners = [
            runner_cls.remote(
                config.env, num_envs=config.num_envs_per_runner,
                seed=config.seed + 1000 * i, spec=self.spec,
                module_factory=factory,
            )
            for i in range(max(1, config.num_env_runners))
        ]
        self._timesteps = 0
        self._iteration = 0
        self._updates = 0
        self._sync_runners()

    def _sync_runners(self) -> None:
        weights = self.learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self._runners])

    def _to_transitions(self, sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        cfg = self.config
        cols = nstep_columns(
            sample["obs"], sample["rewards"], sample["terminateds"],
            sample["valids"], sample["bootstrap_obs"],
            n_step=cfg.n_step, gamma=cfg.gamma)
        keep = cols.pop("_keep")
        acts = sample["actions"]
        cols["actions"] = acts.reshape((-1,) + acts.shape[2:])[keep]
        return cols

    # -- the Tune contract ---------------------------------------------------

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        samples = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length)
             for r in self._runners])
        for s in samples:
            trans = self._to_transitions(s)
            self.buffer.add_batch(trans)
            self._timesteps += len(trans["rewards"])

        q_losses, ent = [], []
        if (len(self.buffer) >= cfg.num_steps_sampled_before_learning
                and len(self.buffer) >= cfg.train_batch_size):
            for _ in range(cfg.updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size)
                m = self.learner.update(batch)
                if "indices" in batch:
                    self.buffer.update_priorities(batch["indices"],
                                                  m["td_errors"])
                q_losses.append(m["q_loss"])
                ent.append(m["entropy"])
                self._updates += 1
        self._sync_runners()

        self._iteration += 1
        metrics = ray_tpu.get([r.get_metrics.remote() for r in self._runners])
        returns = [m["episode_return_mean"] for m in metrics
                   if m["num_episodes"] > 0]
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps,
            "episode_return_mean": float(np.mean(returns)) if returns else float("nan"),
            "loss": float(np.mean(q_losses)) if q_losses else float("nan"),
            "entropy": float(np.mean(ent)) if ent else float("nan"),
            "alpha": float(np.exp(float(np.asarray(self.learner.log_alpha)))),
            "buffer_size": len(self.buffer),
            "num_updates": self._updates,
            "time_total_s": dt,
        }

    def save(self, path: str) -> str:
        from ray_tpu.train.checkpoint import save_pytree

        save_pytree({"state": self.learner.get_state(),
                     "iteration": self._iteration,
                     "timesteps": self._timesteps}, path)
        return path

    def restore(self, path: str) -> None:
        from ray_tpu.train.checkpoint import load_pytree

        data = load_pytree(path)
        self.learner.set_state(data["state"])
        self._iteration = int(data["iteration"])
        self._timesteps = int(data["timesteps"])
        self._sync_runners()

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
