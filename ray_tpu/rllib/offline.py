"""Offline RL — BC and MARWIL training from datasets.

Analog of the reference's offline family
(``rllib/algorithms/bc/bc.py``, ``rllib/algorithms/marwil/marwil.py``,
dataset ingestion via ``rllib/offline/dataset_reader.py``): the algorithm
never touches an environment — it streams (obs, actions[, rewards,
terminateds]) batches out of a ``ray_tpu.data`` Dataset and trains the
policy supervised.

- **BC** maximizes log π(a|s) over the dataset (pure behavior cloning).
- **MARWIL** (Wang et al. 2018) weights the cloning term by
  exp(β · Â(s, a)) with advantages from a jointly-learned value baseline —
  β = 0 reduces exactly to BC (the reference documents the same contract).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.algorithm_config import AlgorithmConfigBase
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import RLModuleSpec


def episodes_to_dataset(episodes) -> "Any":
    """Build a ``ray_tpu.data`` Dataset from a list of episode dicts
    (each with columns obs/actions/rewards/terminateds) — the writer-side
    helper for producing offline corpora from env runners."""
    from ray_tpu import data as rt_data

    rows = []
    for ep in episodes:
        n = len(ep["actions"])
        for i in range(n):
            rows.append({
                # list<float> cells (arrow-friendly; ndarray cells are not)
                "obs": np.asarray(ep["obs"][i], np.float32).tolist(),
                "actions": (np.asarray(ep["actions"][i]).tolist()
                            if np.ndim(ep["actions"][i]) else ep["actions"][i]),
                "rewards": float(ep["rewards"][i]),
                "terminateds": float(i == n - 1
                                     and ep.get("terminated", True)),
                # Monte-Carlo return-to-go, the MARWIL advantage target.
                "returns": float(sum(ep["rewards"][i:])),
            })
    return rt_data.from_items(rows)


class MARWILLearner(Learner):
    """Advantage-weighted behavior cloning + value baseline.

    loss = -E[ exp(β Â / c) · log π(a|s) ] + vf_coeff · E[(V(s) - R)²]
    with Â = R - V(s) (stop-grad) and c a running advantage-norm estimate
    (the reference normalizes the same way, ``marwil_torch_policy.py``).
    β = 0 → plain BC (the vf head still trains but nothing depends on it).
    """

    def __init__(self, spec: RLModuleSpec, config: Dict[str, Any], seed: int = 0):
        super().__init__(spec, config, seed=seed)
        self._adv_norm = 1.0

    def loss_fn(self, params, batch):
        beta = self.config.get("beta", 1.0)
        vf_coeff = self.config.get("vf_coeff", 1.0)
        logp, _entropy, values = self.module.logp_and_entropy(
            params, batch["obs"], batch["actions"])
        returns = batch["returns"]
        adv = jax.lax.stop_gradient(returns - values)
        adv = adv / jnp.maximum(batch["adv_norm"], 1e-8)
        weights = jnp.exp(jnp.clip(beta * adv, -10.0, 10.0))
        bc_term = -jnp.mean(jax.lax.stop_gradient(weights) * logp)
        vf_term = jnp.mean((values - returns) ** 2)
        return bc_term + vf_coeff * vf_term

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        # Running advantage scale (EMA of |Â|'s RMS) keeps exp(βÂ) tame.
        b = dict(batch)
        b["adv_norm"] = np.float32(self._adv_norm)
        metrics = super().update(b)
        # Refresh the norm from this batch (host-side, cheap).
        vals = np.asarray(self._value_of(batch["obs"]))
        adv = batch["returns"] - vals
        rms = float(np.sqrt(np.mean(adv ** 2)) + 1e-8)
        self._adv_norm = 0.99 * self._adv_norm + 0.01 * rms
        return metrics

    def _value_of(self, obs):
        return self.module.forward_train(
            self.params, jnp.asarray(obs))["vf_preds"]


@dataclass
class BCConfig(AlgorithmConfigBase):
    """Behavior cloning: MARWIL with β = 0 (exactly the reference's BC,
    ``rllib/algorithms/bc/bc.py`` — "MARWIL with beta 0")."""

    dataset: Any = None                 # ray_tpu.data Dataset
    observation_dim: Optional[int] = None
    action_dim: Optional[int] = None
    discrete: bool = True
    hidden: Tuple[int, ...] = (64, 64)
    train_batch_size: int = 256
    updates_per_iteration: int = 32
    lr: float = 1e-3
    grad_clip: float = 10.0
    beta: float = 0.0
    vf_coeff: float = 1.0
    shuffle_seed: int = 0
    seed: int = 0

    def build(self) -> "BC":
        return BC(self)


@dataclass
class MARWILConfig(BCConfig):
    beta: float = 1.0

    def build(self) -> "BC":
        return BC(self)


class BC:
    """Tune-compatible train() over a dataset (no env runners).

    Streams permuted minibatches out of the Dataset each iteration
    (reference: ``offline/dataset_reader.py`` shuffled reads).
    """

    def __init__(self, config: BCConfig):
        assert config.dataset is not None, "config.dataset required"
        assert config.observation_dim and config.action_dim, (
            "observation_dim/action_dim required (offline data has no env "
            "to probe)")
        self.config = config
        self.spec = RLModuleSpec(
            observation_dim=config.observation_dim,
            action_dim=config.action_dim,
            discrete=config.discrete,
            hidden=tuple(config.hidden),
        )
        self.learner = MARWILLearner(self.spec, {
            "lr": config.lr, "grad_clip": config.grad_clip,
            "beta": config.beta, "vf_coeff": config.vf_coeff,
        }, seed=config.seed)
        # jitted eval forward, built lazily on the first evaluate() and
        # cached — rebuilding jax.jit per call recompiles every time
        self._eval_fwd = None
        # Materialize the dataset once into columnar arrays (offline
        # corpora for control tasks are small; a streaming path can batch
        # through iter_batches for bigger ones).
        rows = config.dataset.take_all()
        returns = np.asarray([r.get("returns", r.get("rewards", 0.0))
                              for r in rows], np.float32)
        # Standardize returns over the (fixed) corpus: the value head
        # regresses a ~unit-scale target, so it neither swamps the cloning
        # term through the shared torso nor leaves advantages on a scale
        # that saturates exp(β·Â) (the reference's MARWIL normalizes
        # advantages the same way).
        self._ret_mean = float(returns.mean())
        self._ret_std = float(returns.std() + 1e-6)
        self._columns = {
            "obs": np.stack([np.asarray(r["obs"], np.float32) for r in rows]),
            "actions": np.asarray([r["actions"] for r in rows]),
            "returns": (returns - self._ret_mean) / self._ret_std,
        }
        self._n = len(rows)
        self._rng = np.random.default_rng(config.shuffle_seed)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        losses = []
        for _ in range(cfg.updates_per_iteration):
            idx = self._rng.integers(0, self._n,
                                     min(cfg.train_batch_size, self._n))
            batch = {k: v[idx] for k, v in self._columns.items()}
            losses.append(self.learner.update(batch)["loss"])
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "loss": float(np.mean(losses)),
            "num_samples": self._n,
            "time_total_s": time.perf_counter() - t0,
        }

    def evaluate(self, env_creator: Callable[[], Any],
                 num_episodes: int = 10, seed: int = 0) -> Dict[str, float]:
        """Greedy policy rollout in a real env — the offline-RL report card."""
        env = env_creator()
        module = self.learner.module
        params = self.learner.params
        if self._eval_fwd is None:
            self._eval_fwd = jax.jit(module.forward_inference)
        fwd = self._eval_fwd
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            done, total = False, 0.0
            while not done:
                out = fwd(params, jnp.asarray(obs, jnp.float32)[None])
                if self.spec.discrete:
                    a = int(jnp.argmax(out["action_dist_inputs"][0]))
                else:
                    a = np.asarray(out["action_dist_inputs"][0])
                obs, r, term, trunc, _ = env.step(a)
                total += float(r)
                done = term or trunc
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": float(num_episodes)}

    def save(self, path: str) -> str:
        from ray_tpu.train.checkpoint import save_pytree

        save_pytree({"state": self.learner.get_state(),
                     "iteration": self._iteration}, path)
        return path

    def restore(self, path: str) -> None:
        from ray_tpu.train.checkpoint import load_pytree

        data = load_pytree(path)
        self.learner.set_state(data["state"])
        self._iteration = int(data["iteration"])

    def stop(self) -> None:
        pass


MARWIL = BC  # same engine; the config's beta selects the algorithm
