"""Sebulba-style inference actors — centralized action selection for RL.

The Podracer "Sebulba" split (PAPERS.md): env runners shed their local
policy params and ship per-step observation batches to a small shared pool
of ``InferenceActor``s. Each actor fuses concurrent runner requests into
ONE jitted forward dispatch (the ``serve/batching.py`` pacing pattern:
flush on ``rl_inference_max_batch`` or after ``rl_inference_window_s``),
so a weight broadcast touches K inference actors instead of N runners and
action selection amortizes a single dispatch over many envs.

Equivalence contract: same-shaped requests stack into a vmapped
``module.sample_action`` over per-request PRNG keys, which is bitwise
identical on actions/log-probs to each runner sampling locally with the
same key (the runner still owns its key stream and splits it per step —
only the forward+sample computation moves here). Runner-local mode stays
available as the Anakin/colocated baseline (``ImpalaConfig
.num_inference_actors=0``).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.core.config import config
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec
from ray_tpu.serve.batching import _Batcher
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger(__name__)


class _Request:
    __slots__ = ("obs", "key_data", "greedy")

    def __init__(self, obs: np.ndarray, key_data: Optional[np.ndarray],
                 greedy: bool):
        self.obs = obs
        self.key_data = key_data
        self.greedy = greedy


class InferenceActor:
    """Batched forward passes for a set of env runners.

    Spawn with ``max_concurrency > 1``: concurrent ``infer`` calls block
    inside ``_Batcher.submit`` until the shared flush runs, which is what
    lets requests from different runners land in one dispatch.
    """

    def __init__(
        self,
        spec: RLModuleSpec,
        *,
        seed: int = 0,
        module_factory: Optional[Callable[[RLModuleSpec], Any]] = None,
        max_batch: int = 0,
        window_s: Optional[float] = None,
    ):
        cfg = config()
        self.spec = spec
        self.module = (module_factory(spec) if module_factory
                       else RLModule(spec))
        # Same placement rationale as the env runner: tiny latency-bound
        # forwards stay on host CPU (the learner owns the TPU).
        self._device = jax.local_devices(backend="cpu")[0]
        self._params = jax.device_put(
            self.module.init_params(jax.random.key(seed)), self._device)
        max_batch = int(max_batch or cfg.rl_inference_max_batch or 8)
        window = float(cfg.rl_inference_window_s
                       if window_s is None else window_s)
        self._batcher = _Batcher(self._run_batch, max_batch, window)
        # vmapped over stacked same-shape requests: one dispatch per flush.
        self._sample_many = jax.jit(
            jax.vmap(self.module.sample_action, in_axes=(None, 0, 0)))
        self._greedy_many = jax.jit(jax.vmap(
            lambda p, o: jnp.argmax(
                self.module.forward_inference(p, o)["action_dist_inputs"],
                axis=-1),
            in_axes=(None, 0)))
        self._value_fn = jax.jit(
            lambda p, o: self.module.forward_inference(p, o)["vf_preds"])

    # -- weights sync (one broadcast target instead of N runners) -----------
    def set_weights(self, params) -> bool:
        self._params = jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), self._device), params)
        return True

    def get_weights(self):
        return jax.tree.map(np.asarray, self._params)

    def ping(self) -> bool:
        return True

    # -- request path --------------------------------------------------------
    def infer(self, obs: np.ndarray, key_data: Optional[np.ndarray],
              greedy: bool = False):
        """One env-runner step: returns ``(actions, logps, values)`` as
        numpy. Blocks until the shared batch containing it flushes."""
        action, logp, value = self._batcher.submit(
            None, _Request(np.asarray(obs), key_data, bool(greedy)))
        return action, logp, value

    def values(self, obs: np.ndarray) -> np.ndarray:
        """Critic-only forward for fragment bootstrap values (one call per
        fragment — not worth the batching window)."""
        return jax.device_get(self._value_fn(
            self._params, jax.device_put(np.asarray(obs), self._device)))

    def _run_batch(self, requests: List[_Request]):
        from ray_tpu.core.metrics_export import (metrics_enabled,
                                                 rl_inference_batch_hist)

        if metrics_enabled():
            rl_inference_batch_hist().observe(len(requests))
        results: List[Any] = [None] * len(requests)
        # Group by (shape, mode) so each group is one stacked dispatch;
        # mixed shapes (runners with different env counts) simply split
        # into one dispatch per shape.
        groups = {}
        for i, req in enumerate(requests):
            groups.setdefault((req.obs.shape, req.greedy), []).append(i)
        for (shape, greedy), idxs in groups.items():
            obs = jax.device_put(
                np.stack([requests[i].obs for i in idxs]), self._device)
            if greedy:
                actions = jax.device_get(self._greedy_many(self._params, obs))
                n = shape[0]
                for j, i in enumerate(idxs):
                    results[i] = (actions[j], np.zeros(n, np.float32),
                                  np.zeros(n, np.float32))
            else:
                keys = jnp.stack([
                    jax.random.wrap_key_data(
                        jnp.asarray(requests[i].key_data))
                    for i in idxs])
                a, logp, v = self._sample_many(self._params, obs, keys)
                # one batched fetch per dispatch group, not three syncs
                a, logp, v = jax.device_get((a, logp, v))
                for j, i in enumerate(idxs):
                    results[i] = (a[j], logp[j], v[j])
        return results

    def stop(self) -> None:
        self._batcher.stop()


class InferencePool:
    """Driver-side handle over K inference actors: round-robin runner
    assignment and the K-way weight broadcast."""

    def __init__(
        self,
        num_actors: int,
        spec: RLModuleSpec,
        *,
        seed: int = 0,
        num_clients: int = 0,
        module_factory: Optional[Callable[[RLModuleSpec], Any]] = None,
        window_s: Optional[float] = None,
    ):
        assert num_actors > 0
        cfg = config()
        # Auto batch size: one in-flight step per attached runner, capped at
        # a flush quorum of 4. Waiting for EVERY client before flushing
        # stalls the whole pool on the slowest runner (they desync at
        # fragment boundaries), and dispatch amortization has already
        # saturated by ~4 requests — measured 2204 vs 3926 env-steps/s at
        # 16 runners for quorum 16 vs 4.
        max_batch = int(cfg.rl_inference_max_batch)
        if max_batch <= 0:
            per_actor = max(1, -(-max(num_clients, 1) // num_actors))
            max_batch = min(per_actor, 4)
        actor_cls = ray_tpu.remote(InferenceActor)
        self._actors = [
            actor_cls.options(max_concurrency=max(8, 2 * max_batch)).remote(
                spec, seed=seed, module_factory=module_factory,
                max_batch=max_batch, window_s=window_s)
            for _ in range(num_actors)
        ]
        # Fail fast on construction errors before runners start stepping.
        ray_tpu.get([a.ping.remote() for a in self._actors])

    @property
    def actors(self):
        return list(self._actors)

    def handle_for(self, client_index: int):
        return self._actors[client_index % len(self._actors)]

    def set_weights(self, params) -> None:
        ray_tpu.get([a.set_weights.remote(params) for a in self._actors])

    def stop(self) -> None:
        for a in self._actors:
            try:
                ray_tpu.get(a.stop.remote(), timeout=5.0)
            except Exception:  # noqa: BLE001
                log_swallowed(logger, "inference actor stop")
            try:
                ray_tpu.kill(a)
            except Exception:  # noqa: BLE001
                log_swallowed(logger, "inference actor kill")
