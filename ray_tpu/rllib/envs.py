"""Environment helpers — Atari-style pixel envs for the north-star bench.

The reference's PPO-Atari baseline runs ALE through gymnasium wrappers
(grayscale, resize to 84×84, frame-stack 4 — ``rllib/env/wrappers/
atari_wrappers.py``). This image has no ALE ROMs, so the bench gate runs on
:class:`SyntheticAtariEnv` — a pixel env with the exact Atari interface
(uint8 [84, 84, 4] observations, Discrete(6) actions, episodic structure)
and non-trivial learnable dynamics, so the measured pipeline cost (conv
inference per env step, pixel batches through the object plane, conv
training on device) matches the real thing. ``make_atari`` transparently
prefers real ALE when available.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import gymnasium as gym
from gymnasium import spaces


class SyntheticAtariEnv(gym.Env):
    """Pong-like synthetic pixel env.

    A ball bounces around an 84×84 screen; the agent moves a paddle on the
    right edge (actions: NOOP×2, UP×2, DOWN×2 — six to match ALE's minimal
    action sets). Reward +1 for touching the ball with the paddle, -1 when
    the ball exits right. Episodes cap at ``max_steps``. Observations are
    the latest 4 rendered frames stacked on the channel axis, uint8 — the
    standard frame-stack layout.
    """

    metadata = {"render_modes": []}

    def __init__(self, max_steps: int = 1000, size: int = 84):
        self.size = size
        self.max_steps = max_steps
        self.observation_space = spaces.Box(0, 255, (size, size, 4), np.uint8)
        self.action_space = spaces.Discrete(6)
        self._rng = np.random.default_rng(0)
        self._frames = np.zeros((size, size, 4), np.uint8)

    def _render_frame(self) -> np.ndarray:
        s = self.size
        frame = np.zeros((s, s), np.uint8)
        frame[0, :] = frame[-1, :] = 40  # walls
        bx, by = int(self._ball[0]), int(self._ball[1])
        frame[max(0, by - 2):by + 2, max(0, bx - 2):bx + 2] = 255
        py = int(self._paddle)
        frame[max(0, py - 6):py + 6, s - 3:s - 1] = 180
        return frame

    def _obs(self) -> np.ndarray:
        self._frames = np.roll(self._frames, -1, axis=-1)
        self._frames[..., -1] = self._render_frame()
        return self._frames.copy()

    def reset(self, *, seed: Optional[int] = None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        s = self.size
        self._ball = np.array([s * 0.3, self._rng.uniform(10, s - 10)])
        self._vel = np.array([self._rng.uniform(1.5, 2.5),
                              self._rng.uniform(-2, 2)])
        self._paddle = s / 2.0
        self._t = 0
        self._frames[:] = 0
        return self._obs(), {}

    def step(self, action):
        s = self.size
        if action in (2, 3):
            self._paddle = max(6.0, self._paddle - 3.0)
        elif action in (4, 5):
            self._paddle = min(s - 6.0, self._paddle + 3.0)
        self._ball += self._vel
        if self._ball[1] <= 2 or self._ball[1] >= s - 2:
            self._vel[1] = -self._vel[1]
        reward = 0.0
        terminated = False
        if self._ball[0] >= s - 4:
            if abs(self._ball[1] - self._paddle) < 7:
                reward = 1.0
                self._vel[0] = -abs(self._vel[0])
            else:
                reward = -1.0
                terminated = True
        if self._ball[0] <= 2:
            self._vel[0] = abs(self._vel[0])
        self._t += 1
        truncated = self._t >= self.max_steps
        return self._obs(), reward, terminated, truncated, {}


def make_atari(name: str = "ALE/Pong-v5", **kwargs):
    """Real ALE with standard preprocessing when available, else the
    synthetic stand-in (this image carries no ROMs)."""
    try:
        import ale_py  # noqa: F401

        env = gym.make(name, frameskip=1)
        env = gym.wrappers.AtariPreprocessing(env, frame_skip=4,
                                              grayscale_obs=True)
        env = gym.wrappers.FrameStackObservation(env, 4)
        return gym.wrappers.TransformObservation(
            env, lambda o: np.transpose(np.asarray(o), (1, 2, 0)),
            spaces.Box(0, 255, (84, 84, 4), np.uint8))
    except Exception:  # noqa: BLE001 — missing package, ROMs, or namespace
        return SyntheticAtariEnv(**kwargs)
