"""LLM post-training RL — the RLAX-style actor-learner split on TPU parts.

The workload that ties the serving and training stacks together
(PAPERS.md RLAX): **generation actors** sample completions for a prompt
dataset from the paged continuous-batching engine
(``serve/llm.py PagedLLMEngine`` over ``models/generate.py`` — repeated
prompts hit the prefix cache, so rollout prefill cost amortizes across
rounds), a **pluggable reward function** scores them into the replay
buffer (``rllib/replay.py``), and a **policy-gradient learner** updates a
toy transformer with the APPO loss shape — clipped surrogate over
per-token sequence log-probs, advantage = reward − batch baseline.
Weights flow back learner→generators every ``weight_sync_interval``
iterations (the staleness knob); each sync resets the generators' KV pool
since cached K/V computed under old params would otherwise leak into new
rollouts.

The whole loop is deterministic under a fixed seed: request seeds are a
counter over the base seed, prompts round-robin the dataset, and the
driver consumes generator results in fixed order — the reward-improvement
acceptance test relies on this.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu.models import transformer
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.replay import PrioritizedReplayBuffer
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger(__name__)


def _default_reward(prompt: Sequence[int], completion: Sequence[int],
                    target: int = 3) -> float:
    """Toy dense reward: fraction of completion tokens equal to ``target``.
    Trivially gameable by design — the smoke test only needs a signal the
    policy gradient can climb deterministically."""
    if not len(completion):
        return 0.0
    return float(np.mean(np.asarray(completion) == target))


@dataclass
class LLMRLConfig:
    # Toy transformer shape (models/transformer.py tiny() overrides).
    # vocab_size stays a multiple of vocab_multiple so sampled ids < vocab.
    model_kwargs: Dict[str, Any] = field(default_factory=dict)
    # Prompt dataset: token-id lists. None = a small synthetic set.
    prompts: Optional[List[List[int]]] = None
    # reward_fn(prompt_tokens, completion_tokens) -> float
    reward_fn: Callable[[Sequence[int], Sequence[int]], float] = _default_reward
    num_generators: int = 2
    rollouts_per_iter: int = 16       # completions sampled per iteration
    max_new_tokens: int = 8
    temperature: float = 1.0
    train_batch: int = 32             # sequences per learner update
    updates_per_iter: int = 8
    buffer_capacity: int = 1024
    lr: float = 1e-2
    clip_param: float = 0.3
    grad_clip: float = 1.0
    # Iterations between learner→generator weight broadcasts (staleness).
    weight_sync_interval: int = 1
    engine_slots: int = 2
    seed: int = 0

    def build(self) -> "LLMRL":
        return LLMRL(self)


class GenerationActor:
    """Samples completions from a private paged LLM engine and returns the
    padded columnar rollout (tokens / mask / behavior log-probs)."""

    def __init__(self, model_config, *, slots: int = 2, seed: int = 0):
        from ray_tpu.serve.llm import PagedLLMEngine

        self.model_config = model_config
        self._seed = seed
        params = transformer.init_params(model_config, jax.random.key(seed))
        self._engine = PagedLLMEngine(
            params, model_config, slots=slots,
            max_len=model_config.max_seq_len, chunk=4, name="llm-rl-gen")
        self._max_len = int(model_config.max_seq_len)
        # Behavior log-probs under the params that SAMPLED the tokens (the
        # importance-ratio denominator): one extra forward over the padded
        # sequence, jitted once for the fixed max_len shape.
        self._logp_fn = jax.jit(self._token_logps)

    def _token_logps(self, params, tokens):
        # tokens [1, L] → per-position log p(tokens[t] | tokens[<t]), [1, L]
        # (position 0 is a dummy; masks never select it).
        logits = transformer.forward(params, tokens, self.model_config)
        logp_all = jax.nn.log_softmax(logits[:, :-1, :], axis=-1)
        tgt = tokens[:, 1:]
        logp = jnp.take_along_axis(logp_all, tgt[..., None], axis=-1)[..., 0]
        return jnp.concatenate([jnp.zeros_like(logp[:, :1]), logp], axis=1)

    def set_weights(self, params) -> bool:
        self._engine.params = jax.tree.map(jnp.asarray, params)
        # Cached KV blocks hold K/V computed under the OLD params — a prefix
        # hit after this sync would splice stale activations into the new
        # policy's rollouts. No requests are in flight between rollout()
        # calls, so the reset is safe.
        self._engine._reset_device_state()
        return True

    def ping(self) -> bool:
        return True

    def rollout(self, prompts: List[List[int]], seeds: List[int],
                max_new_tokens: int, temperature: float) -> Dict[str, np.ndarray]:
        """Generate one completion per (prompt, seed); returns fixed-width
        columns padded to the engine max_len."""
        B, L = len(prompts), self._max_len
        tokens = np.zeros((B, L), np.int32)
        gen_mask = np.zeros((B, L), np.float32)
        behavior_logp = np.zeros((B, L), np.float32)
        prompt_len = np.zeros(B, np.int32)
        gen_len = np.zeros(B, np.int32)
        for b, (prompt, seed) in enumerate(zip(prompts, seeds)):
            completion = self._engine.generate(
                prompt, max_new_tokens=max_new_tokens,
                temperature=temperature, seed=int(seed))
            seq = list(prompt) + list(completion)
            n, p = len(seq), len(prompt)
            tokens[b, :n] = seq
            gen_mask[b, p:n] = 1.0
            prompt_len[b] = p
            gen_len[b] = n - p
            logp = np.asarray(self._logp_fn(
                self._engine.params, tokens[b][None]))[0]
            behavior_logp[b] = logp * gen_mask[b]
        return {
            "tokens": tokens,
            "gen_mask": gen_mask,
            "behavior_logp": behavior_logp,
            "prompt_len": prompt_len,
            "gen_len": gen_len,
        }

    def kv_stats(self) -> Dict[str, float]:
        return self._engine.stats()

    def stop(self) -> None:
        close = getattr(self._engine, "close", None)
        if close is not None:
            close()


class LLMRLLearner(Learner):
    """Clipped-surrogate policy gradient over sequence log-probs — the
    APPO loss shape (appo.py ``_pg_loss``) applied per completion token,
    riding the base Learner's jitted optimizer machinery."""

    def __init__(self, model_config, config: Dict[str, Any], seed: int = 0):
        self.spec = None
        self.model_config = model_config
        self.config = dict(config)
        self.device = jax.local_devices(backend="cpu")[0]
        self.params = jax.device_put(
            transformer.init_params(model_config, jax.random.key(seed)),
            self.device)
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(self.config.get("grad_clip", 1.0)),
            optax.adam(self.config.get("lr", 3e-3)),
        )
        self.opt_state = jax.device_put(self.optimizer.init(self.params),
                                        self.device)
        self._update_fn = jax.jit(self._update)

    def loss_fn(self, params, batch) -> jax.Array:
        clip = self.config.get("clip_param", 0.3)
        tokens = batch["tokens"].astype(jnp.int32)
        logits = transformer.forward(params, tokens, self.model_config)
        logp_all = jax.nn.log_softmax(logits[:, :-1, :], axis=-1)
        tgt = tokens[:, 1:]
        logp = jnp.take_along_axis(logp_all, tgt[..., None], axis=-1)[..., 0]
        mask = batch["gen_mask"][:, 1:]
        behavior = batch["behavior_logp"][:, 1:]
        adv = batch["advantage"][:, None]          # [B, 1] per-sequence
        ratio = jnp.exp(logp - behavior)
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        denom = jnp.maximum(mask.sum(), 1.0)
        return -jnp.sum(surrogate * mask) / denom


class LLMRL:
    """The end-to-end post-training loop (Tune-compatible ``train()``)."""

    def __init__(self, config: LLMRLConfig):
        self.config = config
        kw = dict(config.model_kwargs)
        self.model_config = transformer.tiny(**kw)
        assert (self.model_config.padded_vocab
                == self.model_config.vocab_size), \
            "vocab must pad to itself or sampled ids could exceed vocab"
        self.learner = LLMRLLearner(
            self.model_config,
            {"lr": config.lr, "clip_param": config.clip_param,
             "grad_clip": config.grad_clip},
            seed=config.seed)
        gen_cls = ray_tpu.remote(GenerationActor)
        self._generators = [
            gen_cls.remote(self.model_config, slots=config.engine_slots,
                           seed=config.seed)
            for _ in range(max(1, config.num_generators))
        ]
        self.prompts = config.prompts or self._default_prompts()
        self.buffer = PrioritizedReplayBuffer(
            config.buffer_capacity, alpha=0.0, seed=config.seed)
        self._iteration = 0
        self._rollouts = 0
        self._updates = 0
        # Generators start from the same seed as the learner, so their
        # params are already in sync; the first broadcast happens after the
        # first weight_sync_interval.

    def _default_prompts(self) -> List[List[int]]:
        rng = np.random.default_rng(self.config.seed + 7)
        V = self.model_config.vocab_size
        return [list(rng.integers(1, V, size=4)) for _ in range(8)]

    def _next_prompt_batches(self) -> List[List[List[int]]]:
        """Deterministic round-robin split of this iteration's prompts
        across generators."""
        cfg = self.config
        batches: List[List[List[int]]] = [[] for _ in self._generators]
        for j in range(cfg.rollouts_per_iter):
            idx = (self._rollouts + j) % len(self.prompts)
            batches[j % len(self._generators)].append(self.prompts[idx])
        return batches

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        # Staleness sync at iteration start: generators run the whole
        # iteration under these weights.
        if self._iteration > 0 and cfg.weight_sync_interval > 0 \
                and self._iteration % cfg.weight_sync_interval == 0:
            weights = self.learner.get_weights()
            ray_tpu.get([g.set_weights.remote(weights)
                         for g in self._generators])

        batches = self._next_prompt_batches()
        seed0 = cfg.seed + 100_000
        refs = []
        offset = 0
        for g, prompt_batch in zip(self._generators, batches):
            if not prompt_batch:
                continue
            seeds = [seed0 + self._rollouts + offset + j
                     for j in range(len(prompt_batch))]
            offset += len(prompt_batch)
            refs.append((g, prompt_batch,
                         g.rollout.remote(prompt_batch, seeds,
                                          cfg.max_new_tokens,
                                          cfg.temperature)))
        self._rollouts += cfg.rollouts_per_iter

        rewards: List[float] = []
        # Fixed consumption order keeps the run deterministic even though
        # the generators sample concurrently.
        for g, prompt_batch, ref in refs:
            out = ray_tpu.get(ref)
            B = len(prompt_batch)
            batch_rewards = np.zeros(B, np.float32)
            for b in range(B):
                p, n = int(out["prompt_len"][b]), int(out["gen_len"][b])
                completion = out["tokens"][b, p:p + n].tolist()
                batch_rewards[b] = cfg.reward_fn(prompt_batch[b], completion)
            rewards.extend(batch_rewards.tolist())
            self.buffer.add_batch({
                "tokens": out["tokens"],
                "gen_mask": out["gen_mask"],
                "behavior_logp": out["behavior_logp"],
                "reward": batch_rewards,
            })

        losses = []
        for _ in range(cfg.updates_per_iter):
            if len(self.buffer) < cfg.train_batch:
                break
            sampled = self.buffer.sample(cfg.train_batch)
            batch = {
                "tokens": sampled["tokens"],
                "gen_mask": sampled["gen_mask"],
                "behavior_logp": sampled["behavior_logp"],
                # Advantage = reward − batch baseline (the RLAX-style
                # leave-nothing-to-a-critic estimator for bandit-style
                # sequence rewards).
                "advantage": (sampled["reward"]
                              - float(np.mean(sampled["reward"]))),
            }
            losses.append(self.learner.update(batch)["loss"])
            self._updates += 1

        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "reward_mean": float(np.mean(rewards)) if rewards else float("nan"),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_updates": self._updates,
            "num_rollouts": self._rollouts,
            "buffer_size": len(self.buffer),
        }

    def stop(self) -> None:
        for g in self._generators:
            try:
                ray_tpu.get(g.stop.remote(), timeout=10.0)
            except Exception:  # noqa: BLE001
                log_swallowed(logger, "generation actor stop")
            try:
                ray_tpu.kill(g)
            except Exception:  # noqa: BLE001
                log_swallowed(logger, "generation actor kill")
