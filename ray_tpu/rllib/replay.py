"""Replay buffers — prioritized experience replay + n-step returns.

Analog of the reference's replay stack
(``rllib/utils/replay_buffers/prioritized_episode_buffer.py`` — proportional
PER per Schaul et al. 2016, and the n-step preprocessing its DQN/SAC configs
apply before insertion). Storage is columnar numpy (ring arrays), priorities
live in a binary-indexed sum tree so sampling and priority updates are
O(log N) without touching the payload arrays.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


class _SumTree:
    """Fixed-size sum tree over leaf priorities (prefix-sum sampling)."""

    def __init__(self, capacity: int):
        # Round up to a power of two: the vectorized descent assumes every
        # leaf sits at the same depth (a ragged last level would let some
        # lanes run past their leaf). Unused leaves keep priority 0 and are
        # never sampled.
        self.capacity = 1 << (capacity - 1).bit_length()
        # Full binary tree in an array; leaves at [capacity, 2*capacity).
        self._tree = np.zeros(2 * self.capacity, np.float64)

    def set(self, idx: np.ndarray, priority: np.ndarray) -> None:
        i = np.asarray(idx, np.int64) + self.capacity
        self._tree[i] = priority
        i //= 2
        # Propagate sums up level by level (vectorized over the batch; dedup
        # per level so parents are recomputed from CURRENT children).
        while i[0] > 0 or len(i) > 1:
            i = np.unique(i)
            if i[0] == 0:
                i = i[1:]
                if len(i) == 0:
                    break
            self._tree[i] = self._tree[2 * i] + self._tree[2 * i + 1]
            i //= 2

    @property
    def total(self) -> float:
        return float(self._tree[1])

    def sample(self, prefix: np.ndarray) -> np.ndarray:
        """Leaf indices whose cumulative-priority interval contains each
        prefix value (vectorized descent)."""
        idx = np.ones(len(prefix), np.int64)
        prefix = prefix.astype(np.float64).copy()
        while idx[0] < self.capacity:
            left = 2 * idx
            left_sum = self._tree[left]
            go_right = prefix > left_sum
            prefix = np.where(go_right, prefix - left_sum, prefix)
            idx = np.where(go_right, left + 1, left)
        return idx - self.capacity

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self._tree[np.asarray(idx, np.int64) + self.capacity]


class PrioritizedReplayBuffer:
    """Proportional PER: P(i) ∝ p_i^alpha, importance weights
    w_i = (N * P(i))^-beta / max w (Schaul et al. 2016, the reference DQN
    default). ``sample`` returns ``indices`` + ``weights`` columns; call
    ``update_priorities(indices, td_errors)`` after the gradient step."""

    def __init__(self, capacity: int, *, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        self.capacity = capacity
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._storage: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)
        self._tree = _SumTree(capacity)
        self._max_priority = 1.0

    def add_batch(self, transitions: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(transitions.values())))
        if n == 0:
            return
        if not self._storage:
            for k, v in transitions.items():
                shape = (self.capacity,) + v.shape[1:]
                self._storage[k] = np.zeros(shape, v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in transitions.items():
            self._storage[k][idx] = v
        # New transitions get max priority so they are seen at least once.
        self._tree.set(idx, np.full(n, self._max_priority ** self.alpha))
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        total = self._tree.total
        # Stratified prefix sampling over the cumulative priority mass.
        bounds = np.linspace(0.0, total, batch_size + 1)
        prefix = self._rng.uniform(bounds[:-1], bounds[1:])
        idx = self._tree.sample(np.minimum(prefix, total * (1 - 1e-12)))
        idx = np.minimum(idx, self._size - 1)
        p = self._tree.get(idx) / max(total, 1e-12)
        w = (self._size * np.maximum(p, 1e-12)) ** (-self.beta)
        w = w / w.max()
        out = {k: v[idx] for k, v in self._storage.items()}
        out["indices"] = idx
        out["weights"] = w.astype(np.float32)
        return out

    def update_priorities(self, indices: np.ndarray,
                          td_errors: np.ndarray) -> None:
        pr = (np.abs(np.asarray(td_errors, np.float64)) + self.eps)
        self._max_priority = max(self._max_priority, float(pr.max()))
        self._tree.set(np.asarray(indices, np.int64), pr ** self.alpha)

    def __len__(self) -> int:
        return self._size


def nstep_columns(
    obs: np.ndarray,            # [T, N, ...]
    rewards: np.ndarray,        # [T, N]
    terminateds: np.ndarray,    # [T, N]
    valids: np.ndarray,         # [T, N] (0 = autoreset junk step)
    bootstrap_obs: np.ndarray,  # [N, ...] obs after step T-1
    *,
    n_step: int,
    gamma: float,
) -> Dict[str, np.ndarray]:
    """n-step return preprocessing on [T, N] rollout columns (the layout
    env runners emit — flattening first would interleave sub-envs and
    corrupt the temporal chains). For each (t, n): R = Σ_{k<s} γ^k r_{t+k},
    next_obs = obs_{t+s}, discount = γ^s, where the chain length s ≤ n_step
    stops at terminations, fragment end, or an autoreset junk step (the
    reference applies the same preprocessing before buffer insertion —
    its DQN/SAC n-step connector). TD targets then use the PER-SAMPLE
    ``discounts`` column: y = R + γ^s (1 - done) max_a Q(s', a)."""
    T, N = rewards.shape
    rewards = rewards.astype(np.float32)
    terms = terminateds.astype(np.float32)
    next_obs_all = np.concatenate([obs[1:], bootstrap_obs[None]], axis=0)
    R = rewards.copy()
    nxt = next_obs_all.copy()
    term_out = terms.copy()
    disc = np.full((T, N), gamma, np.float32)
    # alive: the chain starting at t may still extend past step t+k-1.
    alive = (1.0 - terms) > 0
    t_idx = np.arange(T)[:, None]
    for k in range(1, n_step):
        src = t_idx + k                       # [T, 1] + k
        in_range = (src < T)
        src_c = np.minimum(src, T - 1)
        row = np.broadcast_to(src_c, (T, N))
        col = np.broadcast_to(np.arange(N)[None, :], (T, N))
        can = in_range & alive & (valids[row, col] > 0)
        R = R + (gamma ** k) * rewards[row, col] * can
        nxt[can] = next_obs_all[row[can], col[can]]
        term_out = np.where(can, terms[row, col], term_out)
        disc = np.where(can, gamma ** (k + 1), disc).astype(np.float32)
        alive = alive & can & ((1.0 - terms[row, col]) > 0)
    flat_keep = valids.reshape(T * N) > 0
    obs_flat = obs.reshape((T * N,) + obs.shape[2:])
    return {
        "obs": obs_flat[flat_keep],
        "rewards": R.reshape(T * N)[flat_keep],
        "next_obs": nxt.reshape((T * N,) + obs.shape[2:])[flat_keep],
        "terminateds": term_out.reshape(T * N)[flat_keep],
        "discounts": disc.reshape(T * N)[flat_keep],
        "_keep": flat_keep,  # for callers to filter aligned extra columns
    }
