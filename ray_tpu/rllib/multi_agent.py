"""Multi-agent environments: runner actors + independent-learner PPO.

Analog of the reference's multi-agent stack
(``rllib/env/multi_agent_env.py`` env contract,
``rllib/env/multi_agent_env_runner.py:24`` — the episode-based runner —
and the ``policies`` / ``policy_mapping_fn`` config surface of
``AlgorithmConfig.multi_agent()``). The TPU-native shape: one JAX
``RLModule`` per POLICY; each env step batches all agents mapped to a
policy into one forward pass, and training runs one jitted PPO update per
policy over the concatenated trajectories of its agents (independent
learners — the reference's default multi-agent mode).

Env contract (dict-keyed, mirroring the reference's MultiAgentEnv):

    reset(seed) -> (obs: {agent: obs}, infos)
    step(actions: {agent: a}) -> (obs, rewards, terminateds, truncateds,
                                  infos)  # dicts; terminateds["__all__"]
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.algorithm_config import AlgorithmConfigBase
from ray_tpu.rllib.ppo import PPOLearner, compute_gae
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec


class MultiAgentEnvRunner:
    """Samples episodes from one multi-agent env; returns per-POLICY
    trajectory lists (each trajectory = one agent's contiguous episode
    segment, the unit GAE runs over)."""

    def __init__(self, env_creator: Callable[[], Any], *,
                 policies: Dict[str, RLModuleSpec],
                 policy_mapping_fn: Callable[[str], str],
                 seed: int = 0):
        self._env = env_creator()
        self._policies = dict(policies)
        self._map = policy_mapping_fn
        self._modules = {pid: RLModule(spec)
                         for pid, spec in self._policies.items()}
        self._device = jax.local_devices(backend="cpu")[0]
        self._params = {
            pid: jax.device_put(m.init_params(jax.random.key(seed + i)),
                                self._device)
            for i, (pid, m) in enumerate(self._modules.items())
        }
        self._sample_fns = {pid: jax.jit(m.sample_action)
                            for pid, m in self._modules.items()}
        self._value_fns = {
            pid: jax.jit(lambda p, o, _m=m: _m.forward_train(p, o)["vf_preds"])
            for pid, m in self._modules.items()
        }
        self._key = jax.random.key(seed + 10_000)
        self._seed = seed
        self._episode = 0
        self._completed_returns: List[float] = []
        # Episode state persists ACROSS sample() calls (like the
        # single-agent runner's self._obs): episodes longer than one
        # fragment continue where the previous fragment stopped.
        self._obs: Optional[Dict[str, Any]] = None
        self._ep_return = 0.0

    # -- weights sync ---------------------------------------------------------

    def set_weights(self, weights: Dict[str, Any]) -> bool:
        for pid, w in weights.items():
            self._params[pid] = jax.tree.map(
                lambda x: jax.device_put(jnp.asarray(x), self._device), w)
        return True

    # -- sampling -------------------------------------------------------------

    def sample(self, num_env_steps: int) -> Dict[str, Any]:
        """Run ``num_env_steps`` env steps (across episode boundaries);
        returns ``{"trajectories": {policy_id: [traj, ...]},
        "episode_return_mean": float, "num_episodes": int}``. A traj dict
        carries obs/actions/logp/values/rewards arrays plus ``terminated``
        and ``bootstrap_value`` (0 at termination; V(last obs) at
        truncation/segment cuts — the same bootstrap rule the
        single-agent path applies)."""
        open_trajs: Dict[str, Dict[str, list]] = {}
        done_trajs: Dict[str, List[dict]] = {p: [] for p in self._policies}

        if self._obs is None:
            self._obs, _ = self._env.reset(seed=self._seed + self._episode)
        obs = self._obs
        for _ in range(num_env_steps):
            # Group live agents by policy; one batched forward per policy.
            by_policy: Dict[str, List[str]] = {}
            for agent in obs:
                by_policy.setdefault(self._map(agent), []).append(agent)
            actions: Dict[str, Any] = {}
            step_info: Dict[str, tuple] = {}
            for pid, agents in by_policy.items():
                batch = np.stack([np.asarray(obs[a], np.float32).reshape(-1)
                                  for a in agents])
                self._key, sub = jax.random.split(self._key)
                act, logp, value = self._sample_fns[pid](
                    self._params[pid],
                    jax.device_put(batch, self._device), sub)
                act = np.asarray(act)
                logp = np.asarray(logp)
                value = np.asarray(value)
                spec = self._policies[pid]
                for i, a in enumerate(agents):
                    env_action = (int(act[i]) if spec.discrete
                                  else np.asarray(act[i]))
                    actions[a] = env_action
                    step_info[a] = (pid, batch[i], act[i], logp[i], value[i])

            next_obs, rewards, terms, truncs, _ = self._env.step(actions)
            for agent, (pid, ob, ac, lp, va) in step_info.items():
                t = open_trajs.setdefault(agent, {
                    "pid": pid, "obs": [], "actions": [], "logp": [],
                    "values": [], "rewards": []})
                t["obs"].append(ob)
                t["actions"].append(ac)
                t["logp"].append(lp)
                t["values"].append(va)
                r = float(rewards.get(agent, 0.0))
                t["rewards"].append(r)
                self._ep_return += r

            episode_over = bool(terms.get("__all__") or truncs.get("__all__"))
            for agent in list(open_trajs):
                terminated = bool(terms.get(agent, False))
                if terminated or episode_over:
                    self._finalize(open_trajs.pop(agent), terminated,
                                   next_obs.get(agent), done_trajs)
            if episode_over:
                self._completed_returns.append(self._ep_return)
                self._ep_return = 0.0
                self._episode += 1
                obs, _ = self._env.reset(seed=self._seed + self._episode)
            else:
                obs = next_obs

        self._obs = obs  # episode continues in the next fragment
        # Cut still-open segments at the fragment boundary (bootstrapped).
        for agent in list(open_trajs):
            self._finalize(open_trajs.pop(agent), False, obs.get(agent),
                           done_trajs)
        completed, self._completed_returns = self._completed_returns, []
        return {
            "trajectories": done_trajs,
            "episode_return_mean": (float(np.mean(completed))
                                    if completed else float("nan")),
            "num_episodes": len(completed),
        }

    def _finalize(self, traj: Dict[str, list], terminated: bool,
                  last_obs, out: Dict[str, List[dict]]) -> None:
        if not traj["obs"]:
            return
        pid = traj["pid"]
        if terminated or last_obs is None:
            bootstrap = 0.0
        else:
            ob = np.asarray(last_obs, np.float32).reshape(1, -1)
            bootstrap = float(np.asarray(self._value_fns[pid](
                self._params[pid], jax.device_put(ob, self._device)))[0])
        out[pid].append({
            "obs": np.stack(traj["obs"]),
            "actions": np.asarray(traj["actions"]),
            "logp": np.asarray(traj["logp"], np.float32),
            "values": np.asarray(traj["values"], np.float32),
            "rewards": np.asarray(traj["rewards"], np.float32),
            "terminated": terminated,
            "bootstrap_value": bootstrap,
        })

    def stop(self) -> None:
        try:
            self._env.close()
        except Exception:  # noqa: BLE001
            pass


@dataclass
class MultiAgentPPOConfig(AlgorithmConfigBase):
    env: Optional[Callable[[], Any]] = None
    policies: Optional[Dict[str, RLModuleSpec]] = None  # None: infer, shared
    policy_mapping_fn: Optional[Callable[[str], str]] = None
    num_env_runners: int = 1
    rollout_fragment_length: int = 128
    num_sgd_iter: int = 4
    minibatch_size: int = 128
    gamma: float = 0.99
    lambda_: float = 0.95
    lr: float = 3e-4
    clip_param: float = 0.2
    vf_clip_param: float = 10.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 0.5
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def multi_agent(self, *, policies=None, policy_mapping_fn=None):
        if policies is not None:
            self.policies = policies
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


def _infer_policies(env, hidden) -> Dict[str, RLModuleSpec]:
    """Default: one SHARED policy for every agent (reference default when
    no ``policies`` dict is configured)."""
    obs, _ = env.reset(seed=0)
    first = next(iter(obs.values()))
    obs_dim = int(np.asarray(first).reshape(-1).shape[0])
    n_actions = int(env.action_space_n) if hasattr(env, "action_space_n") \
        else 2
    return {"shared": RLModuleSpec(observation_dim=obs_dim,
                                   action_dim=n_actions,
                                   hidden=tuple(hidden))}


class MultiAgentPPO:
    """Independent-learner PPO over per-policy modules (the reference's
    default multi-agent training mode: each policy optimizes its own
    objective on its own agents' experience)."""

    def __init__(self, config: MultiAgentPPOConfig):
        assert config.env is not None, "config.environment(env_creator) required"
        self.config = config
        if config.policies is None:
            probe = config.env()
            config.policies = _infer_policies(probe, config.hidden)
            try:
                probe.close()
            except Exception:  # noqa: BLE001
                pass
        if config.policy_mapping_fn is None:
            only = next(iter(config.policies))
            config.policy_mapping_fn = lambda agent_id, _p=only: _p

        lcfg = {"lr": config.lr, "clip_param": config.clip_param,
                "vf_clip_param": config.vf_clip_param,
                "vf_loss_coeff": config.vf_loss_coeff,
                "entropy_coeff": config.entropy_coeff,
                "grad_clip": config.grad_clip}
        self.learners = {pid: PPOLearner(spec, lcfg, seed=config.seed + i)
                         for i, (pid, spec) in
                         enumerate(config.policies.items())}

        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self._runners = [
            runner_cls.remote(
                config.env, policies=config.policies,
                policy_mapping_fn=config.policy_mapping_fn,
                seed=config.seed + 1000 * i)
            for i in range(max(1, config.num_env_runners))
        ]
        self._iteration = 0
        self._timesteps = 0
        self._rng = np.random.default_rng(config.seed)
        self._sync()

    def _sync(self) -> None:
        weights = {pid: lrn.get_weights()
                   for pid, lrn in self.learners.items()}
        ray_tpu.get([r.set_weights.remote(weights) for r in self._runners])

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        samples = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length)
             for r in self._runners], timeout=600)

        # Per-policy batch assembly: GAE per trajectory, then concat.
        losses: Dict[str, List[float]] = {p: [] for p in self.learners}
        for pid, lrn in self.learners.items():
            cols: Dict[str, List[np.ndarray]] = {
                "obs": [], "actions": [], "logp": [],
                "advantages": [], "value_targets": []}
            for s in samples:
                for traj in s["trajectories"][pid]:
                    T = len(traj["rewards"])
                    adv, tgt = compute_gae(
                        traj["rewards"].reshape(T, 1),
                        traj["values"].reshape(T, 1),
                        np.full((T, 1), 0.0, np.float32) if not traj["terminated"]
                        else np.concatenate(
                            [np.zeros((T - 1, 1), np.float32),
                             np.ones((1, 1), np.float32)]),
                        np.asarray([traj["bootstrap_value"]], np.float32),
                        gamma=cfg.gamma, lambda_=cfg.lambda_)
                    cols["obs"].append(traj["obs"])
                    cols["actions"].append(traj["actions"])
                    cols["logp"].append(traj["logp"])
                    cols["advantages"].append(adv[:, 0])
                    cols["value_targets"].append(tgt[:, 0])
            if not cols["obs"]:
                continue
            batch = {k: np.concatenate(v) for k, v in cols.items()}
            n = len(batch["logp"])
            self._timesteps += n
            adv = batch["advantages"]
            batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
            for _ in range(cfg.num_sgd_iter):
                idx = self._rng.permutation(n)
                for lo in range(0, n, cfg.minibatch_size):
                    sel = idx[lo:lo + cfg.minibatch_size]
                    mb = {k: v[sel] for k, v in batch.items()}
                    losses[pid].append(lrn.update(mb)["loss"])
        self._sync()

        self._iteration += 1
        rets = [s["episode_return_mean"] for s in samples
                if s["num_episodes"] > 0]
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps,
            "episode_return_mean": (float(np.mean(rets)) if rets
                                    else float("nan")),
            "policy_loss": {p: float(np.mean(ls)) if ls else float("nan")
                            for p, ls in losses.items()},
            "time_total_s": time.perf_counter() - t0,
        }

    def save(self, path: str) -> str:
        from ray_tpu.train.checkpoint import save_pytree

        save_pytree({pid: lrn.get_state()
                     for pid, lrn in self.learners.items()}, path)
        return path

    def restore(self, path: str) -> None:
        from ray_tpu.train.checkpoint import load_pytree

        data = load_pytree(path)
        for pid, state in data.items():
            self.learners[pid].set_state(state)
        self._sync()

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
