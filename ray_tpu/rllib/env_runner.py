"""EnvRunner — vectorized environment sampling actors.

Analog of the reference's ``rllib/env/single_agent_env_runner.py:101
sample``: each runner holds a vectorized gymnasium env + a local copy of the
module params, steps envs with jitted forward passes, and returns columnar
sample batches (numpy — they cross the object store to the learners).
Episode returns are tracked per sub-env for metrics.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec, spec_for_env


class SingleAgentEnvRunner:
    def __init__(
        self,
        env_creator: Callable[[], Any],
        *,
        num_envs: int = 1,
        seed: int = 0,
        spec: Optional[RLModuleSpec] = None,
        module_factory: Optional[Callable[[RLModuleSpec], Any]] = None,
        inference: Optional[Any] = None,
    ):
        import gymnasium as gym

        self._envs = gym.vector.SyncVectorEnv(
            [self._thunk(env_creator, seed + i) for i in range(num_envs)]
        )
        self.num_envs = num_envs
        probe = env_creator()
        self.spec = spec or spec_for_env(probe)
        probe.close()
        # Algorithms with non-actor-critic policies (SAC's tanh-squashed
        # Gaussian) plug in their own module; the contract is
        # ``init_params`` / ``sample_action(params, obs, key)`` /
        # ``forward_inference`` (reference: RLModuleSpec.module_class).
        self.module = (module_factory(self.spec) if module_factory
                       else RLModule(self.spec))
        # Env-runner inference is tiny and latency-bound: pin it to host CPU
        # (committed args steer jit placement). The TPU belongs to learners —
        # shipping a 4-float CartPole obs across the interconnect per step
        # would make sampling interconnect-latency-bound.
        self._device = jax.local_devices(backend="cpu")[0]
        self._params = jax.device_put(
            self.module.init_params(jax.random.key(seed)), self._device
        )
        self._key = jax.device_put(jax.random.key(seed + 10_000), self._device)
        self._sample_fn = jax.jit(self.module.sample_action)
        # Value-based algorithms (DQN family) explore epsilon-greedily over
        # the argmax policy instead of sampling the softmax
        # (rllib/utils/exploration/epsilon_greedy.py analog).
        self._greedy = False
        self._epsilon = 0.0
        self._np_rng = np.random.default_rng(seed + 20_000)
        self._greedy_fn = jax.jit(
            lambda p, o: jnp.argmax(
                self.module.forward_inference(p, o)["action_dist_inputs"],
                axis=-1))
        # Sebulba mode: an InferenceActor handle. The runner keeps its key
        # stream (split per step, key data shipped with the obs) so the
        # sampled actions are bitwise-identical to runner-local inference;
        # only the forward pass moves to the shared, batched actor.
        self._inference = inference
        self._obs, _ = self._envs.reset(seed=seed)
        # gymnasium >=1.0 vector envs autoreset on the step AFTER done
        # (NEXT_STEP mode): that step ignores the action and returns the new
        # episode's reset obs with reward 0.  Transitions recorded on such
        # steps are junk (action never executed) and must be masked out of
        # GAE and the loss; this tracks which sub-envs are in that state.
        self._autoreset = np.zeros(num_envs, dtype=bool)
        self._ep_returns = np.zeros(num_envs)
        self._ep_lens = np.zeros(num_envs, dtype=np.int64)
        self._completed: List[float] = []
        self._completed_lens: List[int] = []

    @staticmethod
    def _thunk(creator, seed):
        def make():
            env = creator()
            env.reset(seed=seed)
            return env

        return make

    # -- weights sync (reference: WorkerSet weight broadcast) ----------------
    def set_weights(self, params) -> bool:
        self._params = jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), self._device), params
        )
        return True

    def get_weights(self):
        return jax.tree.map(np.asarray, self._params)

    def set_exploration(self, epsilon: float, greedy: bool = True) -> bool:
        """Epsilon-greedy exploration for value-based learners: with prob
        epsilon a uniform random action, else argmax over the head outputs
        (interpreted as Q-values)."""
        assert self.spec.discrete, "epsilon-greedy needs a discrete space"
        self._epsilon = float(epsilon)
        self._greedy = bool(greedy)
        return True

    # -- sampling ------------------------------------------------------------
    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect ``num_steps`` per sub-env; returns a columnar batch with
        bootstrap values for GAE (shape [T, N, ...] flattened to [T*N, ...]
        AFTER advantage computation by the algorithm — kept 2D here)."""
        T, N = num_steps, self.num_envs
        # Pixel obs stay uint8 end-to-end (the conv torso casts /255 on
        # device) — 4x less object-plane traffic than float32.
        obs_dtype = np.uint8 if self.spec.conv else np.float32
        obs_buf = np.zeros((T, N, self.spec.observation_dim), obs_dtype)
        act_shape = (T, N) if self.spec.discrete else (T, N, self.spec.action_dim)
        act_buf = np.zeros(act_shape, np.float32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)
        valid_buf = np.ones((T, N), np.float32)

        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            obs = np.asarray(self._obs, obs_dtype).reshape(N, -1)
            # numpy → CPU device directly: jnp.asarray would materialize on
            # the DEFAULT device first (a tunnel round trip per env step when
            # the default device is a remote TPU)
            if self._inference is not None:
                import ray_tpu

                key_data = (None if self._greedy
                            else jax.device_get(jax.random.key_data(sub)))
                action_np, logp_np, val_np = ray_tpu.get(
                    self._inference.infer.remote(obs, key_data, self._greedy))
                if self._greedy and self._epsilon > 0:
                    explore = self._np_rng.random(N) < self._epsilon
                    randoms = self._np_rng.integers(
                        0, self.spec.action_dim, N)
                    action_np = np.where(explore, randoms, action_np)
            elif self._greedy:
                action = self._greedy_fn(
                    self._params, jax.device_put(obs, self._device))
                action_np = jax.device_get(action)  # the step's one sync
                logp_np = np.zeros(N, np.float32)
                val_np = np.zeros(N, np.float32)
                if self._epsilon > 0:
                    explore = self._np_rng.random(N) < self._epsilon
                    randoms = self._np_rng.integers(
                        0, self.spec.action_dim, N)
                    action_np = np.where(explore, randoms, action_np)
            else:
                action, logp, value = self._sample_fn(
                    self._params, jax.device_put(obs, self._device), sub
                )
                # one batched fetch per env step instead of three syncs
                action_np, logp_np, val_np = jax.device_get(
                    (action, logp, value))
            env_action = action_np.astype(np.int64) if self.spec.discrete else action_np
            next_obs, reward, terminated, truncated, _ = self._envs.step(env_action)
            done = np.logical_or(terminated, truncated)

            obs_buf[t] = obs
            act_buf[t] = action_np
            logp_buf[t] = logp_np
            val_buf[t] = val_np
            rew_buf[t] = reward
            # GAE must not bootstrap across true terminations; truncations
            # keep bootstrapping (the obs recorded on the autoreset step is
            # the truncated episode's FINAL obs, so its value is exactly the
            # truncation bootstrap — see compute_gae's valids handling).
            done_buf[t] = terminated.astype(np.float32)
            valid_buf[t] = (~self._autoreset).astype(np.float32)
            self._autoreset = done.copy()

            live = (valid_buf[t] > 0)
            self._ep_returns += reward * live
            self._ep_lens += live.astype(np.int64)
            for i in np.nonzero(done)[0]:
                self._completed.append(float(self._ep_returns[i]))
                self._completed_lens.append(int(self._ep_lens[i]))
                self._ep_returns[i] = 0.0
                self._ep_lens[i] = 0
            self._obs = next_obs

        # bootstrap value of the final observation
        last_obs = np.asarray(self._obs, obs_dtype).reshape(N, -1)
        if self._inference is not None:
            import ray_tpu

            last_val = np.asarray(ray_tpu.get(
                self._inference.values.remote(last_obs)))
        else:
            out = self.module.forward_inference(
                self._params, jax.device_put(last_obs, self._device)
            )
            last_val = jax.device_get(out["vf_preds"])

        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "terminateds": done_buf,
            "valids": valid_buf,
            "bootstrap_value": last_val,
            # Off-policy learners (V-trace) re-evaluate the bootstrap under
            # the CURRENT policy — they need the obs, not our stale value.
            "bootstrap_obs": last_obs,
        }

    def sample_dag(self, payload: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """One rollout-lane tick (rllib/rollout_lanes.py). A lane-parked
        actor's execution thread lives inside the DAG loop, so ordinary
        method calls (``set_weights``/``get_metrics``) would queue behind
        it forever — weight updates ride the tick payload in and episode
        metrics ride the fragment out instead."""
        weights = payload.get("weights")
        if weights is not None:
            self.set_weights(weights)
        fragment = self.sample(int(payload["num_steps"]))
        fragment["metrics"] = self.get_metrics()
        return fragment

    def ping(self) -> bool:
        """Liveness probe for the driver's respawn path."""
        return True

    def get_metrics(self) -> Dict[str, float]:
        completed, self._completed = self._completed, []
        lens, self._completed_lens = self._completed_lens, []
        return {
            "episode_return_mean": float(np.mean(completed)) if completed else float("nan"),
            "episode_len_mean": float(np.mean(lens)) if lens else float("nan"),
            "num_episodes": float(len(completed)),
        }

    def stop(self) -> None:
        self._envs.close()
