"""Learner / LearnerGroup — the gradient side of the RL stack.

Analog of the reference's ``rllib/core/learner/learner.py`` +
``learner_group.py`` (remote learner actors, torch-DDP allreduce
``torch_learner.py:386``). TPU-native difference: a single Learner jits its
update over a device MESH (DP axis → gradient psum compiled by XLA), and the
multi-actor ``LearnerGroup`` shards batches across learner actors whose
gradients sync through the eager collective API
(``ray_tpu.parallel.collectives`` — the ray.util.collective analog), keeping
updates bitwise-identical across members.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp
import optax

import ray_tpu
from ray_tpu.rllib.rl_module import RLModule, RLModuleSpec


class Learner:
    """Owns params + optimizer; subclasses define the loss."""

    def __init__(self, spec: RLModuleSpec, config: Dict[str, Any], seed: int = 0):
        self.spec = spec
        self.config = dict(config)
        self.module = RLModule(spec)
        # device policy: tiny models are latency-bound — run them on host CPU;
        # big models use the default accelerator. "auto" picks by param count.
        dev_cfg = self.config.get("device", "auto")
        n_params = spec.observation_dim * sum(spec.hidden) + spec.hidden[-1] * spec.action_dim
        if dev_cfg == "cpu" or (dev_cfg == "auto" and n_params < 1_000_000):
            self.device = jax.local_devices(backend="cpu")[0]
        else:
            self.device = jax.devices()[0]
        self.params = jax.device_put(
            self.module.init_params(jax.random.key(seed)), self.device
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(self.config.get("grad_clip", 0.5)),
            optax.adam(self.config.get("lr", 3e-4)),
        )
        self.opt_state = jax.device_put(self.optimizer.init(self.params), self.device)
        self._update_fn = jax.jit(self._update)

    # -- override point ------------------------------------------------------
    def loss_fn(self, params, batch) -> jax.Array:
        raise NotImplementedError

    def _update(self, params, opt_state, batch):
        loss, grads = jax.value_and_grad(self.loss_fn)(params, batch)
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss, "grad_norm": optax.global_norm(grads)}

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        jbatch = {k: jax.device_put(jnp.asarray(v), self.device) for k, v in batch.items()}
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, jbatch
        )
        metrics = jax.device_get(metrics)  # one batched fetch, not per-key
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> bool:
        self.params = jax.tree.map(
            lambda x: jax.device_put(jnp.asarray(x), self.device), params
        )
        return True

    def get_state(self) -> Dict:
        return {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(
                lambda x: np.asarray(x) if isinstance(x, (jax.Array, np.ndarray)) else x,
                self.opt_state,
            ),
        }

    def set_state(self, state: Dict) -> bool:
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            lambda x: jnp.asarray(x) if isinstance(x, np.ndarray) else x,
            state["opt_state"],
        )
        return True


class _DistributedLearnerActor:
    """One member of a LearnerGroup; gradients allreduce through the eager
    collective group (reference analog: TorchDDPRLModule NCCL sync)."""

    def __init__(
        self,
        learner_cls,
        spec: RLModuleSpec,
        config: Dict,
        rank: int,
        world: int,
        group_name: str,
        seed: int,
    ):
        from ray_tpu.parallel import collectives

        # identical seed everywhere → identical initial params (the reference
        # broadcasts rank-0 weights; same effect, no wire traffic)
        self.learner: Learner = learner_cls(spec, config, seed=seed)
        self.rank = rank
        self.world = world
        self.group = group_name
        collectives.init_collective_group(world, rank, group_name=group_name)
        # swap the jitted update for a grad-allreduce variant
        self._grad_fn = jax.jit(jax.value_and_grad(self.learner.loss_fn))

    def update_shard(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        from ray_tpu.parallel import collectives

        L = self.learner
        if any(v.size == 0 for v in batch.values()):
            # Fewer batch rows than learners: this member got an empty
            # shard. It must STILL join the allreduce (fixed world size) —
            # with zero gradients, not the NaNs an empty-mean loss yields
            # (which would poison every replica).
            loss = float("nan")
            grads = jax.tree.map(jnp.zeros_like, L.params)
        else:
            jbatch = {k: jnp.asarray(v) for k, v in batch.items()}
            loss, grads = self._grad_fn(L.params, jbatch)
        flat, treedef = jax.tree.flatten(grads)
        summed = [
            collectives.allreduce(np.asarray(g), op="sum", group_name=self.group)
            for g in flat
        ]
        mean_grads = jax.tree.unflatten(
            treedef, [jnp.asarray(g) / self.world for g in summed]
        )
        updates, L.opt_state = L.optimizer.update(mean_grads, L.opt_state, L.params)
        L.params = optax.apply_updates(L.params, updates)
        return {"loss": float(loss)}

    def get_weights(self):
        return self.learner.get_weights()

    def get_state(self):
        return self.learner.get_state()

    def set_state(self, state):
        return self.learner.set_state(state)


class LearnerGroup:
    """N learner actors with synchronized updates (reference:
    ``learner_group.py``); n=1 degenerates to a local in-process learner."""

    def __init__(
        self,
        learner_cls,
        spec: RLModuleSpec,
        config: Dict,
        *,
        num_learners: int = 0,
        group_name: str = "learner_group",
        seed: int = 0,
        shard_axes: Optional[Dict[str, int]] = None,
    ):
        # Per-key batch-shard axis (default 0). Trajectory learners
        # (IMPALA) shard [T, N] columns on the ENV axis (1) so V-trace's
        # time recursion stays intact per shard.
        self.shard_axes = dict(shard_axes or {})
        self.num_learners = num_learners
        if num_learners == 0:
            self._local = learner_cls(spec, config, seed=seed)
            self._actors = []
        else:
            self._local = None
            actor_cls = ray_tpu.remote(_DistributedLearnerActor)
            self._actors = [
                actor_cls.remote(
                    learner_cls, spec, config, i, num_learners, group_name, seed
                )
                for i in range(num_learners)
            ]
            # barrier: all members joined the collective group
            ray_tpu.get([a.get_weights.remote() for a in self._actors])

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if self._local is not None:
            return self._local.update(batch)
        n = len(self._actors)
        first_key = next(iter(batch))
        rows = batch[first_key].shape[self.shard_axes.get(first_key, 0)]
        shard = max(1, rows // n)
        refs = []
        for i, actor in enumerate(self._actors):
            lo = i * shard
            hi = rows if i == n - 1 else (i + 1) * shard
            piece = {}
            for k, v in batch.items():
                axis = self.shard_axes.get(k, 0)
                idx = [slice(None)] * v.ndim
                idx[axis] = slice(lo, hi)
                piece[k] = v[tuple(idx)]
            refs.append(actor.update_shard.remote(piece))
        metrics = ray_tpu.get(refs)
        losses = [m["loss"] for m in metrics if not np.isnan(m["loss"])]
        return {"loss": float(np.mean(losses)) if losses else float("nan")}

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def get_state(self):
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state):
        if self._local is not None:
            return self._local.set_state(state)
        return ray_tpu.get([a.set_state.remote(state) for a in self._actors])

    def shutdown(self):
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
