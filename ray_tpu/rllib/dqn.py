"""DQN — the off-policy value-learning family.

Analog of the reference's ``rllib/algorithms/dqn/dqn.py`` on the new API
stack: EnvRunner actors explore epsilon-greedily, transitions land in a
uniform replay buffer, and the learner minimizes the Huber TD error
against a periodically-synced TARGET network (Mnih et al. 2015; double-DQN
action selection per van Hasselt 2016 is the default, as in the
reference). TPU-native shape: the TD targets and the gradient step are
two jitted programs; the target sync is a pytree copy.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.algorithm_config import AlgorithmConfigBase
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.rl_module import spec_for_env


class ReplayBuffer:
    """Uniform ring-buffer replay (reference:
    ``rllib/utils/replay_buffers/replay_buffer.py``)."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._storage: Dict[str, np.ndarray] = {}
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, transitions: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(transitions.values())))
        if not self._storage:
            for k, v in transitions.items():
                shape = (self.capacity,) + v.shape[1:]
                self._storage[k] = np.zeros(shape, v.dtype)
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in transitions.items():
            self._storage[k][idx] = v
        self._next = int((self._next + n) % self.capacity)
        self._size = min(self.capacity, self._size + n)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        return {k: v[idx] for k, v in self._storage.items()}

    def __len__(self) -> int:
        return self._size


class DQNLearner(Learner):
    """Huber TD loss vs a target network; the head's outputs ARE Q(s, .)."""

    def __init__(self, spec, config: Dict[str, Any], seed: int = 0):
        super().__init__(spec, config, seed=seed)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self._updates = 0

        def td_targets(target_params, online_params, next_obs, rewards,
                       terminateds, discounts):
            q_next_t = self.module.forward_train(
                target_params, next_obs)["action_dist_inputs"]
            if self.config.get("double_q", True):
                # Double DQN: ONLINE net picks the argmax action, the
                # TARGET net evaluates it (van Hasselt 2016).
                q_next_o = self.module.forward_train(
                    online_params, next_obs)["action_dist_inputs"]
                best = jnp.argmax(q_next_o, axis=-1)
                next_q = q_next_t[jnp.arange(q_next_t.shape[0]), best]
            else:
                next_q = jnp.max(q_next_t, axis=-1)
            # Per-sample discount γ^s (n-step chains have varying length).
            return rewards + discounts * (1.0 - terminateds) * next_q

        self._targets_fn = jax.jit(td_targets)

        def td_errors(params, obs, actions, targets):
            q = self.module.forward_train(params, obs)["action_dist_inputs"]
            qa = q[jnp.arange(q.shape[0]), actions.astype(jnp.int32)]
            return qa - targets

        self._errors_fn = jax.jit(td_errors)

    def loss_fn(self, params, batch):
        q = self.module.forward_train(params, batch["obs"])["action_dist_inputs"]
        qa = q[jnp.arange(q.shape[0]), batch["actions"].astype(jnp.int32)]
        err = qa - batch["targets"]
        # Huber (delta=1): quadratic near 0, linear in the tails.
        huber = jnp.where(jnp.abs(err) <= 1.0, 0.5 * err**2,
                          jnp.abs(err) - 0.5)
        # PER importance weights (ones under uniform replay).
        return jnp.mean(batch["weights"] * huber)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        n = len(batch["rewards"])
        discounts = batch.get(
            "discounts",
            np.full(n, self.config.get("gamma", 0.99), np.float32))
        targets = self._targets_fn(
            self.target_params, self.params,
            jnp.asarray(batch["next_obs"]), jnp.asarray(batch["rewards"]),
            jnp.asarray(batch["terminateds"]), jnp.asarray(discounts))
        weights = batch.get("weights", np.ones(n, np.float32))
        metrics = super().update({
            "obs": batch["obs"],
            "actions": batch["actions"],
            "targets": np.asarray(targets),
            "weights": weights,
        })
        if "indices" in batch:
            # |TD error| for PER priority refresh (post-update params) —
            # skipped under uniform replay, where nothing would read it.
            metrics["td_errors"] = np.asarray(self._errors_fn(
                self.params, jnp.asarray(batch["obs"]),
                jnp.asarray(batch["actions"]), targets))
        self._updates += 1
        if self._updates % self.config.get("target_update_freq", 100) == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        return metrics

    def get_state(self) -> Dict:
        state = super().get_state()
        state["target_params"] = jax.tree.map(np.asarray, self.target_params)
        state["updates"] = self._updates
        return state

    def set_state(self, state: Dict) -> bool:
        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.tree.map(jnp.asarray,
                                              state["target_params"])
            self._updates = int(state.get("updates", 0))
        return True


@dataclass
class DQNConfig(AlgorithmConfigBase):
    env: Optional[Callable[[], Any]] = None
    num_env_runners: int = 1
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 32
    buffer_capacity: int = 50_000
    train_batch_size: int = 64
    num_steps_sampled_before_learning: int = 500
    updates_per_iteration: int = 32
    target_update_freq: int = 100
    gamma: float = 0.99
    lr: float = 1e-3
    grad_clip: float = 10.0
    double_q: bool = True
    # Prioritized replay (the reference's DQN default) + n-step returns.
    replay: str = "prioritized"  # "prioritized" | "uniform"
    per_alpha: float = 0.6
    per_beta: float = 0.4
    n_step: int = 1
    epsilon_initial: float = 1.0
    epsilon_final: float = 0.05
    epsilon_decay_timesteps: int = 5_000
    seed: int = 0
    hidden: Optional[tuple] = None

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    """Tune-compatible train() contract (reference: dqn.py training_step)."""

    def __init__(self, config: DQNConfig):
        assert config.env is not None, "config.environment(env_creator) required"
        self.config = config
        probe = config.env()
        self.spec = spec_for_env(probe)
        probe.close()
        assert self.spec.discrete, "DQN requires a discrete action space"
        if config.hidden and not self.spec.conv:
            import dataclasses

            self.spec = dataclasses.replace(self.spec,
                                            hidden=tuple(config.hidden))

        self.learner = DQNLearner(self.spec, {
            "lr": config.lr, "gamma": config.gamma,
            "grad_clip": config.grad_clip, "double_q": config.double_q,
            "target_update_freq": config.target_update_freq,
        }, seed=config.seed)
        if config.replay == "prioritized":
            from ray_tpu.rllib.replay import PrioritizedReplayBuffer

            self.buffer = PrioritizedReplayBuffer(
                config.buffer_capacity, alpha=config.per_alpha,
                beta=config.per_beta, seed=config.seed)
        else:
            self.buffer = ReplayBuffer(config.buffer_capacity,
                                       seed=config.seed)

        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self._runners = [
            runner_cls.remote(
                config.env, num_envs=config.num_envs_per_runner,
                seed=config.seed + 1000 * i, spec=self.spec,
            )
            for i in range(max(1, config.num_env_runners))
        ]
        self._timesteps = 0
        self._iteration = 0
        self._updates = 0
        self._sync_runners()

    # -- plumbing ------------------------------------------------------------
    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._timesteps / max(1, c.epsilon_decay_timesteps))
        return c.epsilon_initial + frac * (c.epsilon_final - c.epsilon_initial)

    def _sync_runners(self) -> None:
        weights = self.learner.get_weights()
        eps = self._epsilon()
        ray_tpu.get([r.set_weights.remote(weights) for r in self._runners])
        ray_tpu.get([r.set_exploration.remote(eps) for r in self._runners])

    def _to_transitions(self, sample: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """[T, N] rollout columns -> flat (s, a, R^(n), s_{t+n}, done, γ^s)
        transitions via the shared n-step preprocessor (replay.py).

        gymnasium NEXT_STEP autoreset: obs[t+1] is the episode's FINAL obs
        when step t ended it (reset obs only appears one step later), so
        (obs[t], a[t], r[t], obs[t+1]) is a valid transition for both
        termination and truncation; the autoreset step itself
        (valids==0) is junk, dropped here and treated as a chain break by
        the n-step accumulation."""
        from ray_tpu.rllib.replay import nstep_columns

        cols = nstep_columns(
            sample["obs"], sample["rewards"], sample["terminateds"],
            sample["valids"], sample["bootstrap_obs"],
            n_step=self.config.n_step, gamma=self.config.gamma)
        keep = cols.pop("_keep")
        cols["actions"] = sample["actions"].reshape(-1)[keep]
        return cols

    # -- the Tune contract ---------------------------------------------------
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        samples = ray_tpu.get(
            [r.sample.remote(cfg.rollout_fragment_length)
             for r in self._runners])
        for s in samples:
            trans = self._to_transitions(s)
            self.buffer.add_batch(trans)
            self._timesteps += len(trans["rewards"])

        losses = []
        if (len(self.buffer) >= cfg.num_steps_sampled_before_learning
                and len(self.buffer) >= cfg.train_batch_size):
            for _ in range(cfg.updates_per_iteration):
                batch = self.buffer.sample(cfg.train_batch_size)
                m = self.learner.update(batch)
                if "indices" in batch:
                    # PER priority refresh from this step's |TD error|.
                    self.buffer.update_priorities(batch["indices"],
                                                  m["td_errors"])
                losses.append(m["loss"])
                self._updates += 1
        self._sync_runners()

        self._iteration += 1
        metrics = ray_tpu.get([r.get_metrics.remote() for r in self._runners])
        returns = [m["episode_return_mean"] for m in metrics
                   if m["num_episodes"] > 0]
        dt = time.perf_counter() - t0
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps,
            "episode_return_mean": float(np.mean(returns)) if returns else float("nan"),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "epsilon": self._epsilon(),
            "buffer_size": len(self.buffer),
            "num_updates": self._updates,
            "env_steps_per_sec": (len(self._runners) * cfg.rollout_fragment_length
                                  * cfg.num_envs_per_runner) / dt,
            "time_total_s": dt,
        }

    def save(self, path: str) -> str:
        from ray_tpu.train.checkpoint import save_pytree

        save_pytree({"state": self.learner.get_state(),
                     "iteration": self._iteration,
                     "timesteps": self._timesteps}, path)
        return path

    def restore(self, path: str) -> None:
        from ray_tpu.train.checkpoint import load_pytree

        data = load_pytree(path)
        self.learner.set_state(data["state"])
        self._iteration = int(data["iteration"])
        self._timesteps = int(data["timesteps"])
        self._sync_runners()

    def stop(self) -> None:
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:  # noqa: BLE001
                pass
