"""Compiled-DAG rollout lanes — shm fragment transport for IMPALA/APPO.

PR 7's compiled DAGs measured ~190x lower per-tick overhead than the task
path for exactly this N-producers→1-consumer shape, so the rollout loop
gets a lane tier: every env runner parks in a resident stage loop
(``actor_dag_loop``) and streams sample fragments to the driver over
multi-slot shm ring channels, gathered per tick by a ``MultiOutputNode``.

What the lane replaces, per fragment, vs the task path:
- the ``ray_tpu.wait`` 5ms readiness poll + ObjectRef store round trip,
- a fresh ``sample.remote`` task submission to keep the pipeline full,
- the per-iteration ``get_metrics`` RPCs that queue behind in-flight
  ``sample`` calls on the serial runner actors (metrics ride the fragment
  instead — see ``SingleAgentEnvRunner.sample_dag``).

Backpressure is the ring's deferred ack: with ``dag_channel_slots`` ticks
in flight on an edge, a slow learner blocks the runners' next write — no
fragment is ever dropped (the satellite test SIGSTOPs the consumer and
counts). Weight broadcasts ride the tick payload (a lane-parked actor
cannot serve ``set_weights`` calls), which makes broadcast staleness
exactly the submission pipeline depth.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.dag.dag_node import InputNode, MultiOutputNode


class RolloutLanes:
    """One compiled DAG: driver input fans out to every runner's
    ``sample_dag`` stage; the per-tick gather returns one fragment per
    runner, in runner order."""

    def __init__(
        self,
        runners: Sequence[Any],
        num_steps: int,
        *,
        depth: int = 2,
        channel_capacity: int = 16 * 1024 * 1024,
        execute_timeout_s: float = 120.0,
    ):
        assert len(runners) >= 1
        self._runners = list(runners)
        self._num_steps = int(num_steps)
        self._depth = max(1, int(depth))
        self._execute_timeout_s = float(execute_timeout_s)
        with InputNode() as inp:
            leaves = [r.sample_dag.bind(inp) for r in self._runners]
        out = MultiOutputNode(leaves) if len(leaves) > 1 else leaves[0]
        self._multi = len(leaves) > 1
        self._dag = out.experimental_compile(
            channel_capacity=channel_capacity)
        self._pending: deque = deque()

    @property
    def num_runners(self) -> int:
        return len(self._runners)

    def in_flight(self) -> int:
        return len(self._pending)

    def submit(self, weights: Optional[Any] = None) -> None:
        """Launch one tick. ``weights`` (or None) reaches every runner
        before it samples — the broadcast path in lane mode."""
        ref = self._dag.execute(
            {"num_steps": self._num_steps, "weights": weights},
            timeout=self._execute_timeout_s)
        self._pending.append(ref)

    def fill(self, weights: Optional[Any] = None) -> None:
        """Top the submission pipeline up to ``depth`` in-flight ticks.
        Only the first backfilled tick carries ``weights``: the runners
        apply it once, the rest of the window samples under it."""
        while len(self._pending) < self._depth:
            self.submit(weights)
            weights = None

    def next(self, timeout: Optional[float] = None) -> Tuple[Dict, ...]:
        """Fetch the oldest in-flight tick: one fragment dict per runner.
        Raises TimeoutError/RuntimeError on a lost or failed stage — the
        caller (IMPALA) tears the lane down, respawns dead runners and
        rebuilds."""
        if not self._pending:
            self.fill()
        ref = self._pending[0]
        result = ref.get(timeout=timeout)
        self._pending.popleft()
        return result if self._multi else (result,)

    def teardown(self) -> None:
        self._pending.clear()
        self._dag.teardown()
