"""PPO — the first algorithm (reference gate: PPO CartPole/Atari).

Analog of the reference's ``rllib/algorithms/ppo/ppo.py`` (``training_step``
:403) on the new API stack: parallel EnvRunner actors sample; GAE advantages
computed on the driver (vectorized numpy); the LearnerGroup runs clipped-
surrogate SGD epochs; weights broadcast back to runners. The loss lives in
``PPOLearner.loss_fn`` and jits onto whatever devices the learner owns.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu.rllib.algorithm_config import AlgorithmConfigBase
from ray_tpu.rllib.env_runner import SingleAgentEnvRunner
from ray_tpu.rllib.learner import Learner, LearnerGroup
from ray_tpu.rllib.rl_module import RLModuleSpec, spec_for_env


@dataclass
class PPOConfig(AlgorithmConfigBase):
    """Reference: ``rllib/algorithms/ppo/ppo.py PPOConfig`` +
    ``algorithm_config.py`` builder style (``.environment().training()...``
    collapsed into one dataclass)."""

    env: Optional[Callable[[], Any]] = None         # env creator
    num_env_runners: int = 0                        # 0 = sample inline
    num_envs_per_runner: int = 4
    rollout_fragment_length: int = 128
    num_learners: int = 0                           # 0 = local learner
    gamma: float = 0.99
    lambda_: float = 0.95
    lr: float = 3e-4
    clip_param: float = 0.2
    vf_clip_param: float = 10.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 8
    minibatch_size: int = 256
    grad_clip: float = 0.5
    seed: int = 0
    hidden: tuple = (64, 64)

    def learners(self, *, num_learners: int) -> "PPOConfig":
        self.num_learners = num_learners
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPOLearner(Learner):
    def loss_fn(self, params, batch):
        cfg = self.config
        logp, entropy, values = self.module.logp_and_entropy(
            params, batch["obs"], batch["actions"]
        )
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - cfg["clip_param"], 1 + cfg["clip_param"]) * adv,
        )
        policy_loss = -jnp.mean(surr)
        vf_err = jnp.clip(
            values - batch["value_targets"], -cfg["vf_clip_param"], cfg["vf_clip_param"]
        )
        vf_loss = jnp.mean(vf_err**2)
        ent = jnp.mean(entropy)
        return (
            policy_loss
            + cfg["vf_loss_coeff"] * vf_loss
            - cfg["entropy_coeff"] * ent
        )


def compute_gae(
    rewards: np.ndarray,       # [T, N]
    values: np.ndarray,        # [T, N]
    terminateds: np.ndarray,   # [T, N]
    bootstrap_value: np.ndarray,  # [N]
    *,
    gamma: float,
    lambda_: float,
    valids: np.ndarray | None = None,  # [T, N] 0 on autoreset (junk) steps
):
    """Vectorized GAE (reference: ``rllib/evaluation/postprocessing.py``).

    ``valids[t, n] == 0`` marks a gymnasium NEXT_STEP-autoreset transition:
    the action was ignored by the env and ``obs[t]`` is the *final* obs of
    the episode that just ended.  Zeroing ``last`` there (a) cuts the GAE
    trace so nothing leaks across the episode boundary, and (b) leaves
    ``next_value = values[t] = V(final obs)`` for the preceding step — which
    is exactly the truncation bootstrap the reference computes from
    ``final_observation`` (``rllib/evaluation/postprocessing.py``).
    Terminated boundaries are handled by ``nonterminal`` as usual.
    """
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last = np.zeros(N, np.float32)
    next_value = bootstrap_value
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - terminateds[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last = delta + gamma * lambda_ * nonterminal * last
        if valids is not None:
            last = last * valids[t]
        adv[t] = last
        next_value = values[t]
    targets = adv + values
    return adv, targets


class PPO:
    """Tune-compatible Algorithm (reference: Algorithm is a Trainable)."""

    def __init__(self, config: PPOConfig):
        assert config.env is not None, "config.environment(env_creator) required"
        self.config = config
        probe = config.env()
        self.spec = spec_for_env(probe)
        if config.hidden and not self.spec.conv:
            # Pixel specs keep their conv torso + (512,) head regardless of
            # the MLP default; dataclasses.replace preserves every other
            # field so new spec knobs can't silently drop here.
            import dataclasses

            self.spec = dataclasses.replace(self.spec,
                                            hidden=tuple(config.hidden))
        probe.close()

        learner_cfg = {
            "lr": config.lr,
            "clip_param": config.clip_param,
            "vf_clip_param": config.vf_clip_param,
            "vf_loss_coeff": config.vf_loss_coeff,
            "entropy_coeff": config.entropy_coeff,
            "grad_clip": config.grad_clip,
        }
        self.learner_group = LearnerGroup(
            PPOLearner, self.spec, learner_cfg,
            num_learners=config.num_learners, seed=config.seed,
        )

        if config.num_env_runners == 0:
            self._local_runner = SingleAgentEnvRunner(
                config.env,
                num_envs=config.num_envs_per_runner,
                seed=config.seed,
                spec=self.spec,
            )
            self._runners = []
        else:
            self._local_runner = None
            runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
            self._runners = [
                runner_cls.remote(
                    config.env,
                    num_envs=config.num_envs_per_runner,
                    seed=config.seed + 1000 * i,
                    spec=self.spec,
                )
                for i in range(config.num_env_runners)
            ]
        self._iteration = 0
        self._timesteps = 0
        self._sync_weights()

    # -- weight broadcast (reference: WorkerSet.sync_weights) ----------------
    def _sync_weights(self):
        weights = self.learner_group.get_weights()
        if self._local_runner is not None:
            self._local_runner.set_weights(weights)
        else:
            ray_tpu.get([r.set_weights.remote(weights) for r in self._runners])

    # -- one training iteration (reference: training_step) -------------------
    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()

        # 1. sample
        if self._local_runner is not None:
            samples = [self._local_runner.sample(cfg.rollout_fragment_length)]
            metric_srcs = [self._local_runner.get_metrics()]
        else:
            samples = ray_tpu.get(
                [r.sample.remote(cfg.rollout_fragment_length) for r in self._runners]
            )
            metric_srcs = ray_tpu.get([r.get_metrics.remote() for r in self._runners])
        t_sample = time.perf_counter() - t0

        # 2. advantages per runner, then concat to a flat train batch
        obs_l, act_l, logp_l, adv_l, tgt_l = [], [], [], [], []
        sampled_steps = 0
        for s in samples:
            valids = s.get("valids")
            adv, tgt = compute_gae(
                s["rewards"], s["values"], s["terminateds"], s["bootstrap_value"],
                gamma=cfg.gamma, lambda_=cfg.lambda_, valids=valids,
            )
            T, N = s["rewards"].shape
            sampled_steps += T * N
            # Drop autoreset (junk) transitions entirely — their action was
            # never executed, so they carry no training signal.
            keep = (
                valids.reshape(T * N) > 0
                if valids is not None
                else np.ones(T * N, bool)
            )
            obs_l.append(s["obs"].reshape(T * N, -1)[keep])
            act_l.append(s["actions"].reshape(T * N, *s["actions"].shape[2:])[keep])
            logp_l.append(s["logp"].reshape(T * N)[keep])
            adv_l.append(adv.reshape(T * N)[keep])
            tgt_l.append(tgt.reshape(T * N)[keep])
        batch = {
            "obs": np.concatenate(obs_l),
            "actions": np.concatenate(act_l),
            "logp": np.concatenate(logp_l),
            "advantages": np.concatenate(adv_l),
            "value_targets": np.concatenate(tgt_l),
        }
        # advantage normalization (reference PPO default)
        adv = batch["advantages"]
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        rows = len(batch["obs"])
        # timesteps = env steps sampled (autoreset steps included — they
        # occupy a sample slot even though their transition is dropped).
        self._timesteps += sampled_steps

        # 3. SGD epochs over minibatches
        rng = np.random.default_rng(cfg.seed + self._iteration)
        losses = []
        for _ in range(cfg.num_epochs):
            perm = rng.permutation(rows)
            for lo in range(0, rows, cfg.minibatch_size):
                idx = perm[lo : lo + cfg.minibatch_size]
                if len(idx) < 2:
                    continue
                mb = {k: v[idx] for k, v in batch.items()}
                losses.append(self.learner_group.update(mb)["loss"])
        t_total = time.perf_counter() - t0

        # 4. broadcast
        self._sync_weights()
        self._iteration += 1

        returns = [m["episode_return_mean"] for m in metric_srcs if m["num_episodes"] > 0]
        return {
            "training_iteration": self._iteration,
            "timesteps_total": self._timesteps,
            "episode_return_mean": float(np.mean(returns)) if returns else float("nan"),
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "env_steps_per_sec": sampled_steps / t_total,
            "time_sample_s": t_sample,
            "time_total_s": t_total,
        }

    # -- checkpointing (reference: Algorithm.save/restore) -------------------
    def save(self, path: str) -> str:
        from ray_tpu.train.checkpoint import save_pytree

        state = self.learner_group.get_state()
        save_pytree(
            {"params": state["params"], "iteration": self._iteration,
             "timesteps": self._timesteps},
            path,
        )
        return path

    def restore(self, path: str) -> None:
        from ray_tpu.train.checkpoint import load_pytree

        data = load_pytree(path)
        state = self.learner_group.get_state()
        state["params"] = data["params"]
        self.learner_group.set_state(state)
        self._iteration = int(data["iteration"])
        self._timesteps = int(data["timesteps"])
        self._sync_weights()

    def stop(self) -> None:
        self.learner_group.shutdown()
        if self._local_runner is not None:
            self._local_runner.stop()
        for r in self._runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    # -- Tune integration ----------------------------------------------------
    @classmethod
    def as_trainable(cls, base_config: PPOConfig, stop_iters: int = 10):
        """Function trainable running ``stop_iters`` iterations, reporting
        each (reference: Algorithm subclasses Trainable; same contract)."""

        def trainable(overrides: Dict):
            import copy

            cfg = copy.copy(base_config)
            for k, v in overrides.items():
                setattr(cfg, k, v)
            algo = cfg.build()
            try:
                for _ in range(stop_iters):
                    from ray_tpu import tune

                    tune.report(algo.train())
            finally:
                algo.stop()

        return trainable
