"""Shared builder surface for algorithm configs.

The reference's ``AlgorithmConfig`` (``rllib/algorithms/algorithm_config.py``)
gives every algorithm the same fluent ``.environment().env_runners()
.training()`` builder; this mixin is that shared surface for the dataclass
configs here (PPOConfig, ImpalaConfig subclass it and add their fields).
"""

from __future__ import annotations


class AlgorithmConfigBase:
    """Fluent builders over dataclass fields; validation by hasattr."""

    def environment(self, env):
        self.env = env
        return self

    def env_runners(self, *, num_env_runners=None,
                    num_envs_per_env_runner=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_runner = num_envs_per_env_runner
        return self

    def training(self, **kw):
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(
                    f"unknown {type(self).__name__} option {k}")
            setattr(self, k, v)
        return self
