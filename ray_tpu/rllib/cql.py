"""CQL — conservative Q-learning, offline continuous control.

Analog of the reference's ``rllib/algorithms/cql/cql.py`` (which builds on
SAC the same way): the learner is SAC's twin-Q actor-critic plus the
CQL(H) conservative regularizer

    α_cql · E_s[ logsumexp_a Q(s, a) − Q(s, a_data) ]

with the logsumexp estimated over uniform-random and current-policy
actions (Kumar et al. 2020). Pushing DOWN Q on out-of-distribution
actions while anchoring it on dataset actions keeps the learned policy
inside the data support — the core offline-RL failure mode SAC alone
cannot handle. Training reads a ``ray_tpu.data`` Dataset (or columnar
arrays); there are no env runners.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithm_config import AlgorithmConfigBase
from ray_tpu.rllib.rl_module import RLModuleSpec
from ray_tpu.rllib.sac import SACLearner, SACModule


class CQLLearner(SACLearner):
    """SAC learner + CQL(H) conservative penalty on both Q heads."""

    def _conservative_penalty(self, qp, params, batch, key):
        m = self.module
        cfg = self.config
        n_samples = cfg.get("cql_n_actions", 4)
        alpha_cql = cfg.get("cql_alpha", 1.0)
        obs = batch["obs"]
        B = obs.shape[0]
        A = m.spec.action_dim

        krand, kpi = jax.random.split(key)
        # Uniform actions over the env range + current-policy actions —
        # the sampled support of the logsumexp.
        unit = jax.random.uniform(krand, (n_samples, B, A),
                                  minval=-1.0, maxval=1.0)
        rand_actions = unit * m._scale + m._center
        pi_keys = jax.random.split(kpi, n_samples)
        pi_actions = jnp.stack([
            m.pi_sample(params["pi"], obs, pi_keys[i])[0]
            for i in range(n_samples)
        ])
        all_actions = jnp.concatenate([rand_actions, pi_actions])  # [2S,B,A]

        def q_on(qparams):
            qs = jnp.stack([m.q_value(qparams, obs, all_actions[i])
                            for i in range(2 * n_samples)])  # [2S, B]
            lse = jax.nn.logsumexp(qs, axis=0) - jnp.log(2.0 * n_samples)
            data_q = m.q_value(qparams, batch["obs"], batch["actions"])
            return jnp.mean(lse - data_q)

        return alpha_cql * (q_on(qp["q1"]) + q_on(qp["q2"]))


@dataclass
class CQLConfig(AlgorithmConfigBase):
    dataset: Any = None                 # ray_tpu.data Dataset OR dict of columns
    observation_dim: Optional[int] = None
    action_dim: Optional[int] = None
    action_low: Any = None
    action_high: Any = None
    hidden: Tuple[int, ...] = (64, 64)
    train_batch_size: int = 256
    updates_per_iteration: int = 64
    gamma: float = 0.99
    lr: float = 3e-4
    tau: float = 0.005
    cql_alpha: float = 1.0
    cql_n_actions: int = 4
    seed: int = 0

    def build(self) -> "CQL":
        return CQL(self)


class CQL:
    """Tune-compatible offline train() over a fixed transition corpus."""

    def __init__(self, config: CQLConfig):
        assert config.dataset is not None, "config.dataset required"
        assert config.observation_dim and config.action_dim, (
            "observation_dim/action_dim required (offline data, no env)")
        self.config = config
        low = np.asarray(
            config.action_low if config.action_low is not None else -1.0,
            np.float32).reshape(-1)
        high = np.asarray(
            config.action_high if config.action_high is not None else 1.0,
            np.float32).reshape(-1)
        if low.shape[0] == 1:
            low = np.repeat(low, config.action_dim)
            high = np.repeat(high, config.action_dim)
        self.spec = RLModuleSpec(
            observation_dim=config.observation_dim,
            action_dim=config.action_dim, discrete=False,
            hidden=tuple(config.hidden))
        self.module = SACModule(self.spec, low, high,
                                hidden=tuple(config.hidden))
        # jitted eval forward, built lazily on the first evaluate() and
        # cached — rebuilding jax.jit per call recompiles every time
        self._eval_fwd = None
        self.learner = CQLLearner(self.module, {
            "lr": config.lr, "gamma": config.gamma, "tau": config.tau,
            "cql_alpha": config.cql_alpha,
            "cql_n_actions": config.cql_n_actions,
        }, seed=config.seed)

        if isinstance(config.dataset, dict):
            cols = {k: np.asarray(v) for k, v in config.dataset.items()}
        else:
            rows = config.dataset.take_all()
            cols = {
                k: np.stack([np.asarray(r[k], np.float32) for r in rows])
                for k in ("obs", "actions", "rewards", "next_obs",
                          "terminateds")
            }
        self._cols = cols
        self._n = len(cols["rewards"])
        self._rng = np.random.default_rng(config.seed)
        self._iteration = 0

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        t0 = time.perf_counter()
        q_losses = []
        for _ in range(cfg.updates_per_iteration):
            idx = self._rng.integers(0, self._n,
                                     min(cfg.train_batch_size, self._n))
            batch = {k: v[idx] for k, v in self._cols.items()}
            m = self.learner.update(batch)
            q_losses.append(m["q_loss"])
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "loss": float(np.mean(q_losses)),
            "num_samples": self._n,
            "time_total_s": time.perf_counter() - t0,
        }

    def evaluate(self, env_creator: Callable[[], Any],
                 num_episodes: int = 5, seed: int = 0) -> Dict[str, float]:
        """Mean-policy rollout in a real env."""
        env = env_creator()
        if self._eval_fwd is None:
            self._eval_fwd = jax.jit(self.module.forward_inference)
        fwd = self._eval_fwd
        returns = []
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed + ep)
            done, total = False, 0.0
            while not done:
                out = fwd(self.learner.params,
                          jnp.asarray(obs, jnp.float32)[None])
                # mean action, squashed + scaled like pi_sample's center
                a = np.asarray(jnp.tanh(out["action_dist_inputs"][0])
                               * self.module._scale + self.module._center)
                obs, r, term, trunc, _ = env.step(a)
                total += float(r)
                done = term or trunc
            returns.append(total)
        env.close()
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": float(num_episodes)}

    def save(self, path: str) -> str:
        from ray_tpu.train.checkpoint import save_pytree

        save_pytree({"state": self.learner.get_state(),
                     "iteration": self._iteration}, path)
        return path

    def restore(self, path: str) -> None:
        from ray_tpu.train.checkpoint import load_pytree

        data = load_pytree(path)
        self.learner.set_state(data["state"])
        self._iteration = int(data["iteration"])

    def stop(self) -> None:
        pass
