"""Job submission — run driver scripts as supervised jobs.

Analog of the reference's job API (``dashboard/modules/job/`` —
``JobManager`` :529 spawning a ``JobSupervisor`` actor :142 that runs the
entrypoint command; REST surface ``submit_job`` :875). The supervisor is an
actor holding the subprocess; status/logs/stop flow through it; job metadata
lives in the GCS job table.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import ray_tpu


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class _JobSupervisor:
    """Reference: ``job_manager.py:142 JobSupervisor`` — owns the driver
    subprocess for one job."""

    def __init__(self, job_id: str, entrypoint: str, env: Dict[str, str], log_path: str):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self.status = JobStatus.PENDING
        self.returncode: Optional[int] = None
        # The child dups the log fd at spawn; close the parent's copy right
        # away instead of holding one fd per running job until exit.
        log_file = open(log_path, "wb")
        try:
            child_env = {**os.environ, **env, "RAY_TPU_JOB_ID": job_id}
            self._proc = subprocess.Popen(
                entrypoint,
                shell=True,
                stdout=log_file,
                stderr=subprocess.STDOUT,
                env=child_env,
                start_new_session=True,
            )
        finally:
            log_file.close()
        self.status = JobStatus.RUNNING
        self._waiter = threading.Thread(target=self._wait, daemon=True)
        self._waiter.start()

    def _wait(self):
        self.returncode = self._proc.wait()
        if self.status != JobStatus.STOPPED:
            self.status = (
                JobStatus.SUCCEEDED if self.returncode == 0 else JobStatus.FAILED
            )

    def get_status(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "status": self.status,
            "entrypoint": self.entrypoint,
            "returncode": self.returncode,
        }

    def get_logs(self) -> str:
        try:
            with open(self.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop(self) -> bool:
        if self.status == JobStatus.RUNNING:
            self.status = JobStatus.STOPPED
            try:
                os.killpg(os.getpgid(self._proc.pid), 15)
            except Exception:
                self._proc.terminate()
            return True
        return False


class JobSubmissionClient:
    """Reference: ``ray.job_submission.JobSubmissionClient`` surface
    (submit_job / get_job_status / get_job_logs / stop_job / list_jobs /
    wait — address-free: talks to the in-runtime supervisor actors)."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._supervisors: Dict[str, Any] = {}
        self._log_dir = os.path.join(tempfile.gettempdir(), "ray_tpu_jobs")
        os.makedirs(self._log_dir, exist_ok=True)

    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[Dict] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        env_vars = dict((runtime_env or {}).get("env_vars", {}))
        working_dir = (runtime_env or {}).get("working_dir")
        if working_dir:
            env_vars["PYTHONPATH"] = (
                working_dir + os.pathsep + env_vars.get("PYTHONPATH", os.environ.get("PYTHONPATH", ""))
            )
            entrypoint = f"cd {working_dir} && {entrypoint}"
        log_path = os.path.join(self._log_dir, f"{job_id}.log")
        supervisor_cls = ray_tpu.remote(_JobSupervisor)
        sup = supervisor_cls.options(num_cpus=0, name=f"_job_supervisor_{job_id}").remote(
            job_id, entrypoint, env_vars, log_path
        )
        self._supervisors[job_id] = sup
        return job_id

    def _sup(self, job_id: str):
        if job_id in self._supervisors:
            return self._supervisors[job_id]
        return ray_tpu.get_actor(f"_job_supervisor_{job_id}")

    def get_job_status(self, job_id: str) -> str:
        return ray_tpu.get(self._sup(job_id).get_status.remote())["status"]

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        return ray_tpu.get(self._sup(job_id).get_status.remote())

    def get_job_logs(self, job_id: str) -> str:
        return ray_tpu.get(self._sup(job_id).get_logs.remote())

    def stop_job(self, job_id: str) -> bool:
        return ray_tpu.get(self._sup(job_id).stop.remote())

    def list_jobs(self) -> List[Dict[str, Any]]:
        return [
            ray_tpu.get(sup.get_status.remote()) for sup in self._supervisors.values()
        ]

    def wait_until_finish(self, job_id: str, timeout_s: float = 120.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.1)
        raise TimeoutError(f"job {job_id} not finished in {timeout_s}s")
