"""Push-based shuffle — pipelined map/merge exchange.

Analog of the reference's push-based shuffle scheduler
(``python/ray/data/_internal/planner/exchange/
push_based_shuffle_task_scheduler.py``): instead of every reducer pulling
ALL map partials at the end (a P×M memory spike and zero overlap), mappers
run in bounded **rounds** and each round's partials are immediately **merged
into the running reducer state** — merge work overlaps the next map round,
and peak reducer memory is (merged block + one round's partials) regardless
of how many input blocks exist. That is what lets a shuffle of a
larger-than-memory dataset stream through a small cluster.

Used by ``Dataset.random_shuffle`` and ``Dataset.repartition``.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def _merge_blocks(*parts: Block) -> Block:
    return BlockAccessor.concat([p for p in parts if p is not None])


def _merge_and_permute(seed: Optional[int], *parts: Block) -> Block:
    table = BlockAccessor.concat([p for p in parts if p is not None])
    rng = np.random.default_rng(seed)
    perm = rng.permutation(table.num_rows)
    return BlockAccessor(table).take(list(perm))


def _final_concat(seed: Optional[int], *parts: Block) -> Block:
    return _merge_blocks(*parts)


def push_based_shuffle(
    input_refs: Sequence[Any],
    *,
    num_partitions: int,
    map_fn: Callable[..., Any],       # (block, P, round_seed) -> P partials
    final_fn: Callable[..., Block] = _final_concat,  # (seed, *parts) -> Block
    maps_per_round: Optional[int] = None,
    seed: Optional[int] = None,
    map_args: Optional[Sequence[tuple]] = None,  # extra per-ref args
) -> List[Any]:
    """Run the pipelined exchange; returns ``num_partitions`` block refs.

    Schedule per round r (reference's merge-factor pipeline):
      1. launch ``maps_per_round`` map tasks → P partials each;
      2. for every partition p, launch ``merge(prev_merged[p], *round_p)``;
      3. the merged refs feed round r+1 while its maps already run.
    The final round's merge applies ``final_fn`` (e.g. permute for
    random_shuffle) instead of plain concat.
    """
    P = num_partitions
    refs = list(input_refs)
    if not refs:
        return []
    R = maps_per_round or max(2, P)
    map_remote = ray_tpu.remote(map_fn).options(num_returns=P)
    merge_remote = ray_tpu.remote(_merge_blocks)
    final_remote = ray_tpu.remote(final_fn)

    merged: List[Any] = [None] * P
    indexed = list(enumerate(refs))
    rounds = [indexed[i:i + R] for i in range(0, len(indexed), R)]
    for r, round_refs in enumerate(rounds):
        # 1. map this round
        round_parts: List[List[Any]] = [[] for _ in range(P)]
        for idx, ref in round_refs:
            s = None if seed is None else seed + idx
            extra = map_args[idx] if map_args is not None else ()
            out = map_remote.remote(ref, P, s, *extra)
            if P == 1:
                out = [out]
            for p, part in enumerate(out):
                round_parts[p].append(part)
        last = r == len(rounds) - 1
        # 2. merge into the running state (overlaps next round's maps)
        for p in range(P):
            prior = [merged[p]] if merged[p] is not None else []
            if last:
                fs = None if seed is None else seed + 7919 * p
                merged[p] = final_remote.remote(fs, *(prior + round_parts[p]))
            else:
                merged[p] = merge_remote.remote(*(prior + round_parts[p]))
    return merged


def shuffle_map_split(block: Block, P: int, seed: Optional[int]):
    """Random-partition mapper for random_shuffle."""
    acc = BlockAccessor(block)
    rng = np.random.default_rng(seed)
    assignment = rng.integers(0, P, acc.num_rows())
    parts = [acc.take(list(np.nonzero(assignment == p)[0])) for p in range(P)]
    return tuple(parts) if P > 1 else parts[0]


def repartition_map_split(block: Block, P: int, seed: Optional[int],
                          offset: int, bounds: Sequence[int]):
    """Order-preserving splitter for repartition.

    The block covers global rows [offset, offset+rows); each output p owns
    the global range [bounds[p], bounds[p+1]) — this block contributes the
    intersection, so concatenating per-partition partials in input order
    reproduces the original global row order exactly.
    """
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    parts = []
    for p in range(P):
        lo = max(0, min(rows, bounds[p] - offset))
        hi = max(0, min(rows, bounds[p + 1] - offset))
        parts.append(block.slice(lo, max(0, hi - lo)))
    return tuple(parts) if P > 1 else parts[0]


def block_num_rows(block: Block) -> int:
    return BlockAccessor(block).num_rows()
