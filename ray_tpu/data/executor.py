"""Streaming executor — pull-based block pipeline over runtime tasks.

Analog of the reference's ``python/ray/data/_internal/execution/``
(``StreamingExecutor`` ``streaming_executor.py:51``, operators under
``operators/``, backpressure policies): the optimized plan compiles to a
chain of generators over block refs. Task map stages keep at most
``max_in_flight`` tasks outstanding (backpressure: a stage only submits when
the consumer pulls), so a Dataset never materializes fully unless an
all-to-all barrier requires it. ``compute="actors"`` runs an AUTOSCALING
actor pool (least-loaded dispatch, ``concurrency=(min, max)``, backlog-driven
scale-up, drain-time retirement — the ``ActorPoolMapOperator`` analog) whose
outstanding cap grows with the pool: ``max(2·actors, max_in_flight)``.
"""

from __future__ import annotations

from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional

import ray_tpu
from ray_tpu.data.plan import (
    AllToAll,
    InputData,
    Limit,
    LogicalOp,
    LogicalPlan,
    MapBlocks,
    Read,
    Union,
)

DEFAULT_MAX_IN_FLIGHT = 8
# Default object-plane budget for one streaming execution (all stages
# combined). The window of each stage adapts to measured block sizes so a
# pipeline of 100MB blocks holds far fewer in flight than one of 100KB
# blocks (reference: resource-budgeted operator scheduling,
# streaming_executor_state.py:494 + backpressure_policy/).
DEFAULT_MEMORY_BUDGET = 512 * 1024 * 1024


class _MemoryBudget:
    """Adaptive per-stage windows from a shared byte budget.

    Block sizes are learned online: sealed blocks register their size in
    the GCS object directory; inline-small blocks fall back to the running
    estimate. Each stage's window = share of the remaining budget divided
    by the size estimate, clamped to [1, max_in_flight]."""

    def __init__(self, total_bytes: int, max_in_flight: int):
        self.total = total_bytes
        self.max_in_flight = max_in_flight
        self._avg = 1 * 1024 * 1024  # prior: 1MB blocks
        self._samples = 0
        self._seen = 0
        self.stages = 1

    def note_block(self, ref) -> None:
        # Size probes are a GCS RPC — sample the first blocks to learn the
        # shape, then only every 32nd, so the estimate stays fresh without
        # a control-plane round trip per block on the streaming hot path.
        self._seen += 1
        if self._samples >= 8 and self._seen % 32 != 0:
            return
        size = _ref_size(ref)
        if size is None or size <= 0:
            return
        self._samples += 1
        alpha = max(0.1, 1.0 / self._samples)
        self._avg = (1 - alpha) * self._avg + alpha * size

    def window(self) -> int:
        per_stage = self.total / max(1, self.stages)
        return max(1, min(self.max_in_flight, int(per_stage // self._avg)))

    @property
    def avg_block_bytes(self) -> float:
        return self._avg


def _ref_size(ref) -> Optional[int]:
    """Size of a sealed block from the object directory (None if the block
    is inline-owned/unsealed — those are sub-100KiB by construction)."""
    try:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        locations = rt._gcs_rpc.call("locate_object", ref.id.binary())
        for _node, _addr, size in locations:
            if size:
                return int(size)
    except Exception:  # noqa: BLE001 — in-process runtime / GCS miss
        return None
    return None


def _run_read_task(task: Callable):
    return task()


def _apply_map(fn: Callable, block):
    return fn(block)


class _MapActorImpl:
    """Reusable map worker (reference: ``ActorPoolMapOperator``)."""

    def __init__(self, fn_ctor: Optional[Callable] = None):
        self._state = fn_ctor() if fn_ctor is not None else None

    def apply(self, fn: Callable, block):
        if self._state is not None:
            return fn(self._state, block)
        return fn(block)


def execute_streaming(
    plan: LogicalPlan, *, max_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
    memory_budget: int = DEFAULT_MEMORY_BUDGET,
    _stats: Optional[Dict[str, Any]] = None,
) -> Iterator[Any]:
    """Yield block refs as they become available. ``memory_budget`` bounds
    the object-plane bytes the whole pipeline holds in flight (adaptive
    per-stage windows; ``max_in_flight`` is the hard task-count cap)."""
    dag = plan.optimized().dag
    budget = _MemoryBudget(memory_budget, max_in_flight)
    budget.stages = _count_windowed_stages(dag)
    if _stats is not None:
        _stats["budget"] = budget
        _stats.setdefault("max_pending", 0)
    return _compile(dag, max_in_flight, budget, _stats)


def _count_windowed_stages(op: LogicalOp) -> int:
    n = 1 if isinstance(op, (Read, MapBlocks)) else 0
    return n + sum(_count_windowed_stages(i) for i in op.inputs)


def _note_pending(stats: Optional[Dict[str, Any]], n: int) -> None:
    if stats is not None and n > stats.get("max_pending", 0):
        stats["max_pending"] = n


def _compile(op: LogicalOp, max_in_flight: int, budget: _MemoryBudget,
             stats: Optional[Dict[str, Any]] = None) -> Iterator[Any]:
    if isinstance(op, InputData):
        return iter(list(op.block_refs))
    if isinstance(op, Read):
        read_remote = ray_tpu.remote(_run_read_task)

        def gen_read() -> Iterator[Any]:
            pending: deque = deque()
            tasks = iter(op.read_tasks)
            exhausted = False
            while True:
                while not exhausted and len(pending) < budget.window():
                    t = next(tasks, None)
                    if t is None:
                        exhausted = True
                        break
                    pending.append(read_remote.remote(t))
                _note_pending(stats, len(pending))
                if not pending:
                    return
                ref = pending.popleft()
                budget.note_block(ref)
                yield ref

        return gen_read()
    if isinstance(op, MapBlocks):
        upstream = _compile(op.inputs[0], max_in_flight, budget, stats)
        if op.compute == "actors":
            return _actor_map(op, upstream, max_in_flight)
        map_remote = ray_tpu.remote(_apply_map).options(num_cpus=op.num_cpus)

        def gen_map() -> Iterator[Any]:
            pending: deque = deque()
            exhausted = False
            while True:
                cap = op.concurrency or budget.window()
                while not exhausted and len(pending) < cap:
                    ref = next(upstream, None)
                    if ref is None:
                        exhausted = True
                        break
                    pending.append(map_remote.remote(op.fn, ref))
                _note_pending(stats, len(pending))
                if not pending:
                    return
                ref = pending.popleft()
                budget.note_block(ref)
                yield ref

        return gen_map()
    if isinstance(op, AllToAll):
        upstream = _compile(op.inputs[0], max_in_flight, budget, stats)

        def gen_barrier() -> Iterator[Any]:
            all_refs = list(upstream)
            yield from op.fn(all_refs)

        return gen_barrier()
    if isinstance(op, Union):
        streams = [_compile(i, max_in_flight, budget, stats)
                   for i in op.inputs]

        def gen_union() -> Iterator[Any]:
            for s in streams:
                yield from s

        return gen_union()
    if isinstance(op, Limit):
        upstream = _compile(op.inputs[0], max_in_flight, budget, stats)

        def gen_limit() -> Iterator[Any]:
            from ray_tpu.data.block import BlockAccessor

            remaining = op.n
            for ref in upstream:
                if remaining <= 0:
                    return
                block = ray_tpu.get(ref)
                acc = BlockAccessor(block)
                if acc.num_rows() <= remaining:
                    remaining -= acc.num_rows()
                    yield ray_tpu.put(block)
                else:
                    yield ray_tpu.put(acc.slice(0, remaining))
                    remaining = 0

        return gen_limit()
    raise TypeError(f"unknown logical op {type(op)}")


def _actor_map(op: MapBlocks, upstream: Iterator[Any], max_in_flight: int) -> Iterator[Any]:
    """Autoscaling actor pool (reference: ``ActorPoolMapOperator`` with the
    autoscaling policy of ``_internal/execution/autoscaler``): ``compute=
    "actors"`` with ``concurrency=(min, max)`` starts ``min`` actors, adds
    one whenever every actor already has ≥2 blocks in flight (backlog), and
    retires the emptiest actors once the input is exhausted and the backlog
    drains below the pool size. A plain int pins the pool size."""
    conc = op.concurrency
    if isinstance(conc, (tuple, list)):
        min_actors, max_actors = int(conc[0]), int(conc[1])
    else:
        min_actors = max_actors = int(conc or 2)
    actor_cls = ray_tpu.remote(_MapActorImpl)

    def spawn():
        return actor_cls.options(num_cpus=op.num_cpus).remote()

    actors: list = [spawn() for _ in range(max(1, min_actors))]
    # submitted-not-yet-yielded per actor (the executor's load signal)
    load: dict = {id(a): 0 for a in actors}
    # Refs YIELDED to the consumer whose tasks may still be running: killing
    # an actor drains its mailbox into ActorDiedError, which would poison
    # these (never-yielded pending refs are abandoned with the stream, so
    # they need no drain). Pruned via zero-timeout waits so the list stays
    # ~max_in_flight long and completed blocks aren't pinned forever.
    yielded: dict = {id(a): [] for a in actors}

    def _prune(actor) -> None:
        refs = yielded.get(id(actor))
        if refs:
            try:
                _, not_ready = ray_tpu.wait(refs, num_returns=len(refs),
                                            timeout=0)
                yielded[id(actor)] = not_ready
            except Exception:  # noqa: BLE001
                pass

    def _safe_kill(actor) -> None:
        _prune(actor)
        refs = yielded.pop(id(actor), [])
        if refs:
            try:
                ray_tpu.wait(refs, num_returns=len(refs), timeout=60.0)
            except Exception:  # noqa: BLE001
                pass
        try:
            ray_tpu.kill(actor)
        except Exception:  # noqa: BLE001
            pass

    def gen() -> Iterator[Any]:
        pending: deque = deque()  # (ref, actor)
        exhausted = False
        try:
            while True:
                while not exhausted and len(pending) < max(
                        2 * len(actors), max_in_flight):
                    ref = next(upstream, None)
                    if ref is None:
                        exhausted = True
                        break
                    target = min(actors, key=lambda a: load[id(a)])
                    if load[id(target)] >= 2 and len(actors) < max_actors:
                        target = spawn()
                        actors.append(target)
                        load[id(target)] = 0
                        yielded[id(target)] = []
                    out_ref = target.apply.remote(op.fn, ref)
                    load[id(target)] += 1
                    pending.append((out_ref, target))
                if not pending:
                    return
                out, actor = pending.popleft()
                load[id(actor)] -= 1
                _prune(actor)
                if id(actor) in yielded:
                    yielded[id(actor)].append(out)
                # Retire surplus idle actors while the tail drains.
                if exhausted and len(actors) > min_actors:
                    idle = [a for a in actors if load[id(a)] == 0]
                    for a in idle[:len(actors) - max(1, min_actors)]:
                        actors.remove(a)
                        load.pop(id(a), None)
                        _safe_kill(a)
                yield out
        finally:
            for a in actors:
                _safe_kill(a)

    return gen()
