"""DataIterator — per-rank Train ingest.

Analog of the reference's ``python/ray/data/iterator.py`` (``DataIterator``,
``iter_torch_batches``): the TPU variant is ``iter_jax_batches`` — host numpy
batches placed on device under a caller-provided sharding (the idiomatic
host→HBM feed: no framework tensors in the object store, placement decided by
the consumer's mesh).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import numpy as np


def batches_from_blocks(blocks, *, batch_size: int,
                        batch_format: str = "numpy",
                        drop_last: bool = False) -> Iterator[Any]:
    """Re-batch a stream of pyarrow blocks into fixed-size batches (the
    carry/slice loop shared by Dataset.iter_batches and the coordinated
    streaming-split iterators)."""
    from ray_tpu.data.block import BlockAccessor
    from ray_tpu.data.dataset import _format_batch

    carry = None
    for block in blocks:
        if carry is not None and carry.num_rows:
            block = BlockAccessor.concat([carry, block])
            carry = None
        acc = BlockAccessor(block)
        n = acc.num_rows()
        pos = 0
        while n - pos >= batch_size:
            yield _format_batch(acc.slice(pos, pos + batch_size),
                                batch_format)
            pos += batch_size
        if pos < n:
            carry = acc.slice(pos, n)
    if carry is not None and carry.num_rows and not drop_last:
        yield _format_batch(carry, batch_format)


class JaxBatchesMixin:
    """``iter_jax_batches`` over any ``iter_batches`` implementation."""

    def iter_jax_batches(
        self,
        *,
        batch_size: int = 1024,
        sharding: Optional[Any] = None,
        dtypes: Optional[Dict[str, Any]] = None,
        drop_last: bool = True,
        collate_fn: Optional[Callable[[Dict[str, np.ndarray]], Any]] = None,
    ) -> Iterator[Any]:
        """Numpy batches → device arrays (optionally under ``sharding``)."""
        import jax
        import jax.numpy as jnp

        for batch in self.iter_batches(batch_size=batch_size,
                                       drop_last=drop_last):
            if collate_fn is not None:
                yield collate_fn(batch)
                continue
            out = {}
            for k, v in batch.items():
                arr = jnp.asarray(v, dtype=dtypes.get(k) if dtypes else None)
                if sharding is not None:
                    arr = jax.device_put(arr, sharding)
                out[k] = arr
            yield out


class DataIterator(JaxBatchesMixin):
    def __init__(self, dataset):
        self._ds = dataset

    def iter_batches(self, **kw) -> Iterator[Dict[str, np.ndarray]]:
        return self._ds.iter_batches(**kw)

    def iter_rows(self):
        return self._ds.iter_rows()

    def materialize(self):
        return self._ds.materialize()

    def stats(self) -> str:
        return f"DataIterator over {self._ds!r}"
