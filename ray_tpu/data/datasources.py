"""File datasources beyond the columnar formats.

Broadens the source coverage toward the reference's ``python/ray/data/
datasource/`` family: text, raw binary files, images (PIL), and TFRecords —
the formats LLM/vision ingest actually touches. Each reader produces one
read task per file (parallel, streaming through the executor); writers
round-trip for tests.

TFRecord framing (``tensorflow/core/lib/io/record_writer.cc``): each record
is ``len:uint64le | masked_crc32c(len):uint32le | data | masked_crc32c(data)
:uint32le``; the CRC is Castagnoli with TensorFlow's rotate-right masking —
implemented here table-driven so files interoperate with real TF readers.
"""

from __future__ import annotations

import os
import struct
from typing import List, Optional, Union

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.dataset import Dataset, _expand_paths
from ray_tpu.data.plan import LogicalPlan, Read

# ---------------------------------------------------------------------------
# crc32c (Castagnoli), table-driven — tiny and dependency-free
# ---------------------------------------------------------------------------

_CRC_TABLE: Optional[List[int]] = None


def _crc32c_table() -> List[int]:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ poly if crc & 1 else crc >> 1
            table.append(crc)
        _CRC_TABLE = table
    return _CRC_TABLE


try:  # accelerated CRC when a native wheel is present
    import google_crc32c as _gcrc
except ImportError:
    _gcrc = None


def crc32c(data: bytes) -> int:
    if _gcrc is not None:
        return _gcrc.value(bytes(data))
    table = _crc32c_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# TFRecords
# ---------------------------------------------------------------------------

def _read_tfrecord_file(path: str) -> List[bytes]:
    records = []
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if not header:
                break
            if len(header) < 12:
                raise ValueError(f"truncated TFRecord header in {path}")
            (length,) = struct.unpack("<Q", header[:8])
            (len_crc,) = struct.unpack("<I", header[8:])
            if len_crc != _masked_crc(header[:8]):
                raise ValueError(f"corrupt TFRecord length crc in {path}")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"truncated TFRecord payload in {path}")
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4:
                raise ValueError(f"truncated TFRecord data crc in {path}")
            (data_crc,) = struct.unpack("<I", crc_bytes)
            if data_crc != _masked_crc(data):
                raise ValueError(f"corrupt TFRecord data crc in {path}")
            records.append(data)
    return records


def read_tfrecords(paths: Union[str, List[str]]) -> Dataset:
    """Rows of ``{"data": bytes}`` — decode (e.g. tf.Example protos) with a
    downstream ``map``/``map_batches``."""
    files = _expand_paths(paths, ".tfrecord")

    def make_task(f: str):
        def read():
            recs = _read_tfrecord_file(f)
            return pa.table({"data": pa.array(recs, pa.binary())})

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))


def write_tfrecords(ds: Dataset, path: str, *, column: str = "data") -> None:
    os.makedirs(path, exist_ok=True)
    for i, block in enumerate(ds.iter_blocks()):
        with open(os.path.join(path, f"part-{i:05d}.tfrecord"), "wb") as f:
            for row in BlockAccessor(block).iter_rows():
                data = row[column]
                if not isinstance(data, (bytes, bytearray)):
                    data = bytes(data)
                header = struct.pack("<Q", len(data))
                f.write(header)
                f.write(struct.pack("<I", _masked_crc(header)))
                f.write(data)
                f.write(struct.pack("<I", _masked_crc(data)))


# ---------------------------------------------------------------------------
# text / binary / images
# ---------------------------------------------------------------------------

def read_text(paths: Union[str, List[str]], *, encoding: str = "utf-8") -> Dataset:
    """One row per line: ``{"text": str}`` (reference: ``read_text``)."""
    files = _expand_paths(paths, ".txt")

    def make_task(f: str):
        def read():
            with open(f, encoding=encoding) as fh:
                lines = [line.rstrip("\r\n") for line in fh]
            return pa.table({"text": pa.array(lines, pa.string())})

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))


def read_binary_files(paths: Union[str, List[str]],
                      *, include_paths: bool = False) -> Dataset:
    """One row per file: ``{"bytes": ..., ["path"]}``."""
    files = _expand_paths(paths, "")

    def make_task(f: str):
        def read():
            with open(f, "rb") as fh:
                payload = fh.read()
            cols = {"bytes": pa.array([payload], pa.binary())}
            if include_paths:
                cols["path"] = pa.array([f], pa.string())
            return pa.table(cols)

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))


_IMAGE_SUFFIXES = (".png", ".jpg", ".jpeg", ".bmp", ".gif")


def read_images(paths: Union[str, List[str]], *, size=None,
                mode: Optional[str] = None,
                include_paths: bool = False) -> Dataset:
    """One row per image: ``{"image": HxWxC uint8, ["path"]}`` via PIL
    (reference: ``datasource/image_datasource.py``)."""
    if isinstance(paths, str) and os.path.isdir(paths):
        files = sorted(
            os.path.join(paths, f) for f in os.listdir(paths)
            if f.lower().endswith(_IMAGE_SUFFIXES))
        if not files:
            raise FileNotFoundError(f"no images under {paths}")
    else:
        files = _expand_paths(paths, "")

    def make_task(f: str):
        def read():
            from PIL import Image

            img = Image.open(f)
            if mode is not None:
                img = img.convert(mode)
            if size is not None:
                img = img.resize(size)
            arr = np.asarray(img)
            cols = {"image": arr[None, ...]}
            block = BlockAccessor.from_numpy(cols)
            if include_paths:
                table = block
                return table.append_column("path", pa.array([f], pa.string()))
            return block

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))
