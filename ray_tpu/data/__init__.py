"""ray_tpu.data — lazy streaming datasets over the distributed runtime.

Public surface mirrors ``ray.data``: read_* constructors, Dataset transforms,
streaming execution, per-rank iterators for Train ingest.
"""

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import (
    Dataset,
    GroupedData,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_csv,
    read_json,
    read_numpy,
    read_parquet,
)
from ray_tpu.data.connectors import (
    read_mongo,
    read_parquet_partitioned,
    read_sql,
    read_webdataset,
    write_parquet_partitioned,
    write_webdataset,
)
from ray_tpu.data.datasources import (
    read_binary_files,
    read_images,
    read_text,
    read_tfrecords,
    write_tfrecords,
)
from ray_tpu.data.iterator import DataIterator

__all__ = [
    "Dataset",
    "GroupedData",
    "DataIterator",
    "Block",
    "BlockAccessor",
    "range",
    "from_items",
    "from_pandas",
    "from_numpy",
    "from_arrow",
    "read_parquet",
    "read_csv",
    "read_json",
    "read_numpy",
    "read_text",
    "read_binary_files",
    "read_images",
    "read_tfrecords",
    "write_tfrecords",
    "read_webdataset",
    "write_webdataset",
    "read_sql",
    "read_parquet_partitioned",
    "write_parquet_partitioned",
    "read_mongo",
]
