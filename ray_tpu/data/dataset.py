"""Dataset — lazy, streaming, distributed columnar data.

Analog of the reference's ``python/ray/data/dataset.py`` (5,142 lines) +
``read_api.py`` + shuffle scheduling (``_internal/planner/exchange/``): a
Dataset wraps a LogicalPlan over block refs; transforms append logical ops;
consumption triggers streaming execution. Shuffle/sort/repartition use the
two-stage map/reduce exchange over tasks+objects the reference uses
(``push_based_shuffle_task_scheduler.py`` — simplified to its pull-based
variant here).
"""

from __future__ import annotations

import builtins
import functools
import glob as _glob
import os
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union as TUnion

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import Batch, Block, BlockAccessor, Row
from ray_tpu.data.executor import execute_streaming
from ray_tpu.data.plan import (
    AllToAll,
    InputData,
    Limit,
    LogicalPlan,
    MapBlocks,
    Read,
    Union,
)

DEFAULT_BATCH_SIZE = 1024


class Dataset:
    def __init__(self, plan: LogicalPlan):
        self._plan = plan

    # ------------------------------------------------------------------ meta
    def __repr__(self):
        return f"Dataset(plan={self._plan.dag.name})"

    def schema(self) -> Optional[pa.Schema]:
        for ref in execute_streaming(self._plan):
            block = ray_tpu.get(ref)
            if block.num_rows:
                return block.schema
        return None

    def columns(self) -> List[str]:
        s = self.schema()
        return list(s.names) if s else []

    def count(self) -> int:
        n = 0
        for ref in execute_streaming(self._plan):
            n += BlockAccessor(ray_tpu.get(ref)).num_rows()
        return n

    def num_blocks(self) -> int:
        return sum(1 for _ in execute_streaming(self._plan))

    def size_bytes(self) -> int:
        return sum(
            BlockAccessor(ray_tpu.get(r)).size_bytes() for r in execute_streaming(self._plan)
        )

    # ------------------------------------------------------------ transforms
    def _append(self, op) -> "Dataset":
        return Dataset(LogicalPlan(op))

    def map_batches(
        self,
        fn: Callable[[Batch], TUnion[Batch, pa.Table]],
        *,
        batch_format: str = "numpy",
        compute: str = "tasks",
        concurrency: Optional[int] = None,
        num_cpus: float = 1.0,
        **_ignored,
    ) -> "Dataset":
        def transform(block: Block) -> Block:
            acc = BlockAccessor(block)
            if batch_format == "numpy":
                out = fn(acc.to_numpy())
            elif batch_format == "pandas":
                out = fn(acc.to_pandas())
            elif batch_format in ("pyarrow", "arrow"):
                out = fn(block)
            else:
                raise ValueError(f"unknown batch_format {batch_format}")
            return BlockAccessor.batch_to_block(out)

        return self._append(
            MapBlocks(
                self._plan.dag, transform, label="MapBatches",
                compute=compute, num_cpus=num_cpus, concurrency=concurrency,
            )
        )

    def map(self, fn: Callable[[Row], Row], **kw) -> "Dataset":
        def transform(block: Block) -> Block:
            rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
            return BlockAccessor.from_items(rows)

        return self._append(MapBlocks(self._plan.dag, transform, label="Map"))

    def flat_map(self, fn: Callable[[Row], List[Row]], **kw) -> "Dataset":
        def transform(block: Block) -> Block:
            rows: List[Row] = []
            for r in BlockAccessor(block).iter_rows():
                rows.extend(fn(r))
            return BlockAccessor.from_items(rows)

        return self._append(MapBlocks(self._plan.dag, transform, label="FlatMap"))

    def filter(self, fn: Callable[[Row], bool], **kw) -> "Dataset":
        def transform(block: Block) -> Block:
            acc = BlockAccessor(block)
            keep = [i for i, r in enumerate(acc.iter_rows()) if fn(r)]
            return acc.take(keep)

        return self._append(MapBlocks(self._plan.dag, transform, label="Filter"))

    def select_columns(self, cols: List[str], **kw) -> "Dataset":
        return self._append(
            MapBlocks(self._plan.dag, lambda b: BlockAccessor(b).select(cols), label="Select")
        )

    def drop_columns(self, cols: List[str], **kw) -> "Dataset":
        def transform(block: Block) -> Block:
            keep = [c for c in block.column_names if c not in cols]
            return block.select(keep)

        return self._append(MapBlocks(self._plan.dag, transform, label="Drop"))

    def add_column(self, name: str, fn: Callable[[Batch], np.ndarray], **kw) -> "Dataset":
        def transform(block: Block) -> Block:
            col = fn(BlockAccessor(block).to_numpy())
            return block.append_column(name, pa.array(np.asarray(col)))

        return self._append(MapBlocks(self._plan.dag, transform, label="AddColumn"))

    def limit(self, n: int) -> "Dataset":
        return self._append(Limit(self._plan.dag, n))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._append(Union([self._plan.dag] + [o._plan.dag for o in others]))

    # ------------------------------------------------------------ all-to-all
    def repartition(self, num_blocks: int) -> "Dataset":
        """Order-preserving push-based exchange: a metadata pass computes
        global row offsets, mappers slice each block against the global
        output boundaries, and partials merge in input order — rows keep
        their global order (``data/shuffle.py``)."""

        def do(all_refs: List[Any]) -> List[Any]:
            from ray_tpu.data.shuffle import (
                block_num_rows,
                push_based_shuffle,
                repartition_map_split,
            )

            count_remote = ray_tpu.remote(block_num_rows)
            counts = ray_tpu.get([count_remote.remote(r) for r in all_refs])
            total = sum(counts)
            P = max(1, num_blocks)
            bounds = [p * total // P for p in builtins.range(P + 1)]
            offsets = np.concatenate([[0], np.cumsum(counts)[:-1]])
            return push_based_shuffle(
                all_refs, num_partitions=P, map_fn=repartition_map_split,
                map_args=[(int(o), bounds) for o in offsets],
            )

        return self._append(AllToAll(self._plan.dag, do, "Repartition"))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Push-based shuffle exchange (reference:
        ``_internal/planner/exchange/push_based_shuffle_task_scheduler.py``):
        mappers random-partition each block; partials merge into the running
        reducer state round by round, so peak reducer memory is one merged
        block + one round of partials — not all M map outputs at once."""

        def do(all_refs: List[Any]) -> List[Any]:
            from ray_tpu.data.shuffle import (
                _merge_and_permute,
                push_based_shuffle,
                shuffle_map_split,
            )

            return push_based_shuffle(
                all_refs, num_partitions=max(1, len(all_refs)),
                map_fn=shuffle_map_split, final_fn=_merge_and_permute,
                seed=seed,
            )

        return self._append(AllToAll(self._plan.dag, do, "RandomShuffle"))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        def do(all_refs: List[Any]) -> List[Any]:
            blocks = [ray_tpu.get(r) for r in all_refs]
            table = BlockAccessor.concat(blocks)
            order = "descending" if descending else "ascending"
            out = table.sort_by([(key, order)])
            return [ray_tpu.put(out)]

        return self._append(AllToAll(self._plan.dag, do, "Sort"))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        refs = list(execute_streaming(self._plan))
        shards: List[List[Any]] = [[] for _ in builtins.range(n)]
        if equal:
            blocks = [ray_tpu.get(r) for r in refs]
            table = BlockAccessor.concat(blocks)
            rows = table.num_rows - table.num_rows % n
            per = rows // n
            for i in builtins.range(n):
                shards[i].append(ray_tpu.put(table.slice(i * per, per)))
        else:
            for i, r in enumerate(refs):
                shards[i % n].append(r)
        return [Dataset(LogicalPlan(InputData(s))) for s in shards]

    def zip(self, other: "Dataset") -> "Dataset":
        def do(all_refs: List[Any]) -> List[Any]:
            left = BlockAccessor.concat([ray_tpu.get(r) for r in all_refs])
            right = BlockAccessor.concat(
                [ray_tpu.get(r) for r in execute_streaming(other._plan)]
            )
            if left.num_rows != right.num_rows:
                raise ValueError("zip requires equal row counts")
            cols = {c: left.column(c) for c in left.column_names}
            for c in right.column_names:
                name = c if c not in cols else f"{c}_1"
                cols[name] = right.column(c)
            return [ray_tpu.put(pa.table(cols))]

        return self._append(AllToAll(self._plan.dag, do, "Zip"))

    def random_sample(self, fraction: float, *, seed: Optional[int] = None) -> "Dataset":
        def transform(block: Block) -> Block:
            acc = BlockAccessor(block)
            rng = np.random.default_rng(seed)
            mask = rng.random(acc.num_rows()) < fraction
            return acc.take(list(np.nonzero(mask)[0]))

        return self._append(MapBlocks(self._plan.dag, transform, label="Sample"))

    # ----------------------------------------------------------- consumption
    def iter_blocks(self) -> Iterator[Block]:
        for ref in execute_streaming(self._plan):
            yield ray_tpu.get(ref)

    def iter_rows(self) -> Iterator[Row]:
        for block in self.iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def iter_batches(
        self,
        *,
        batch_size: int = DEFAULT_BATCH_SIZE,
        batch_format: str = "numpy",
        drop_last: bool = False,
    ) -> Iterator[TUnion[Batch, pa.Table]]:
        from ray_tpu.data.iterator import batches_from_blocks

        yield from batches_from_blocks(
            self.iter_blocks(), batch_size=batch_size,
            batch_format=batch_format, drop_last=drop_last)

    def take(self, n: int = 20) -> List[Row]:
        out: List[Row] = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> List[Row]:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        return BlockAccessor.concat(list(self.iter_blocks())).to_pandas()

    def to_arrow(self) -> pa.Table:
        return BlockAccessor.concat(list(self.iter_blocks()))

    def materialize(self) -> "Dataset":
        refs = list(execute_streaming(self._plan))
        return Dataset(LogicalPlan(InputData(refs)))

    def iterator(self):
        from ray_tpu.data.iterator import DataIterator

        return DataIterator(self)

    def streaming_split(self, n: int, *, equal: bool = False):
        """N iterators over ONE coordinated streaming execution with
        DYNAMIC block assignment (work stealing) — not a static split
        (reference: _internal/iterator/stream_split_iterator.py).
        ``equal=True`` keeps consumers within one block of each other."""
        from ray_tpu.data.stream_split import make_stream_split

        return make_stream_split(self._plan, n, equal)

    # ---------------------------------------------------------------- writes
    def write_parquet(self, path: str) -> None:
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                pq.write_table(block, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str) -> None:
        import pyarrow.csv as pcsv

        os.makedirs(path, exist_ok=True)
        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                pcsv.write_csv(block, os.path.join(path, f"part-{i:05d}.csv"))

    def write_json(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        import json

        for i, block in enumerate(self.iter_blocks()):
            if block.num_rows:
                with open(os.path.join(path, f"part-{i:05d}.jsonl"), "w") as f:
                    for row in BlockAccessor(block).iter_rows():
                        f.write(json.dumps(_jsonable(row)) + "\n")

    # ------------------------------------------------------------ aggregates
    def sum(self, on: str):
        return self._agg("sum", on)

    def min(self, on: str):
        return self._agg("min", on)

    def max(self, on: str):
        return self._agg("max", on)

    def mean(self, on: str):
        import pyarrow.compute as pc

        total, count = 0.0, 0
        for block in self.iter_blocks():
            if block.num_rows:
                total += pc.sum(block.column(on)).as_py() or 0
                count += block.num_rows
        return total / count if count else None

    def std(self, on: str):
        vals = np.concatenate(
            [BlockAccessor(b).to_numpy([on])[on] for b in self.iter_blocks() if b.num_rows]
        )
        return float(np.std(vals, ddof=1))

    def _agg(self, op: str, on: str):
        import pyarrow.compute as pc

        vals = []
        for block in self.iter_blocks():
            if block.num_rows:
                vals.append(getattr(pc, op)(block.column(on)).as_py())
        if not vals:
            return None
        if op == "sum":
            return sum(vals)
        return max(vals) if op == "max" else min(vals)


def _jsonable(row: Row) -> Row:
    out = {}
    for k, v in row.items():
        if isinstance(v, (np.generic,)):
            v = v.item()
        elif isinstance(v, np.ndarray):
            v = v.tolist()
        out[k] = v
    return out


def _format_batch(block: Block, batch_format: str):
    acc = BlockAccessor(block)
    if batch_format == "numpy":
        return acc.to_numpy()
    if batch_format == "pandas":
        return acc.to_pandas()
    if batch_format in ("pyarrow", "arrow"):
        return block
    raise ValueError(f"unknown batch_format {batch_format}")


class GroupedData:
    """Reference: ``python/ray/data/grouped_data.py``."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _grouped(self) -> Dict[Any, pa.Table]:
        table = self._ds.to_arrow()
        import pyarrow.compute as pc

        keys = table.column(self._key).to_pylist()
        idx_by_key: Dict[Any, List[int]] = {}
        for i, k in enumerate(keys):
            idx_by_key.setdefault(k, []).append(i)
        return {k: table.take(pa.array(ix)) for k, ix in sorted(idx_by_key.items(), key=lambda kv: str(kv[0]))}

    def count(self) -> Dataset:
        rows = [
            {self._key: k, "count()": t.num_rows} for k, t in self._grouped().items()
        ]
        return from_items(rows)

    def _agg(self, op: str, on: str, label: str) -> Dataset:
        import pyarrow.compute as pc

        rows = []
        for k, t in self._grouped().items():
            rows.append({self._key: k, label: getattr(pc, op)(t.column(on)).as_py()})
        return from_items(rows)

    def sum(self, on: str) -> Dataset:
        return self._agg("sum", on, f"sum({on})")

    def min(self, on: str) -> Dataset:
        return self._agg("min", on, f"min({on})")

    def max(self, on: str) -> Dataset:
        return self._agg("max", on, f"max({on})")

    def mean(self, on: str) -> Dataset:
        return self._agg("mean", on, f"mean({on})")

    def map_groups(self, fn: Callable[[pa.Table], Any]) -> Dataset:
        outs = []
        for _, t in self._grouped().items():
            out = fn(t)
            outs.append(BlockAccessor.batch_to_block(out))
        refs = [ray_tpu.put(b) for b in outs]
        return Dataset(LogicalPlan(InputData(refs)))


# ---------------------------------------------------------------------------
# read_api (reference: python/ray/data/read_api.py)
# ---------------------------------------------------------------------------

def from_items(items: List[Any], *, override_num_blocks: Optional[int] = None) -> Dataset:
    n_blocks = override_num_blocks or max(1, min(len(items) // 1000, 64)) if items else 1
    chunks = np.array_split(np.arange(len(items)), n_blocks)
    refs = [
        ray_tpu.put(BlockAccessor.from_items([items[i] for i in chunk]))
        for chunk in chunks
        if len(chunk)
    ] or [ray_tpu.put(BlockAccessor.from_items([]))]
    return Dataset(LogicalPlan(InputData(refs, num_rows=len(items))))


def range(n: int, *, override_num_blocks: Optional[int] = None) -> Dataset:  # noqa: A001
    n_blocks = override_num_blocks or max(1, min(n // 50_000, 64))
    bounds = np.linspace(0, n, n_blocks + 1, dtype=np.int64)

    def make_task(lo: int, hi: int):
        def read():
            return BlockAccessor.from_numpy({"id": np.arange(lo, hi, dtype=np.int64)})

        return read

    tasks = [make_task(int(lo), int(hi)) for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo]
    return Dataset(LogicalPlan(Read(tasks, num_rows=n)))


def from_pandas(df) -> Dataset:
    return Dataset(LogicalPlan(InputData([ray_tpu.put(BlockAccessor.from_pandas(df))])))


def from_numpy(arr: TUnion[np.ndarray, Dict[str, np.ndarray]]) -> Dataset:
    return Dataset(LogicalPlan(InputData([ray_tpu.put(BlockAccessor.from_numpy(arr))])))


def from_arrow(table: pa.Table) -> Dataset:
    return Dataset(LogicalPlan(InputData([ray_tpu.put(table)])))


def _expand_paths(paths: TUnion[str, List[str]], suffix: str) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(_glob.glob(os.path.join(p, f"*{suffix}"))))
        elif "*" in p:
            files.extend(sorted(_glob.glob(p)))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no files match {paths}")
    return files


def read_parquet(paths: TUnion[str, List[str]], **kw) -> Dataset:
    files = _expand_paths(paths, ".parquet")

    def make_task(f: str):
        def read():
            import pyarrow.parquet as pq

            return pq.read_table(f)

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))


def read_csv(paths: TUnion[str, List[str]], **kw) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def make_task(f: str):
        def read():
            import pyarrow.csv as pcsv

            return pcsv.read_csv(f)

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))


def read_json(paths: TUnion[str, List[str]], **kw) -> Dataset:
    files = _expand_paths(paths, ".jsonl")

    def make_task(f: str):
        def read():
            import json

            with open(f) as fh:
                rows = [json.loads(line) for line in fh if line.strip()]
            return BlockAccessor.from_items(rows)

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))


def read_numpy(paths: TUnion[str, List[str]], **kw) -> Dataset:
    files = _expand_paths(paths, ".npy")

    def make_task(f: str):
        def read():
            return BlockAccessor.from_numpy(np.load(f))

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))
