"""Coordinated streaming split — N consumers over ONE executing pipeline.

Analog of the reference's
``python/ray/data/_internal/iterator/stream_split_iterator.py``: a
coordinator actor owns the streaming execution and assigns output blocks to
consumers DYNAMICALLY on demand (first-come-first-served work stealing), so
a slow Train rank doesn't strand blocks pre-assigned to it the way a static
``split()`` does. Every block goes to exactly one consumer.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterator, List, Optional

import numpy as np
import pyarrow as pa

import ray_tpu
from ray_tpu.data.block import BlockAccessor


class _SplitCoordinatorImpl:
    """Owns one streaming execution; hands each output block to whichever
    consumer asks next. ``equal=True`` throttles a consumer that runs more
    than one block ahead of the most-behind ACTIVE consumer (ranks that
    called ``finish`` stop counting, so stragglers can't wedge the rest)."""

    def __init__(self, plan, n: int, equal: bool):
        from ray_tpu.data.executor import execute_streaming

        self._it: Iterator[Any] = execute_streaming(plan)
        self._n = n
        self._equal = equal
        self._counts = [0] * n
        self._active = [True] * n
        self._lock = threading.Lock()

    def get_next(self, idx: int) -> Optional[list]:
        """Next block for consumer ``idx`` (boxed so the ref rides the
        borrower protocol), or None at end of stream."""
        with self._lock:
            if self._equal:
                floor = min(
                    (c for c, a in zip(self._counts, self._active) if a),
                    default=self._counts[idx])
                if self._counts[idx] - floor > 1:
                    return ["__wait__"]
            ref = next(self._it, None)
            if ref is None:
                self._active[idx] = False
                return None
            self._counts[idx] += 1
            return [ref]

    def finish(self, idx: int) -> bool:
        with self._lock:
            self._active[idx] = False
        return True

    def counts(self) -> List[int]:
        with self._lock:
            return list(self._counts)


from ray_tpu.data.iterator import JaxBatchesMixin


class StreamSplitDataIterator(JaxBatchesMixin):
    """One consumer's view of a coordinated split (duck-types
    ``DataIterator``)."""

    def __init__(self, coordinator, idx: int):
        self._coord = coordinator
        self._idx = idx

    # Max seconds to sit behind the equal-split throttle before giving up —
    # a peer that crashed (or stopped iterating) without finish() must not
    # wedge healthy consumers forever.
    EQUAL_WAIT_TIMEOUT_S = 300.0

    # -- block stream --------------------------------------------------------
    def iter_blocks(self) -> Iterator[pa.Table]:
        import time as _time

        throttle_since = None
        while True:
            box = ray_tpu.get(self._coord.get_next.remote(self._idx),
                              timeout=600)
            if box is None:
                return
            if box[0] == "__wait__":  # equal-split throttle
                now = _time.time()
                throttle_since = throttle_since or now
                if now - throttle_since > self.EQUAL_WAIT_TIMEOUT_S:
                    raise TimeoutError(
                        f"streaming split {self._idx} throttled for "
                        f"{self.EQUAL_WAIT_TIMEOUT_S}s behind a consumer "
                        "that stopped iterating (call finish() on ranks "
                        "that end early)")
                _time.sleep(0.02)
                continue
            throttle_since = None
            yield ray_tpu.get(box[0], timeout=600)

    def iter_batches(self, *, batch_size: int = 1024,
                     batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterator[Dict[str, np.ndarray]]:
        from ray_tpu.data.iterator import batches_from_blocks

        return batches_from_blocks(self.iter_blocks(), batch_size=batch_size,
                                   batch_format=batch_format,
                                   drop_last=drop_last)

    def iter_rows(self):
        for block in self.iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def finish(self) -> None:
        """This rank is done consuming (frees the equal-split throttle)."""
        ray_tpu.get(self._coord.finish.remote(self._idx), timeout=60)

    def stats(self) -> str:
        return f"StreamSplitDataIterator(split={self._idx})"


def make_stream_split(plan, n: int, equal: bool) -> List[StreamSplitDataIterator]:
    coord_cls = ray_tpu.remote(_SplitCoordinatorImpl)
    coordinator = coord_cls.options(num_cpus=0).remote(plan, n, equal)
    return [StreamSplitDataIterator(coordinator, i) for i in range(n)]
