"""High-value data connectors: WebDataset, SQL, partitioned Parquet, Mongo.

Broadens source coverage toward the reference's
``python/ray/data/datasource/`` family with the connectors TPU training
workloads actually hit (VERDICT r4 missing #4):

- **WebDataset** (``webdataset_datasource.py``): tar shards where each
  sample is the group of members sharing a basename stem (``0001.jpg`` +
  ``0001.cls`` + ``0001.json`` → one row) — the de-facto large-scale image/
  multimodal training layout. One read task per shard, streaming through
  the executor.
- **SQL** (``sql_datasource.py``): any DB-API 2.0 connection via a
  ``connection_factory`` (sqlite3 in tests); optional ``shard_keys``
  parallelism by hashing a column into N disjoint WHERE-clauses.
- **Partitioned Parquet** with hive-style partition PRUNING
  (``parquet_datasource.py`` + ``partitioning.py``): ``key=value`` path
  segments become columns, and a row-filter over partition values prunes
  whole files before a byte is read.
- **MongoDB** (``mongo_datasource.py``): pymongo collection → arrow blocks,
  split by ``_id`` range; the client is injectable so the connector is
  testable without a server (pymongo is not in the image).
"""

from __future__ import annotations

import io
import json
import os
import tarfile
from typing import Any, Callable, Dict, List, Optional, Union

import numpy as np
import pyarrow as pa

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.dataset import Dataset, _expand_paths
from ray_tpu.data.plan import LogicalPlan, Read

# ---------------------------------------------------------------------------
# WebDataset
# ---------------------------------------------------------------------------

_WDS_AUTO_DECODE = {
    ".txt": lambda b: b.decode("utf-8"),
    ".cls": lambda b: int(b.decode("utf-8").strip()),
    ".json": lambda b: json.loads(b.decode("utf-8")),
}


def _decode_member(suffix: str, payload: bytes, decode_images: bool):
    if suffix == ".npy":
        return np.load(io.BytesIO(payload), allow_pickle=False)
    if suffix in _WDS_AUTO_DECODE:
        return _WDS_AUTO_DECODE[suffix](payload)
    if decode_images and suffix in (".jpg", ".jpeg", ".png", ".bmp"):
        from PIL import Image

        return np.asarray(Image.open(io.BytesIO(payload)))
    return payload  # raw bytes (bin/unknown — caller maps further)


_WDS_TYPED_SUFFIXES = (".txt", ".cls", ".json", ".npy")


def _split_member(base: str):
    """WebDataset naming: the sample key is the name up to the FIRST dot;
    everything after is the (possibly dotted) extension. A trailing typed
    suffix (``caption.txt``, ``meta.json``, ``emb.npy``) carries the
    value's TYPE while the rest names the column — how the writer
    round-trips str/int/dict/ndarray columns with arbitrary names."""
    stem, _, ext = base.partition(".")
    parts = ext.split(".")
    type_suffix = "." + parts[-1].lower() if parts else ""
    if len(parts) > 1 and type_suffix in _WDS_TYPED_SUFFIXES:
        return stem, ".".join(parts[:-1]), type_suffix
    return stem, ext, type_suffix


def read_webdataset(paths: Union[str, List[str]], *,
                    decode_images: bool = False,
                    suffixes: Optional[List[str]] = None) -> Dataset:
    """Rows of ``{"__key__": stem, "<ext>": value, ...}`` per tar sample.

    ``decode_images=True`` decodes jpg/png members to HxWxC uint8 via PIL;
    ``suffixes`` restricts which member extensions are loaded (dotted,
    e.g. ``[".jpg", ".cls"]``).
    """
    files = _expand_paths(paths, ".tar")

    def make_task(f: str):
        def read():
            samples: Dict[str, Dict[str, Any]] = {}
            order: List[str] = []
            with tarfile.open(f, "r") as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    base = os.path.basename(member.name)
                    stem, column, type_suffix = _split_member(base)
                    if (suffixes is not None
                            and "." + column not in suffixes
                            and type_suffix not in suffixes):
                        continue
                    payload = tar.extractfile(member).read()
                    if stem not in samples:
                        samples[stem] = {"__key__": stem}
                        order.append(stem)
                    samples[stem][column] = _decode_member(
                        type_suffix, payload, decode_images)
            return BlockAccessor.from_items([samples[k] for k in order])

        return read

    return Dataset(LogicalPlan(Read([make_task(f) for f in files])))


def write_webdataset(ds: Dataset, path: str, *,
                     rows_per_shard: int = 1000) -> None:
    """Round-trip writer: each row becomes one sample; bytes columns are
    stored raw, str as .txt, int as .cls, dict/list as .json, ndarray as
    .npy. ``__key__`` names the sample (default: running index)."""
    os.makedirs(path, exist_ok=True)
    shard_idx, n_in_shard, tar = 0, 0, None

    def open_shard(i):
        return tarfile.open(
            os.path.join(path, f"shard-{i:05d}.tar"), "w")

    def add(tar, name, payload: bytes):
        info = tarfile.TarInfo(name)
        info.size = len(payload)
        tar.addfile(info, io.BytesIO(payload))

    idx = 0
    for block in ds.iter_blocks():
        for row in BlockAccessor(block).iter_rows():
            if tar is None:
                tar = open_shard(shard_idx)
            key = str(row.get("__key__", f"{idx:08d}"))
            for col, val in row.items():
                if col == "__key__":
                    continue
                # Typed double extensions (``caption.txt``, ``meta.json``)
                # make ANY column name round-trip with its Python type;
                # a column already named after its type stays single-ext.
                def name(type_ext: str) -> str:
                    if "." + col == type_ext:
                        return f"{key}{type_ext}"
                    return f"{key}.{col}{type_ext}"

                if isinstance(val, (bytes, bytearray)):
                    add(tar, f"{key}.{col}", bytes(val))
                elif isinstance(val, str):
                    add(tar, name(".txt"), val.encode("utf-8"))
                elif isinstance(val, (bool, np.bool_)):
                    add(tar, name(".json"), json.dumps(bool(val)).encode())
                elif isinstance(val, (int, np.integer)):
                    add(tar, name(".cls"), str(int(val)).encode())
                elif isinstance(val, np.ndarray):
                    buf = io.BytesIO()
                    np.save(buf, val)
                    add(tar, name(".npy"), buf.getvalue())
                else:
                    add(tar, name(".json"),
                        json.dumps(val).encode("utf-8"))
            idx += 1
            n_in_shard += 1
            if n_in_shard >= rows_per_shard:
                tar.close()
                tar, n_in_shard = None, 0
                shard_idx += 1
    if tar is not None:
        tar.close()


# ---------------------------------------------------------------------------
# SQL (DB-API 2.0)
# ---------------------------------------------------------------------------

def read_sql(sql: str, connection_factory: Callable[[], Any], *,
             shard_key: Optional[str] = None,
             parallelism: int = 1) -> Dataset:
    """Run ``sql`` through a DB-API connection and emit arrow blocks
    (reference: ``read_sql(sql, connection_factory)``).

    With ``shard_key`` + ``parallelism`` > 1 the query is fanned out as
    ``parallelism`` read tasks, each appending
    ``WHERE/AND (<shard_key> % N) = i`` — disjoint row partitions pulled
    concurrently (each task opens its own connection; the factory must be
    picklable and safe to call in worker processes)."""
    if parallelism > 1 and shard_key is None:
        raise ValueError("parallelism > 1 requires shard_key")

    def make_task(clause: Optional[str]):
        def read():
            conn = connection_factory()
            try:
                cur = conn.cursor()
                q = sql
                if clause:
                    # Subquery wrap: appending WHERE/AND to the raw text
                    # breaks on ORDER BY / GROUP BY / LIMIT tails (and on
                    # subqueries that merely contain "where").
                    q = f"SELECT * FROM ({sql}) AS _rt_shard WHERE {clause}"
                cur.execute(q)
                cols = [d[0] for d in cur.description]
                rows = cur.fetchall()
            finally:
                conn.close()
            arrays = {c: [r[i] for r in rows] for i, c in enumerate(cols)}
            return pa.table({c: pa.array(v) for c, v in arrays.items()})

        return read

    if parallelism <= 1:
        tasks = [make_task(None)]
    else:
        # Sign-normalized modulo (SQL % keeps the dividend's sign, so a
        # negative key would match no shard) + NULL keys routed to shard 0
        # (NULL % N is NULL — silently dropped otherwise).
        def clause(i: int) -> str:
            c = (f"((({shard_key}) % {parallelism}) + {parallelism}) "
                 f"% {parallelism} = {i}")
            if i == 0:
                c = f"({c} OR ({shard_key}) IS NULL)"
            return c

        tasks = [make_task(clause(i)) for i in range(parallelism)]
    return Dataset(LogicalPlan(Read(tasks)))


# ---------------------------------------------------------------------------
# Partitioned parquet with pruning
# ---------------------------------------------------------------------------

def _parse_partitions(root: str, file_path: str) -> Dict[str, str]:
    parts: Dict[str, str] = {}
    rel = os.path.relpath(os.path.dirname(file_path), root)
    for seg in rel.split(os.sep):
        if "=" in seg:
            k, v = seg.split("=", 1)
            parts[k] = v
    return parts


def read_parquet_partitioned(
    root: str, *,
    partition_filter: Optional[Callable[[Dict[str, str]], bool]] = None,
) -> Dataset:
    """Hive-layout parquet tree (``.../key=value/.../*.parquet``):
    ``key=value`` path segments become string columns on every row, and
    ``partition_filter(partitions) -> bool`` PRUNES files before any data
    is read — predicate pushdown on the directory structure (reference:
    ``parquet_datasource.py`` + ``partitioning.py``)."""
    files: List[str] = []
    for dirpath, _dirs, names in os.walk(root):
        for n in sorted(names):
            if n.endswith(".parquet"):
                files.append(os.path.join(dirpath, n))
    if not files:
        raise FileNotFoundError(f"no parquet files under {root}")
    kept = []
    for f in files:
        parts = _parse_partitions(root, f)
        if partition_filter is None or partition_filter(parts):
            kept.append((f, parts))
    if not kept:
        raise FileNotFoundError(
            f"partition_filter pruned every file under {root}")

    def make_task(f: str, parts: Dict[str, str]):
        def read():
            import pyarrow.parquet as pq

            table = pq.read_table(f)
            for k, v in parts.items():
                if k not in table.column_names:
                    table = table.append_column(
                        k, pa.array([v] * len(table), pa.string()))
            return table

        return read

    return Dataset(LogicalPlan(Read([make_task(f, p) for f, p in kept])))


def write_parquet_partitioned(ds: Dataset, root: str, *,
                              partition_cols: List[str]) -> None:
    """Writer side of the hive layout: rows land under
    ``root/key=value/...``. STREAMING: blocks are processed one at a time
    and each partition keeps one open ``ParquetWriter`` (appending row
    groups), so datasets larger than driver RAM write fine — the whole
    corpus is never materialized."""
    import pyarrow.parquet as pq

    writers: Dict[tuple, pq.ParquetWriter] = {}
    part_idx: Dict[tuple, int] = {}

    def open_writer(key: tuple, schema) -> pq.ParquetWriter:
        d = os.path.join(root, *(f"{c}={v}" for c, v in
                                 zip(partition_cols, key)))
        os.makedirs(d, exist_ok=True)
        i = part_idx.get(key, 0)
        part_idx[key] = i + 1
        return pq.ParquetWriter(
            os.path.join(d, f"part-{i:05d}.parquet"), schema)

    try:
        for block in ds.iter_blocks():
            # Per-block grouping only (bounded memory): rows of this block
            # split by partition value, then append to the open writers.
            groups: Dict[tuple, List[dict]] = {}
            for row in BlockAccessor(block).iter_rows():
                key = tuple(str(row[c]) for c in partition_cols)
                groups.setdefault(key, []).append(
                    {k: v for k, v in row.items()
                     if k not in partition_cols})
            for key, rows in groups.items():
                table = BlockAccessor.from_items(rows)
                w = writers.get(key)
                if w is None:
                    w = writers[key] = open_writer(key, table.schema)
                if not table.schema.equals(w.schema):
                    # Per-block type inference can disagree (int64 block
                    # then double block): cast when possible, else roll a
                    # NEW part file with the new schema — readers merge
                    # all parts, so no rows are lost either way.
                    try:
                        table = table.cast(w.schema)
                    except (pa.ArrowInvalid, pa.ArrowTypeError,
                            pa.ArrowNotImplementedError, ValueError):
                        # cast raises ValueError (not ArrowInvalid) on
                        # field-name/count mismatches — the common case.
                        w.close()
                        w = writers[key] = open_writer(key, table.schema)
                w.write_table(table)
    finally:
        for w in writers.values():
            w.close()


# ---------------------------------------------------------------------------
# MongoDB
# ---------------------------------------------------------------------------

def read_mongo(uri: str, database: str, collection: str, *,
               pipeline: Optional[List[Dict]] = None,
               shard_filters: Optional[List[Dict]] = None,
               _client_factory: Optional[Callable[[], Any]] = None) -> Dataset:
    """MongoDB collection → Dataset (reference: ``read_mongo``).

    ``pipeline`` is an aggregation prefix applied server-side. Parallel
    reads are EXPLICIT: pass ``shard_filters`` — a list of disjoint
    ``$match`` documents (e.g. ``_id`` range predicates), one read task per
    filter, each pushed down server-side (a client-side modulo split would
    scan the whole collection once per task). Documents' ``_id`` is
    stringified (ObjectId isn't arrow-able). ``_client_factory`` injects a
    client for tests; by default ``pymongo.MongoClient(uri)`` is
    constructed inside each read task (pymongo must be installed — it is
    not baked into this image, matching the reference's optional extra).
    """
    if _client_factory is None:
        def _client_factory():  # noqa: ANN202 — deferred optional dep
            try:
                import pymongo
            except ImportError as e:  # pragma: no cover
                raise ImportError(
                    "read_mongo requires pymongo (pip install pymongo)"
                ) from e
            return pymongo.MongoClient(uri)

    def make_task(shard_match: Optional[Dict]):
        def read():
            client = _client_factory()
            try:
                coll = client[database][collection]
                stages = list(pipeline or [])
                if shard_match is not None:
                    stages = [{"$match": shard_match}] + stages
                docs = list(coll.aggregate(stages)) if stages else list(
                    coll.find())
            finally:
                try:
                    client.close()
                except Exception:  # noqa: BLE001 — fake clients in tests
                    pass
            for d in docs:
                if "_id" in d:
                    d["_id"] = str(d["_id"])
            return BlockAccessor.from_items(docs)

        return read

    shards = shard_filters if shard_filters else [None]
    return Dataset(LogicalPlan(Read([make_task(s) for s in shards])))
