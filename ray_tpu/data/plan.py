"""Logical plan + rule-based optimizer.

Analog of the reference's ``python/ray/data/_internal/logical/``
(``LogicalPlan`` ``interfaces/logical_plan.py:5``, operators under
``operators/``, fusion rules in ``optimizers.py``): a Dataset holds an
immutable operator DAG; execution first optimizes it (map-chain fusion — the
rule that matters: fused maps run as ONE task per block, halving object-store
traffic) then hands it to the streaming executor.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


class LogicalOp:
    name: str = "op"

    def __init__(self, inputs: List["LogicalOp"]):
        self.inputs = inputs


class Read(LogicalOp):
    """Leaf: produces blocks from read tasks (one per file/fragment)."""

    name = "Read"

    def __init__(self, read_tasks: List[Callable[[], Any]], num_rows: Optional[int] = None):
        super().__init__([])
        self.read_tasks = read_tasks
        self.num_rows = num_rows


class InputData(LogicalOp):
    """Leaf: pre-materialized blocks (from_items / from_pandas / refs)."""

    name = "InputData"

    def __init__(self, block_refs: List[Any], num_rows: Optional[int] = None):
        super().__init__([])
        self.block_refs = block_refs
        self.num_rows = num_rows


class MapBlocks(LogicalOp):
    """block -> block transform (map_batches / map / filter / flat_map all
    lower to this; fusable)."""

    name = "MapBlocks"

    def __init__(
        self,
        input_op: LogicalOp,
        fn: Callable,
        *,
        label: str = "Map",
        compute: str = "tasks",           # "tasks" | "actors"
        num_cpus: float = 1.0,
        concurrency: Optional[int] = None,
    ):
        super().__init__([input_op])
        self.fn = fn
        self.label = label
        self.compute = compute
        self.num_cpus = num_cpus
        self.concurrency = concurrency


class AllToAll(LogicalOp):
    """Barrier op: consumes all input blocks, emits new blocks
    (sort / shuffle / repartition / groupby)."""

    name = "AllToAll"

    def __init__(self, input_op: LogicalOp, fn: Callable[[List[Any]], List[Any]], label: str):
        super().__init__([input_op])
        self.fn = fn  # (all_block_refs) -> new_block_refs (driver-side orchestration)
        self.label = label


class Union(LogicalOp):
    name = "Union"

    def __init__(self, inputs: List[LogicalOp]):
        super().__init__(list(inputs))


class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, input_op: LogicalOp, n: int):
        super().__init__([input_op])
        self.n = n


@dataclass
class LogicalPlan:
    dag: LogicalOp

    def optimized(self) -> "LogicalPlan":
        return LogicalPlan(_fuse_maps(self.dag))


def _fuse_maps(op: LogicalOp) -> LogicalOp:
    """Fuse chains of MapBlocks into one (reference:
    ``OperatorFusionRule`` in ``_internal/logical/rules/operator_fusion.py``).
    Only same-compute ("tasks") stages fuse; actor pools keep their own op."""
    op_inputs = [_fuse_maps(i) for i in op.inputs]
    op.inputs = op_inputs
    if (
        isinstance(op, MapBlocks)
        and op.compute == "tasks"
        and len(op_inputs) == 1
        and isinstance(op_inputs[0], MapBlocks)
        and op_inputs[0].compute == "tasks"
    ):
        inner = op_inputs[0]
        outer_fn, inner_fn = op.fn, inner.fn

        def fused(block, _inner=inner_fn, _outer=outer_fn):
            return _outer(_inner(block))

        merged = MapBlocks(
            inner.inputs[0],
            fused,
            label=f"{inner.label}->{op.label}",
            compute="tasks",
            num_cpus=max(op.num_cpus, inner.num_cpus),
            concurrency=op.concurrency or inner.concurrency,
        )
        return merged
    return op
