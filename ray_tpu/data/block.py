"""Blocks — the unit of data movement (Arrow tables in the object store).

Analog of the reference's block model (``python/ray/data/block.py``,
``_internal/arrow_block.py``): a Dataset is a list of object-store refs to
Arrow tables; ``BlockAccessor`` is the typed facade over a block. Arrow
columns convert zero-copy to numpy for the TPU ingest path (host numpy →
``jax.device_put`` under the consumer's sharding).
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Mapping, Optional, Union

import numpy as np
import pyarrow as pa

Block = pa.Table
Row = Dict[str, Any]
Batch = Dict[str, np.ndarray]


class BlockAccessor:
    """Reference: ``python/ray/data/block.py BlockAccessor``."""

    def __init__(self, block: Block):
        self._table = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    # -- constructors --------------------------------------------------------
    @staticmethod
    def from_items(items: List[Any]) -> Block:
        if items and isinstance(items[0], Mapping):
            cols: Dict[str, List] = {}
            for it in items:
                for k, v in it.items():
                    cols.setdefault(k, []).append(v)
            arrays: Dict[str, Any] = {}
            for k, vals in cols.items():
                # MULTI-dim ndarray cells with a uniform shape become a
                # tensor column (reference: ArrowTensorArray) — plain
                # pa.table rejects them. 1-D cells stay list<T> as before:
                # a per-block uniform/ragged switch would give blocks of
                # the same column incompatible schemas and break concat.
                if (vals and isinstance(vals[0], np.ndarray)
                        and vals[0].ndim >= 2
                        and all(isinstance(v, np.ndarray)
                                and v.shape == vals[0].shape
                                and v.dtype == vals[0].dtype
                                for v in vals)):
                    arrays[k] = pa.FixedShapeTensorArray.from_numpy_ndarray(
                        np.ascontiguousarray(np.stack(vals)))
                else:
                    arrays[k] = vals
            return pa.table(arrays)
        return pa.table({"item": list(items)})

    @staticmethod
    def from_pandas(df) -> Block:
        return pa.Table.from_pandas(df, preserve_index=False)

    @staticmethod
    def from_numpy(data: Union[np.ndarray, Dict[str, np.ndarray]]) -> Block:
        if isinstance(data, np.ndarray):
            data = {"data": data}
        cols = {}
        for k, v in data.items():
            v = np.asarray(v)
            if v.ndim > 1:
                # Tensor column with shape preserved in the schema
                # (reference: ArrowTensorArray extension type). pyarrow
                # rejects degenerate strides (e.g. the 0-stride leading axis
                # of arr[None, ...] views), which ascontiguousarray does NOT
                # normalize for size-1 dims — copy restores standard strides.
                v = np.ascontiguousarray(v)
                if v.strides[0] < max(v.strides):
                    v = v.copy()
                cols[k] = pa.FixedShapeTensorArray.from_numpy_ndarray(v)
            else:
                cols[k] = pa.array(v)
        return pa.table(cols)

    @staticmethod
    def batch_to_block(batch: Union[Batch, "pa.Table", Any]) -> Block:
        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            return BlockAccessor.from_numpy(batch)
        try:
            import pandas as pd

            if isinstance(batch, pd.DataFrame):
                return BlockAccessor.from_pandas(batch)
        except ImportError:
            pass
        raise TypeError(f"cannot convert {type(batch)} to a block")

    # -- accessors -----------------------------------------------------------
    def num_rows(self) -> int:
        return self._table.num_rows

    def size_bytes(self) -> int:
        return self._table.nbytes

    def schema(self) -> pa.Schema:
        return self._table.schema

    def slice(self, start: int, end: int) -> Block:
        return self._table.slice(start, end - start)

    def take(self, indices: List[int]) -> Block:
        return self._table.take(pa.array(indices))

    def to_arrow(self) -> pa.Table:
        return self._table

    def to_pandas(self):
        return self._table.to_pandas()

    def to_numpy(self, columns: Optional[List[str]] = None) -> Batch:
        cols = columns or self._table.column_names
        return {c: self._column_to_numpy(c) for c in cols}

    def _column_to_numpy(self, name: str) -> np.ndarray:
        col = self._table.column(name)
        if isinstance(col.type, pa.FixedShapeTensorType):
            return col.combine_chunks().to_numpy_ndarray()
        try:
            return col.to_numpy(zero_copy_only=False)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            return np.asarray(col.to_pylist())

    def iter_rows(self) -> Iterable[Row]:
        # to_pylist flattens tensor-extension columns; restore their shapes.
        tensor_shapes = {
            f.name: tuple(f.type.shape)
            for f in self._table.schema
            if isinstance(f.type, pa.FixedShapeTensorType)
        }
        for batch in self._table.to_batches():
            for row in batch.to_pylist():
                for name, shape in tensor_shapes.items():
                    if row.get(name) is not None:
                        row[name] = np.asarray(row[name]).reshape(shape)
                yield row

    def select(self, columns: List[str]) -> Block:
        return self._table.select(columns)

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        blocks = [b for b in blocks if b is not None and b.num_rows >= 0]
        if not blocks:
            return pa.table({})
        try:
            return pa.concat_tables(blocks, promote_options="default")
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
            # Schema clash — typically tensor columns whose per-block
            # shapes differ (e.g. images of mixed sizes: each block
            # inferred its own fixed_shape_tensor type). Demote tensor
            # columns to list<...> so the union is representable; cells
            # keep their values (to_pylist), shapes are no longer carried
            # by the schema.
            demoted = []
            for b in blocks:
                cols = {}
                for name in b.column_names:
                    col = b.column(name)
                    if isinstance(col.type, pa.FixedShapeTensorType):
                        cols[name] = pa.array(
                            [v.tolist() if v is not None else None
                             for v in col.combine_chunks().to_numpy_ndarray()])
                    else:
                        cols[name] = col
                demoted.append(pa.table(cols))
            return pa.concat_tables(demoted, promote_options="default")

    def sample(self, n: int, seed: Optional[int] = None) -> Block:
        rng = np.random.default_rng(seed)
        n = min(n, self.num_rows())
        idx = rng.choice(self.num_rows(), size=n, replace=False)
        return self.take(list(idx))
