"""Compiled DAG executor — resident actor loops over mutable channels.

Analog of the reference's ``python/ray/dag/compiled_dag_node.py`` (625
lines): compiling a static actor-method graph allocates one mutable channel
per EDGE (``do_allocate_channel`` :28-39) and parks each actor in a resident
gather→exec→broadcast loop (``do_exec_compiled_task`` :43-49); ``execute``
:532 just writes the input channels. Per-call cost collapses from a full
task submission (spec pickle → lease → push → result seal) to one shm write
and one shm read per edge — and with the multi-slot ring channels several
ticks ride each edge concurrently, so burst submission pipelines through
the stages instead of serializing on per-tick hand-offs.

Graph shapes beyond linear chains compile: multi-arg ``bind`` (fan-in),
several consumers of one node (fan-out, broadcast per tick), and
``MultiOutputNode`` gathering multiple leaves into a per-tick result tuple
— the serve preprocess→shard→merge and pipeline shapes.

TPU note: this is the host-side fast path the reference aims at GPU
pipelines; on TPU the same shape feeds device steps whose tensors stay
on-device between stages — the channels carry small host-side control
payloads, not activations (``channel_type="device"`` moves real arrays).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.task_spec import DAG_LOOP_METHOD
from ray_tpu.dag.channel import Channel, ChannelClosed, SocketChannel
from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, InputNode,
                                  MultiOutputNode)
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger("dag")

_DRIVER = "__driver__"  # edge-key sentinel for driver-read output edges


class _DagError:
    def __init__(self, message: str):
        self.message = message


class _TracedPayload:
    """A tick payload carrying its trace context across channel edges.

    Wrapped only when the driver's ``execute`` ran under a SAMPLED trace
    context — the untraced µs-path ships raw payloads and pays one
    ``type`` check per edge read. Stages unwrap, time the method as a
    ``dag.stage`` child span of the tick span, and re-wrap so downstream
    stages (and the driver) stay in the trace."""

    __slots__ = ("ctx", "tick_span", "value")

    def __init__(self, ctx, tick_span, value):
        self.ctx = ctx
        self.tick_span = tick_span
        self.value = value


def actor_dag_loop(instance, method_name: str, in_channels: List[Any],
                   out_channels: List[Any],
                   arg_template: Optional[List[Tuple[str, Any]]] = None
                   ) -> str:
    """The resident loop body; runs INSIDE the actor (both runtimes hook
    ``DAG_LOOP_METHOD`` to call this with the live instance).

    Per tick: read one value from EVERY in-channel (fan-in gather, FIFO per
    edge keeps ticks aligned), assemble the call args from ``arg_template``
    (``("c", i)`` = the i-th gathered value, ``("v", const)`` = a baked
    constant), run the method, broadcast the result to every out-channel.
    A ``_DagError`` input skips the method and forwards downstream (error
    passthrough), so the driver sees the ORIGINATING stage's failure.

    On exit — close pill from any upstream, or a wedged downstream — every
    out-channel is closed (propagating teardown) and every ATTACHED channel
    endpoint is detached, releasing this worker's mmap/fd/socket handles
    (the driver, which created the channels, owns the unlink). In-process
    runtimes pass the driver's own channel objects by reference; those are
    not attached endpoints and the driver's ``destroy`` remains the single
    owner of their lifecycle.
    """
    from ray_tpu.core.config import config

    method = getattr(instance, method_name)
    if arg_template is None:
        arg_template = [("c", 0)]
    write_bound = float(config().internal_wait_timeout_s)
    try:
        while True:
            try:
                values = [ch.read(timeout=None) for ch in in_channels]
            except ChannelClosed:
                for och in out_channels:
                    och.close()
                return "closed"
            trace = None
            if any(type(v) is _TracedPayload for v in values):
                trace = next(v for v in values
                             if type(v) is _TracedPayload)
                values = [v.value if type(v) is _TracedPayload else v
                          for v in values]
            err = next((v for v in values if isinstance(v, _DagError)), None)
            if err is not None:
                result = err
            else:
                args = [values[payload] if kind == "c" else payload
                        for kind, payload in arg_template]
                t0 = time.monotonic()
                try:
                    result = method(*args)
                except Exception as exc:  # noqa: BLE001 — deliver to caller
                    result = _DagError(f"{type(exc).__name__}: {exc}")
                if trace is not None:
                    from ray_tpu.util import tracing

                    tracing.emit(
                        f"dag.stage:{method_name}", trace.ctx,
                        duration=time.monotonic() - t0,
                        parent_span_id=trace.tick_span,
                        attrs={"method": method_name})
            if trace is not None:
                result = _TracedPayload(trace.ctx, trace.tick_span, result)
            try:
                for och in out_channels:
                    # Bounded: a consumer that stopped draining (died mid-
                    # teardown) must not park this loop forever on a full
                    # ring — treat the stall as the teardown it is.
                    och.write(result, timeout=write_bound)
            except (ChannelClosed, TimeoutError):
                for och in out_channels:
                    och.close()
                return "closed"
    finally:
        for ch in list(in_channels) + list(out_channels):
            if getattr(ch, "_attached_endpoint", False):
                try:
                    ch.detach()
                except Exception:  # noqa: BLE001 — teardown is best-effort
                    log_swallowed(logger, "channel detach at DAG loop exit")


class DAGRef:
    """Future for one execute() call (reference returns a channel-backed
    ref from CompiledDAG.execute the same way). ``get`` is idempotent like
    ``ObjectRef.get``: the first call drains the tick off the output
    channels, repeats serve the cached result (or re-raise the cached
    stage error)."""

    _UNSET = object()

    def __init__(self, dag: "CompiledDAG", index: int):
        self._dag = dag
        self._index = index
        self._result = DAGRef._UNSET

    def get(self, timeout: Optional[float] = 30.0):
        if self._result is DAGRef._UNSET:
            # Timeouts propagate WITHOUT caching — the tick is still in
            # flight and a later get() may find it.
            self._result = self._dag._fetch(self._index, timeout)
        result = self._result
        parts = result if self._dag._multi_output else (result,)
        errs = [r for r in parts if isinstance(r, _DagError)]
        if errs:
            raise RuntimeError(f"DAG stage failed: {errs[0].message}")
        return result


class CompiledDAG:
    def __init__(self, output_node: DAGNode, *,
                 channel_capacity: int = 4 * 1024 * 1024,
                 channel_type: str = "auto",
                 channel_slots: Optional[int] = None):
        """``channel_type``: "shm" (same-host mutable shm ring), "socket"
        (cross-host TCP with windowed acks), "device" (DeviceChannel —
        array payloads land as ``jax.Array`` on each stage's device with
        ring-buffered host DMA, the SURVEY §2.1 accelerator-channel tier),
        or "auto" — per EDGE, shm when both endpoints share a host,
        sockets otherwise (the reference's aDAG channels are likewise
        transport-selected per pair, experimental/channel.py:51).

        ``channel_slots`` overrides the ``dag_channel_slots`` ring depth —
        how many ticks can be in flight per edge (1 = lock-step).
        """
        nodes = output_node.collect()
        self._multi_output = isinstance(output_node, MultiOutputNode)
        leaves = (list(output_node.upstreams) if self._multi_output
                  else [output_node])
        input_nodes = [n for n in nodes if isinstance(n, InputNode)]
        stages = [n for n in nodes if isinstance(n, ClassMethodNode)]
        bad = [n for n in nodes
               if not isinstance(n, (InputNode, ClassMethodNode))
               and n is not output_node]
        if bad or (self._multi_output and not all(
                isinstance(leaf, ClassMethodNode) for leaf in leaves)):
            raise ValueError("DAG nodes must be bound actor methods "
                             "(MultiOutputNode only at the root)")
        if len(input_nodes) != 1:
            raise ValueError("DAG must contain exactly one InputNode "
                             f"(found {len(input_nodes)})")
        if not stages or not all(isinstance(leaf, ClassMethodNode)
                                 for leaf in leaves):
            raise ValueError("DAG must contain at least one bound actor "
                             "method ending in actor-method leaves")
        self._input_node = input_nodes[0]
        self._stages = stages
        seen_actors = set()
        for stage in stages:
            aid = stage.actor.actor_id
            if aid in seen_actors:
                raise ValueError(
                    "compiled DAG stages must use DISTINCT actors: the "
                    "resident loop occupies an actor's execution thread, so "
                    "a second stage on the same actor can never start")
            seen_actors.add(aid)

        # -- edges: one channel per (producer, consumer, arg position) ----
        # A stage consumes one channel per DAGNode bind arg; a producer
        # broadcasts to one channel per consumer edge. Leaves additionally
        # produce a driver edge each.
        hosts = (self._node_hosts(nodes) if channel_type == "auto" else None)

        def make_channel(producer, consumer):
            if channel_type == "device":
                from ray_tpu.dag.device_channel import DeviceChannel

                return DeviceChannel(capacity=channel_capacity,
                                     slots=channel_slots)
            if channel_type == "socket":
                cross = True
            elif channel_type == "shm":
                cross = False
            else:
                cross = (hosts is not None
                         and hosts[id(producer)] != hosts.get(
                             id(consumer), hosts[_DRIVER]))
            if cross:
                return SocketChannel(capacity=channel_capacity)
            return Channel(capacity=channel_capacity, slots=channel_slots)

        self._channels: Dict[tuple, Any] = {}
        out_edges: Dict[int, List[tuple]] = {id(n): [] for n in nodes}
        in_chans: Dict[int, List[Any]] = {id(s): [] for s in stages}
        templates: Dict[int, List[Tuple[str, Any]]] = {}
        for stage in stages:
            template: List[Tuple[str, Any]] = []
            for pos, arg in enumerate(stage.bind_args):
                if isinstance(arg, DAGNode):
                    key = (id(arg), id(stage), pos)
                    ch = make_channel(arg, stage)
                    self._channels[key] = ch
                    out_edges[id(arg)].append(key)
                    template.append(("c", len(in_chans[id(stage)])))
                    in_chans[id(stage)].append(ch)
                else:
                    template.append(("v", arg))
            templates[id(stage)] = template
        for k, leaf in enumerate(leaves):
            key = (id(leaf), _DRIVER, k)
            self._channels[key] = make_channel(leaf, _DRIVER)
            out_edges[id(leaf)].append(key)
        self._input_channels = [self._channels[key]
                                for key in out_edges[id(self._input_node)]]
        self._output_channels = [self._channels[(id(leaf), _DRIVER, k)]
                                 for k, leaf in enumerate(leaves)]

        # -- park each actor in its resident loop ------------------------
        self._loop_refs = []
        for stage in stages:
            ref = stage.actor._submit(
                DAG_LOOP_METHOD,
                (stage.method_name, in_chans[id(stage)],
                 [self._channels[key] for key in out_edges[id(stage)]],
                 templates[id(stage)]),
                {}, {},
            )
            self._loop_refs.append(ref)
        # Loop tasks run until teardown — one completing NOW means its
        # startup failed (async actor, bad method, dead worker). Surface it
        # here instead of as an opaque ChannelTimeout at execute().
        import ray_tpu

        ready, _ = ray_tpu.wait(self._loop_refs,
                                num_returns=len(self._loop_refs), timeout=0.3)
        if ready:
            for ch in self._channels.values():
                ch.destroy()
            ray_tpu.get(ready[0])  # raises the loop's startup error
            raise RuntimeError("DAG loop exited prematurely at compile time")
        self._next_index = 0
        self._reads = 0
        self._fetched: Dict[int, Any] = {}
        # Leaves already gathered for the IN-PROGRESS tick: a timeout
        # partway through a multi-output gather must not lose consumed
        # values — the next fetch resumes at the first unread leaf, so
        # tick alignment across output channels survives the retry.
        self._partial_outs: List[Any] = []
        self._tick_start: Dict[int, float] = {}
        # index -> (trace_ctx, tick_span_id) for ticks executed under a
        # sampled trace; the dag.tick span closes at _fetch.
        self._tick_trace: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._torn_down = False

    @staticmethod
    def _node_hosts(nodes) -> Dict[int, str]:
        """Host of every channel endpoint, keyed by node id; the driver's
        host under the ``_DRIVER`` sentinel (InputNode lives with the
        driver)."""
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()

        def actor_host(actor) -> str:
            try:
                addr = rt._actor_address(actor.actor_id)
                return addr.rsplit(":", 1)[0]
            except Exception:  # noqa: BLE001 — in-process runtime
                return "local"

        driver_host = (rt.owner_address.rsplit(":", 1)[0]
                       if hasattr(rt, "owner_address") else "local")
        hosts: Dict[int, str] = {_DRIVER: driver_host}
        for n in nodes:
            hosts[id(n)] = (actor_host(n.actor)
                            if isinstance(n, ClassMethodNode)
                            else driver_host)
        return hosts

    def execute(self, value: Any, timeout: Optional[float] = 30.0) -> DAGRef:
        """One DAG step: a single shm write per input edge; result via the
        returned ref. With multi-slot rings several executes pipeline
        through the stages before the first blocks on backpressure.

        Index assignment and the channel writes share one lock: input
        channels are single-writer, and FIFO index↔result mapping requires
        writes to land in index order. A failed (timed-out) execute
        consumes no index AND publishes to no edge: shm input edges commit
        two-phase — every ring slot is RESERVED before any payload is
        published, and a reservation timeout rolls the already-reserved
        slots back — so a full edge on one input can't leave its fan-out
        siblings a tick ahead (which would desync every later merge).
        """
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        from ray_tpu.core import serialization
        from ray_tpu.core.metrics_export import metrics_enabled
        from ray_tpu.util import tracing

        # Tick tracing: only when execute() runs under an already-SAMPLED
        # context (a serve request, a user span) — the untraced µs path
        # pays one flag check and ships the raw payload.
        trace_ctx = tick_span = None
        if tracing.trace_enabled():
            ctx = tracing.current_context()
            if ctx is not None and ctx[2]:
                trace_ctx, tick_span = ctx, tracing.new_span_id()
                value = _TracedPayload(trace_ctx, tick_span, value)
        rings = [ch for ch in self._input_channels if isinstance(ch, Channel)]
        others = [ch for ch in self._input_channels
                  if not isinstance(ch, Channel)]
        with self._write_lock:
            if rings:
                payload = serialization.dumps(value)
                for ch in rings:
                    if len(payload) > ch.capacity:
                        raise ValueError(
                            f"payload of {len(payload)} bytes exceeds "
                            f"channel capacity {ch.capacity}")
                reserved = []
                try:
                    for ch in rings:
                        ch._wait_writable(timeout)
                        reserved.append(ch)
                except BaseException:
                    for ch in reserved:
                        ch._abort_write()
                    raise
                for ch in rings:
                    off = ch._wpayload_off
                    ch._mm[off:off + len(payload)] = payload
                    ch._publish(len(payload))
            for ch in others:
                # Socket/device edges have no reserve/abort protocol;
                # they publish after every shm edge committed.
                ch.write(value, timeout=timeout)
            index = self._next_index
            self._next_index += 1
            if metrics_enabled() or trace_ctx is not None:
                self._tick_start[index] = time.monotonic()
            if trace_ctx is not None:
                self._tick_trace[index] = (trace_ctx, tick_span)
        return DAGRef(self, index)

    def _fetch(self, index: int, timeout: Optional[float]):
        """Results arrive strictly FIFO on each output channel: the i-th
        read is the i-th execute's result (one read per leaf per tick; a
        MultiOutputNode DAG yields a tuple). The lock makes fetchers take
        turns draining (single-reader channel contract)."""
        with self._lock:
            while index not in self._fetched:
                # Resume a partially gathered tick at its first UNREAD
                # leaf: a timeout mid-gather already consumed (and acked)
                # the earlier leaves' values for this tick.
                while len(self._partial_outs) < len(self._output_channels):
                    ch = self._output_channels[len(self._partial_outs)]
                    self._partial_outs.append(ch.read(timeout=timeout))
                outs, self._partial_outs = self._partial_outs, []
                outs = [o.value if type(o) is _TracedPayload else o
                        for o in outs]
                self._fetched[self._reads] = (tuple(outs) if self._multi_output
                                              else outs[0])
                self._reads += 1
            result = self._fetched.pop(index)
            trace = self._tick_trace.pop(index, None)
        start = self._tick_start.pop(index, None)
        if start is not None:
            from ray_tpu.core.metrics_export import (dag_tick_hist,
                                                     metrics_enabled)

            if metrics_enabled():
                dag_tick_hist().observe(time.monotonic() - start)
            if trace is not None:
                from ray_tpu.util import tracing

                tracing.emit("dag.tick", trace[0], span_id=trace[1],
                             duration=time.monotonic() - start,
                             attrs={"index": index})
        return result

    def teardown(self) -> None:
        """Poison the inputs, DRAIN the stage loops, then destroy.

        The drain is the teardown-race fix: destroying/unlinking the shm
        files while a stage is mid-``read`` would yank the backing file
        out from under its mmap. Instead the close pill propagates edge by
        edge, each loop exits (detaching its endpoints), and only then —
        bounded by ``dag_teardown_timeout_s`` — does the driver unlink.
        """
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._input_channels:
            ch.close()
        import ray_tpu
        from ray_tpu.core.config import config

        try:
            _ready, not_ready = ray_tpu.wait(
                self._loop_refs, num_returns=len(self._loop_refs),
                timeout=float(config().dag_teardown_timeout_s))
        except Exception:  # noqa: BLE001 — runtime already shut down
            not_ready = []
            log_swallowed(logger, "DAG teardown drain")
        if not_ready:
            # A stage never saw the pill (wedged in user code, or parked on
            # an edge whose producer died). Force a pill into every shm
            # edge so spinning readers wake, then destroy anyway — bounded
            # beats leaked.
            logger.warning(
                "%d DAG stage loop(s) did not exit within "
                "dag_teardown_timeout_s; forcing channel close",
                len(not_ready))
            for ch in self._channels.values():
                if not isinstance(ch, SocketChannel):
                    try:
                        ch.close()
                    except Exception:  # noqa: BLE001 — best-effort wakeup
                        log_swallowed(logger, "forced channel close")
        for ch in self._channels.values():
            ch.destroy()
