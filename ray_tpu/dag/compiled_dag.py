"""Compiled DAG executor — resident actor loops over mutable channels.

Analog of the reference's ``python/ray/dag/compiled_dag_node.py`` (625
lines): compiling a static actor-method chain allocates one mutable channel
per edge (``do_allocate_channel`` :28-39) and parks each actor in a resident
read→exec→write loop (``do_exec_compiled_task`` :43-49); ``execute`` :532
just writes the input channel. Per-call cost collapses from a full task
submission (spec pickle → lease → push → result seal) to one shm write and
one shm read per edge.

TPU note: this is the host-side fast path the reference aims at GPU
pipelines; on TPU the same shape feeds device steps whose tensors stay
on-device between stages — the channels carry small host-side control
payloads, not activations.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from ray_tpu.core.task_spec import DAG_LOOP_METHOD
from ray_tpu.dag.channel import Channel, ChannelClosed, SocketChannel
from ray_tpu.dag.dag_node import ClassMethodNode, DAGNode, InputNode


def actor_dag_loop(instance, method_name: str, in_channel: Channel,
                   out_channel: Channel) -> str:
    """The resident loop body; runs INSIDE the actor (both runtimes hook
    ``DAG_LOOP_METHOD`` to call this with the live instance)."""
    method = getattr(instance, method_name)
    while True:
        try:
            value = in_channel.read(timeout=None)
        except ChannelClosed:
            out_channel.close()
            return "closed"
        try:
            result = method(value)
        except Exception as exc:  # noqa: BLE001 — deliver to the caller
            result = _DagError(f"{type(exc).__name__}: {exc}")
        out_channel.write(result)


class _DagError:
    def __init__(self, message: str):
        self.message = message


class DAGRef:
    """Future for one execute() call (reference returns a channel-backed
    ref from CompiledDAG.execute the same way)."""

    def __init__(self, dag: "CompiledDAG", index: int):
        self._dag = dag
        self._index = index

    def get(self, timeout: Optional[float] = 30.0):
        return self._dag._fetch(self._index, timeout)


class CompiledDAG:
    def __init__(self, leaf: DAGNode, *, channel_capacity: int = 4 * 1024 * 1024,
                 channel_type: str = "auto"):
        """``channel_type``: "shm" (same-host mutable shm), "socket"
        (cross-host TCP), "device" (DeviceChannel — array payloads land as
        ``jax.Array`` on each stage's device with double-buffered host DMA,
        the SURVEY §2.1 accelerator-channel tier), or "auto" — per EDGE,
        shm when both endpoints share a host, sockets otherwise (the
        reference's aDAG channels are likewise transport-selected per
        pair, experimental/channel.py:51).
        """
        chain = leaf.chain()
        if not chain or not isinstance(chain[0], InputNode):
            raise ValueError("DAG must start from an InputNode")
        stages = chain[1:]
        if not stages or not all(isinstance(s, ClassMethodNode) for s in stages):
            raise ValueError("DAG must be a chain of bound actor methods")
        self._stages: List[ClassMethodNode] = stages
        seen_actors = set()
        for stage in stages:
            aid = stage.actor.actor_id
            if aid in seen_actors:
                raise ValueError(
                    "compiled DAG stages must use DISTINCT actors: the "
                    "resident loop occupies an actor's execution thread, so "
                    "a second stage on the same actor can never start")
            seen_actors.add(aid)
        # One channel per edge: input + one per stage output. Edge i is
        # written by stage i-1 (the driver for i=0) and read by stage i
        # (the driver for the last).
        hosts = self._endpoint_hosts(stages) if channel_type == "auto" else None
        self._channels = []
        for i in range(len(stages) + 1):
            if channel_type == "device":
                from ray_tpu.dag.device_channel import DeviceChannel

                self._channels.append(DeviceChannel(capacity=channel_capacity))
                continue
            if channel_type == "socket":
                cross = True
            elif channel_type == "shm":
                cross = False
            else:
                cross = hosts is not None and hosts[i] != hosts[i + 1]
            self._channels.append(
                SocketChannel(capacity=channel_capacity) if cross
                else Channel(capacity=channel_capacity))
        self._loop_refs = []
        for i, stage in enumerate(stages):
            # Park the actor in its resident loop (a long-running actor task
            # that the runtimes route to actor_dag_loop with the instance).
            ref = stage.actor._submit(
                DAG_LOOP_METHOD,
                (stage.method_name, self._channels[i], self._channels[i + 1]),
                {}, {},
            )
            self._loop_refs.append(ref)
        # Loop tasks run until teardown — one completing NOW means its
        # startup failed (async actor, bad method, dead worker). Surface it
        # here instead of as an opaque ChannelTimeout at execute().
        import ray_tpu

        ready, _ = ray_tpu.wait(self._loop_refs,
                                num_returns=len(self._loop_refs), timeout=0.3)
        if ready:
            for ch in self._channels:
                ch.destroy()
            ray_tpu.get(ready[0])  # raises the loop's startup error
            raise RuntimeError("DAG loop exited prematurely at compile time")
        self._next_index = 0
        self._reads = 0
        self._fetched = {}
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._torn_down = False

    @staticmethod
    def _endpoint_hosts(stages) -> List[str]:
        """Host of every channel endpoint: [driver, stage0, ..., stageN,
        driver] collapsed to per-edge endpoints (len = stages + 2)."""
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()

        def actor_host(actor) -> str:
            try:
                addr = rt._actor_address(actor.actor_id)
                return addr.rsplit(":", 1)[0]
            except Exception:  # noqa: BLE001 — in-process runtime
                return "local"

        driver_host = (rt.owner_address.rsplit(":", 1)[0]
                       if hasattr(rt, "owner_address") else "local")
        return ([driver_host] + [actor_host(s.actor) for s in stages]
                + [driver_host])

    def execute(self, value: Any) -> DAGRef:
        """One DAG step: a single shm write; result via the returned ref.

        Index assignment and the channel write share one lock: the input
        channel is single-writer, and FIFO index↔result mapping requires
        writes to land in index order. A failed (timed-out) write consumes
        no index.
        """
        if self._torn_down:
            raise RuntimeError("DAG was torn down")
        with self._write_lock:
            self._channels[0].write(value)
            index = self._next_index
            self._next_index += 1
        return DAGRef(self, index)

    def _fetch(self, index: int, timeout: Optional[float]):
        """Results arrive strictly FIFO on the output channel: the i-th read
        is the i-th execute's result. The lock makes fetchers take turns
        draining (single-reader channel contract)."""
        with self._lock:
            while index not in self._fetched:
                out = self._channels[-1].read(timeout=timeout)
                self._fetched[self._reads] = out
                self._reads += 1
            result = self._fetched.pop(index)
        if isinstance(result, _DagError):
            raise RuntimeError(f"DAG stage failed: {result.message}")
        return result

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        # Poison the input; each stage forwards the close downstream.
        self._channels[0].close()
        for ch in self._channels:
            ch.destroy()
