"""DAG authoring — the ``.bind()`` API.

Analog of the reference's ``python/ray/dag/dag_node.py``: ``InputNode`` is
the placeholder for per-call input; ``actor.method.bind(*upstreams)`` builds
a ``ClassMethodNode``. Graphs are general DAGs: a method may take several
upstream nodes (fan-in) plus baked constants, one node's output may feed
several consumers (fan-out), and ``MultiOutputNode([a, b])`` gathers
multiple leaves into one per-tick result tuple — the serve
preprocess→shard→merge and pipeline shapes all compile.
"""

from __future__ import annotations

from typing import Any, List


class DAGNode:
    def __init__(self, upstreams: List["DAGNode"]):
        self.upstreams: List[DAGNode] = list(upstreams)

    def collect(self) -> List["DAGNode"]:
        """All reachable nodes, dependencies first (stable topo order)."""
        order: List[DAGNode] = []
        seen = set()

        def rec(node: "DAGNode"):
            if id(node) in seen:
                return
            seen.add(id(node))
            for up in node.upstreams:
                rec(up)
            order.append(node)

        rec(self)
        return order

    def chain(self) -> List["DAGNode"]:
        """Nodes from InputNode to self for LINEAR graphs (legacy helper;
        general graphs use :meth:`collect`)."""
        nodes: List[DAGNode] = []
        node = self
        while node is not None:
            nodes.append(node)
            if len(node.upstreams) > 1:
                raise ValueError("chain() only walks linear DAGs")
            node = node.upstreams[0] if node.upstreams else None
        return list(reversed(nodes))

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Per-execute input placeholder (``with InputNode() as inp:`` in the
    reference; plain construction here)."""

    def __init__(self):
        super().__init__([])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ClassMethodNode(DAGNode):
    """``actor.method.bind(*args)``: each arg is an upstream DAGNode (one
    channel-fed value per tick) or a constant baked into every call."""

    def __init__(self, actor_handle, method_name: str, *bind_args: Any):
        self.bind_args = list(bind_args)
        super().__init__([a for a in bind_args if isinstance(a, DAGNode)])
        if not self.upstreams:
            raise TypeError(
                "bind() needs at least one InputNode or DAG node argument")
        self.actor = actor_handle
        self.method_name = method_name

    def __repr__(self):
        return f"ClassMethodNode({self.method_name})"


class MultiOutputNode(DAGNode):
    """Terminal gather node: ``execute`` results arrive as a tuple with one
    element per listed leaf (reference: ``ray.dag.MultiOutputNode``)."""

    def __init__(self, outputs: List[DAGNode]):
        outputs = list(outputs)
        if not outputs:
            raise ValueError("MultiOutputNode needs at least one output")
        if len({id(o) for o in outputs}) != len(outputs):
            raise ValueError("MultiOutputNode outputs must be distinct nodes")
        for o in outputs:
            if not isinstance(o, ClassMethodNode):
                raise TypeError(
                    "MultiOutputNode outputs must be bound actor methods")
        super().__init__(outputs)

    def __repr__(self):
        return f"MultiOutputNode({len(self.upstreams)})"
