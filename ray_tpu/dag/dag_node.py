"""DAG authoring — the ``.bind()`` API.

Analog of the reference's ``python/ray/dag/dag_node.py``: ``InputNode`` is
the placeholder for per-call input; ``actor.method.bind(upstream)`` builds a
``ClassMethodNode``. Only linear actor chains compile in v1 (the pipelined
inference/training shape aDAG exists for); fan-out/multi-output is a later
extension.
"""

from __future__ import annotations

from typing import Any, List, Optional


class DAGNode:
    def __init__(self, upstream: Optional["DAGNode"]):
        self.upstream = upstream

    def chain(self) -> List["DAGNode"]:
        """Nodes from InputNode to self, inclusive."""
        nodes: List[DAGNode] = []
        node: Optional[DAGNode] = self
        while node is not None:
            nodes.append(node)
            node = node.upstream
        return list(reversed(nodes))

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled_dag import CompiledDAG

        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """Per-execute input placeholder (``with InputNode() as inp:`` in the
    reference; plain construction here)."""

    def __init__(self):
        super().__init__(None)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ClassMethodNode(DAGNode):
    def __init__(self, actor_handle, method_name: str, upstream: DAGNode):
        super().__init__(upstream)
        self.actor = actor_handle
        self.method_name = method_name

    def __repr__(self):
        return f"ClassMethodNode({self.method_name})"
