"""Device channels — on-device tensors between pipelined actor stages.

The device tier of the compiled-DAG transport (SURVEY §2.1 TPU mapping:
the aDAG mutable channels "map to on-device buffers with double-buffered
host DMA"; reference analog: the accelerator channels reached through
``python/ray/experimental/channel.py:51``, where GPU payloads ride NCCL
instead of plasma). Separate processes own separate PJRT clients, so a
tensor crossing an actor boundary must traverse host memory — the job of
this channel is to make that traversal cost ONE device→host DMA, one shm
landing, and one host→device DMA, with the two directions overlapped:

- the payload is written as dtype/shape header + raw buffer straight into
  the shm segment (no pickle on either side);
- TWO shm slots alternate (ping-pong): the writer fills slot ``k+1`` while
  the reader's host→device upload of slot ``k`` is still in flight, so
  the DMA of one step hides behind the transfer of the next — the
  double-buffering half of the design;
- the reader gets a ``jax.Array`` committed to its device (or sharding),
  and only acks the slot once the upload is done — the writer can never
  overwrite bytes an in-flight DMA still reads.

Non-array payloads (control messages, pytrees with small leaves) fall back
to the pickled path of the underlying channel transparently.
"""

from __future__ import annotations

import pickle
import struct
import uuid
from typing import Any, Optional

import numpy as np

from ray_tpu.dag.channel import Channel, ChannelClosed, ChannelTimeout, HEADER_SIZE

# Payload kinds inside a slot: raw array (header + buffer) or pickled.
_KIND_ARRAY = 0
_KIND_PICKLE = 1
_META = struct.Struct("<BI")  # kind, header_len


class DeviceChannel:
    """Single-writer single-reader device-tensor channel (ping-pong)."""

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 64 * 1024 * 1024, create: bool = True,
                 device: Any = None, sharding: Any = None):
        self.name = name or f"rtpu-devchan-{uuid.uuid4().hex[:12]}"
        self.capacity = capacity
        # Two independent seqlock slots; writer/reader alternate in step.
        self._slots = [
            Channel(f"{self.name}-p{i}", capacity=capacity, create=create)
            for i in (0, 1)
        ]
        self._wcursor = 0
        self._rcursor = 0
        self._device = device
        self._sharding = sharding
        # The previous read's device array: its upload must be complete
        # before we ack the slot it came from (deferred ack = the overlap).
        self._pending_ack: Optional[tuple] = None

    # -- write ---------------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        # The slot cursor advances ONLY on success: an errored write
        # (oversized payload, timeout) must leave the ping-pong in step
        # with the reader or every later value lands one slot off.
        slot = self._slots[self._wcursor % 2]
        arr = self._as_host_array(value)
        if arr is None:
            from ray_tpu.core import serialization

            blob = serialization.dumps(value)
            payload = _META.pack(_KIND_PICKLE, len(blob)) + blob
            slot._write_payload(payload, timeout)
            self._wcursor += 1
            return
        header = pickle.dumps((arr.dtype.str, arr.shape))
        total = _META.size + len(header) + arr.nbytes
        if total > self.capacity:
            raise ValueError(
                f"array of {arr.nbytes} bytes exceeds device-channel "
                f"capacity {self.capacity}")
        # Write header+buffer directly into the slot's shm region — the
        # device→host DMA result lands once, no pickle copy.
        slot._wait_writable(timeout)
        try:
            base = HEADER_SIZE
            mm = slot._mm
            _META.pack_into(mm, base, _KIND_ARRAY, len(header))
            mm[base + _META.size:base + _META.size + len(header)] = header
            off = base + _META.size + len(header)
            dst = np.frombuffer(memoryview(mm)[off:off + arr.nbytes],
                                dtype=np.uint8)
            dst[:] = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
        except BaseException:
            # Roll the seqlock back to even: a failed fill must not leave
            # the slot marked write-in-progress forever.
            slot._store_write_seq(slot._pending_write_seq)
            raise
        slot._publish(total)
        self._wcursor += 1

    @staticmethod
    def _as_host_array(value) -> Optional[np.ndarray]:
        """Host ndarray for array-likes; None for everything else.
        jax.Arrays start their device→host DMA here (np.asarray blocks
        until the transfer lands — by then the PREVIOUS slot's write is
        already visible to the reader, which is the overlap)."""
        try:
            import jax

            if isinstance(value, jax.Array):
                return np.asarray(value)
        except ImportError:  # pragma: no cover - jax is a hard dep
            pass
        if isinstance(value, np.ndarray) and value.dtype != object:
            # object-dtype arrays hold pointers — raw bytes would be
            # garbage cross-process; they take the pickled path.
            return value
        return None

    # -- read ----------------------------------------------------------------

    def read(self, timeout: Optional[float] = 30.0) -> Any:
        """Next value as a ``jax.Array`` on this channel's device/sharding
        (raw arrays) or the pickled object (control payloads)."""
        self._complete_pending_ack()
        slot = self._slots[self._rcursor % 2]
        view, length = slot._read_view(timeout)
        self._rcursor += 1  # only after a value arrived (cursor-on-success)
        kind, hlen = _META.unpack_from(view, 0)
        if kind == _KIND_PICKLE:
            from ray_tpu.core import serialization

            blob = bytes(view[_META.size:_META.size + hlen])
            if slot._load()[0] != slot._pending_read_seq:
                # close() force-published over the slot mid-copy; the only
                # force-publisher is teardown.
                slot._ack_current()
                raise ChannelClosed(self.name)
            slot._ack_current()
            value = serialization.loads(blob)
            if isinstance(value, bytes) and value == _CLOSE_SENTINEL:
                raise ChannelClosed(self.name)
            return value
        dtype_str, shape = pickle.loads(
            bytes(view[_META.size:_META.size + hlen]))
        off = _META.size + hlen
        host = np.frombuffer(view[off:length], dtype=np.dtype(dtype_str))
        host = host.reshape(shape)
        import jax

        if self._sharding is not None:
            dev_arr = jax.device_put(host, self._sharding)
        elif self._device is not None:
            dev_arr = jax.device_put(host, self._device)
        else:
            dev_arr = jax.device_put(host)
        # DEFERRED ack: the host→device upload may still be reading the
        # shm bytes; ack only once it lands — usually on the NEXT read,
        # by which point the writer has been filling the other slot.
        self._pending_ack = (slot, dev_arr, slot._pending_read_seq)
        return dev_arr

    def _complete_pending_ack(self) -> None:
        if self._pending_ack is None:
            return
        slot, dev_arr, seq = self._pending_ack
        self._pending_ack = None
        try:
            dev_arr.block_until_ready()
        except Exception:  # noqa: BLE001 — deleted/donated array: DMA done
            pass
        if slot._load()[0] != seq:
            # A teardown force-publish overwrote the slot while the upload
            # was in flight — the consumer's tensor may be torn. Surface
            # it as the close it is rather than silent corruption.
            slot._ack_current()
            raise ChannelClosed(self.name)
        slot._ack_current()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        from ray_tpu.core import serialization

        slot = self._slots[self._wcursor % 2]
        self._wcursor += 1
        blob = serialization.dumps(_CLOSE_SENTINEL)
        payload = _META.pack(_KIND_PICKLE, len(blob)) + blob
        try:
            slot._write_payload(payload, timeout=0.5)
        except (ChannelTimeout, ValueError):
            # Force-publish the META-FRAMED pill (the raw underlying pill
            # would be misparsed by this channel's framed read path).
            slot._force_publish(payload)

    def destroy(self) -> None:
        self._complete_pending_ack()
        for s in self._slots:
            s.destroy()

    def __reduce__(self):
        return (DeviceChannel, (self.name, self.capacity, False,
                                self._device, self._sharding))


_CLOSE_SENTINEL = b"\x00__ray_tpu_device_channel_closed__"
