"""Device channels — on-device tensors between pipelined actor stages.

The device tier of the compiled-DAG transport (SURVEY §2.1 TPU mapping:
the aDAG mutable channels "map to on-device buffers with double-buffered
host DMA"; reference analog: the accelerator channels reached through
``python/ray/experimental/channel.py:51``, where GPU payloads ride NCCL
instead of plasma). Separate processes own separate PJRT clients, so a
tensor crossing an actor boundary must traverse host memory — the job of
this channel is to make that traversal cost ONE device→host DMA, one shm
landing, and one host→device DMA, with the two directions overlapped:

- the payload is written as dtype/shape header + raw buffer straight into
  the shm ring slot (no pickle on either side);
- the underlying :class:`~ray_tpu.dag.channel.Channel` ring (≥2 slots)
  generalizes the original ping-pong: the writer fills slot ``k+1`` while
  the reader's host→device upload of slot ``k`` is still in flight, so
  the DMA of one step hides behind the transfer of the next — the
  double-buffering half of the design;
- the reader gets a ``jax.Array`` committed to its device (or sharding),
  and only acks the slot once the upload is done — the writer can never
  overwrite bytes an in-flight DMA still reads.

Non-array payloads (control messages, pytrees with small leaves) fall back
to the pickled path of the underlying channel transparently.
"""

from __future__ import annotations

import pickle
import struct
import uuid
from typing import Any, Optional

import numpy as np

from ray_tpu.dag.channel import Channel, ChannelClosed, ChannelTimeout

# Payload kinds inside a slot: raw array (header + buffer) or pickled.
_KIND_ARRAY = 0
_KIND_PICKLE = 1
_META = struct.Struct("<BI")  # kind, header_len


class DeviceChannel:
    """Single-writer single-reader device-tensor channel (ring-buffered)."""

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 64 * 1024 * 1024, create: bool = True,
                 device: Any = None, sharding: Any = None,
                 slots: Optional[int] = None):
        self.name = name or f"rtpu-devchan-{uuid.uuid4().hex[:12]}"
        self.capacity = capacity
        # At least two slots — one in-flight upload + one being filled is
        # the minimum for the DMA overlap this channel exists for — even
        # when dag_channel_slots=1 pins plain channels to lock-step.
        from ray_tpu.dag.channel import _default_slots

        self._ch = Channel(f"{self.name}-ring", capacity=capacity,
                           create=create,
                           slots=max(2, slots if slots else _default_slots()))
        self._device = device
        self._sharding = sharding
        self._attached_endpoint = not create
        # The previous read's device array: its upload must be complete
        # before we ack the slot it came from (deferred ack = the overlap).
        self._pending_ack: Optional[tuple] = None

    # -- write ---------------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        arr = self._as_host_array(value)
        if arr is None:
            from ray_tpu.core import serialization

            blob = serialization.dumps(value)
            payload = _META.pack(_KIND_PICKLE, len(blob)) + blob
            self._ch._write_payload(payload, timeout)
            return
        header = pickle.dumps((arr.dtype.str, arr.shape))
        total = _META.size + len(header) + arr.nbytes
        if total > self.capacity:
            raise ValueError(
                f"array of {arr.nbytes} bytes exceeds device-channel "
                f"capacity {self.capacity}")
        # Write header+buffer directly into the ring slot's shm region —
        # the device→host DMA result lands once, no pickle copy.
        ch = self._ch
        ch._wait_writable(timeout)
        try:
            base = ch._wpayload_off
            mm = ch._mm
            _META.pack_into(mm, base, _KIND_ARRAY, len(header))
            mm[base + _META.size:base + _META.size + len(header)] = header
            off = base + _META.size + len(header)
            dst = np.frombuffer(memoryview(mm)[off:off + arr.nbytes],
                                dtype=np.uint8)
            dst[:] = np.ascontiguousarray(arr).reshape(-1).view(np.uint8)
        except BaseException:
            # Roll the seqlock back to even: a failed fill must not leave
            # the slot marked write-in-progress forever.
            ch._abort_write()
            raise
        ch._publish(total)

    @staticmethod
    def _as_host_array(value) -> Optional[np.ndarray]:
        """Host ndarray for array-likes; None for everything else.
        jax.Arrays start their device→host DMA here (np.asarray blocks
        until the transfer lands — by then the PREVIOUS slot's write is
        already visible to the reader, which is the overlap)."""
        try:
            import jax

            if isinstance(value, jax.Array):
                return np.asarray(value)
        except ImportError:  # pragma: no cover - jax is a hard dep
            pass
        if isinstance(value, np.ndarray) and value.dtype != object:
            # object-dtype arrays hold pointers — raw bytes would be
            # garbage cross-process; they take the pickled path.
            return value
        return None

    # -- read ----------------------------------------------------------------

    def read(self, timeout: Optional[float] = 30.0) -> Any:
        """Next value as a ``jax.Array`` on this channel's device/sharding
        (raw arrays) or the pickled object (control payloads)."""
        self._complete_pending_ack()
        ch = self._ch
        view, length, slot, seq = ch._consume_view(timeout)
        kind, hlen = _META.unpack_from(view, 0)
        if kind == _KIND_PICKLE:
            from ray_tpu.core import serialization

            blob = bytes(view[_META.size:_META.size + hlen])
            if ch._load(slot)[0] != seq:
                # close() force-published over the slot mid-copy; the only
                # force-publisher is teardown.
                ch._ack(slot, ch._load(slot)[0])
                raise ChannelClosed(self.name)
            ch._ack(slot, seq)
            value = serialization.loads(blob)
            if isinstance(value, bytes) and value == _CLOSE_SENTINEL:
                raise ChannelClosed(self.name)
            return value
        dtype_str, shape = pickle.loads(
            bytes(view[_META.size:_META.size + hlen]))
        off = _META.size + hlen
        host = np.frombuffer(view[off:length], dtype=np.dtype(dtype_str))
        host = host.reshape(shape)
        import jax

        if self._sharding is not None:
            dev_arr = jax.device_put(host, self._sharding)
        elif self._device is not None:
            dev_arr = jax.device_put(host, self._device)
        else:
            dev_arr = jax.device_put(host)
        # DEFERRED ack: the host→device upload may still be reading the
        # shm bytes; ack only once it lands — usually on the NEXT read,
        # by which point the writer has been filling the next ring slot.
        self._pending_ack = (slot, seq, dev_arr)
        return dev_arr

    def _complete_pending_ack(self) -> None:
        if self._pending_ack is None:
            return
        slot, seq, dev_arr = self._pending_ack
        self._pending_ack = None
        try:
            dev_arr.block_until_ready()
        except Exception:  # noqa: BLE001 — deleted/donated array: DMA done
            pass
        if self._ch._load(slot)[0] != seq:
            # A teardown force-publish overwrote the slot while the upload
            # was in flight — the consumer's tensor may be torn. Surface
            # it as the close it is rather than silent corruption.
            self._ch._ack(slot, self._ch._load(slot)[0])
            raise ChannelClosed(self.name)
        self._ch._ack(slot, seq)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        from ray_tpu.core import serialization

        blob = serialization.dumps(_CLOSE_SENTINEL)
        payload = _META.pack(_KIND_PICKLE, len(blob)) + blob
        try:
            self._ch._write_payload(payload, timeout=0.5)
        except (ChannelTimeout, ValueError):
            # Force-publish the META-FRAMED pill (the raw underlying pill
            # would be misparsed by this channel's framed read path).
            self._ch._force_publish(payload)

    def _settle(self) -> None:
        try:
            self._complete_pending_ack()
        except ChannelClosed:
            pass  # teardown overwrote the in-flight slot — expected here

    def detach(self) -> None:
        """Worker-side endpoint close (no unlink); see Channel.detach."""
        self._settle()
        self._ch.detach()

    def destroy(self) -> None:
        self._settle()
        self._ch.destroy()

    def __reduce__(self):
        return (DeviceChannel, (self.name, self.capacity, False,
                                self._device, self._sharding,
                                self._ch.slots))


_CLOSE_SENTINEL = b"\x00__ray_tpu_device_channel_closed__"
