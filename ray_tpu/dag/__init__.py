"""ray_tpu.dag — compiled static actor DAGs (aDAG analog).

Public surface mirrors ``python/ray/dag``: ``InputNode``, multi-arg
``.bind()`` on actor methods, ``MultiOutputNode`` for gathered leaves,
``experimental_compile()`` → resident actor loops over mutable multi-slot
shm ring channels (same host), credit-windowed socket channels (cross
host), or device channels (``jax.Array`` payloads with ring-buffered host
DMA).
"""

from ray_tpu.dag.channel import (Channel, ChannelClosed, ChannelTimeout,
                                 SocketChannel)
from ray_tpu.dag.compiled_dag import CompiledDAG, DAGRef
from ray_tpu.dag.dag_node import (ClassMethodNode, DAGNode, InputNode,
                                  MultiOutputNode)
from ray_tpu.dag.device_channel import DeviceChannel

__all__ = [
    "InputNode",
    "DAGNode",
    "ClassMethodNode",
    "MultiOutputNode",
    "CompiledDAG",
    "DAGRef",
    "Channel",
    "SocketChannel",
    "ChannelClosed",
    "ChannelTimeout",
    "DeviceChannel",
]
