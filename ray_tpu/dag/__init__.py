"""ray_tpu.dag — compiled static actor DAGs (aDAG analog).

Public surface mirrors ``python/ray/dag``: ``InputNode``, ``.bind()`` on
actor methods, ``experimental_compile()`` → resident actor loops over
mutable shm channels (same-host scope in v1; the reference's cross-node
channel transport is a later extension).
"""

from ray_tpu.dag.channel import Channel, ChannelClosed, ChannelTimeout
from ray_tpu.dag.compiled_dag import CompiledDAG, DAGRef
from ray_tpu.dag.dag_node import ClassMethodNode, DAGNode, InputNode
from ray_tpu.dag.device_channel import DeviceChannel

__all__ = [
    "InputNode",
    "DAGNode",
    "ClassMethodNode",
    "CompiledDAG",
    "DAGRef",
    "Channel",
    "ChannelClosed",
    "ChannelTimeout",
    "DeviceChannel",
]
