"""Mutable channels — reusable zero-allocation transport for compiled DAGs.

Analog of the reference's mutable plasma channels
(``python/ray/experimental/channel.py:51 Channel``, backed by
``experimental_mutable_object_manager.cc`` — seqlock-style mutable shm
objects): a fixed shm region written in place per DAG step instead of a
fresh sealed object per call. That removes the per-call allocate/seal/
locate/fetch round trips that dominate fine-grained pipelined execution.

Layout (one mmap'd file under /dev/shm, works in- and cross-process)::

    [0:8)   write_seq  — odd while a write is in progress (seqlock)
    [8:16)  ack_seq    — last write_seq the (single) reader consumed
    [16:24) payload_len
    [24:..) payload

Writer blocks until the previous value is acked (capacity-1 backpressure,
matching the reference); reader blocks until a new even write_seq appears.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import uuid
from typing import Any, Optional, Tuple

from ray_tpu.core import serialization

_HEADER = struct.Struct("<QQQ")
HEADER_SIZE = _HEADER.size
_SPIN_S = 50e-6
# Busy-spin iterations before falling back to sleep-polling. 0: measured on
# core-constrained hosts, spinning starves the peer process of the CPU it
# needs to make progress (1540µs round trip at 2000 spins vs 190µs at 0);
# sleep granularity bounds added latency at ~2×_SPIN_S on idle cores.
_TIGHT_SPINS = 0
_SPIN_MAX_S = 2e-3  # idle-poll ceiling (backoff)


class ChannelTimeout(TimeoutError):
    pass


class ChannelClosed(Exception):
    pass


_CLOSE = b"\x00__ray_tpu_channel_closed__"


class Channel:
    """Single-writer single-reader mutable channel over shm."""

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 4 * 1024 * 1024, create: bool = True):
        self.name = name or f"rtpu-chan-{uuid.uuid4().hex[:12]}"
        self.capacity = capacity
        path = f"/dev/shm/{self.name}"
        size = HEADER_SIZE + capacity
        if create and not os.path.exists(path):
            with open(path, "wb") as f:
                f.truncate(size)
        self._f = open(path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), size)
        self._read_seq = 0  # last seq this reader consumed

    # -- header accessors -----------------------------------------------------

    def _load(self) -> Tuple[int, int, int]:
        return _HEADER.unpack_from(self._mm, 0)

    def _store_write_seq(self, v: int) -> None:
        struct.pack_into("<Q", self._mm, 0, v)

    def _store_ack(self, v: int) -> None:
        struct.pack_into("<Q", self._mm, 8, v)

    # -- API ------------------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        # ALWAYS serialize — read() always deserializes; a raw-bytes fast
        # path would misparse user bytes payloads (the close pill goes
        # through _write_raw instead).
        self._write_payload(serialization.dumps(value), timeout)

    def _write_payload(self, payload: bytes, timeout: Optional[float]) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity}")
        deadline = None if timeout is None else time.time() + timeout
        spins = 0
        while True:
            write_seq, ack_seq, _ = self._load()
            if write_seq % 2 == 0 and ack_seq == write_seq:
                break  # previous value consumed (or channel fresh)
            if deadline is not None and time.time() > deadline:
                raise ChannelTimeout(f"writer blocked on unread value in {self.name}")
            spins += 1
            if spins > _TIGHT_SPINS:
                # Exponential backoff to _SPIN_MAX_S: hot hand-offs stay at
                # ~_SPIN_S latency, parked DAG loops stop burning ~20k
                # wakeups/s per stage while idle.
                time.sleep(min(_SPIN_S * (1 << min(spins // 64, 6)), _SPIN_MAX_S))
        self._store_write_seq(write_seq + 1)          # mark in-progress (odd)
        self._mm[HEADER_SIZE:HEADER_SIZE + len(payload)] = payload
        struct.pack_into("<Q", self._mm, 16, len(payload))
        self._store_write_seq(write_seq + 2)          # publish (even)

    def read(self, timeout: Optional[float] = 30.0) -> Any:
        """Block until a value newer than the last read appears; ack it."""
        deadline = None if timeout is None else time.time() + timeout
        spins = 0
        while True:
            write_seq, _ack, length = self._load()
            if write_seq % 2 == 0 and write_seq > self._read_seq:
                payload = bytes(self._mm[HEADER_SIZE:HEADER_SIZE + length])
                # seqlock validation: the writer can't start a new write
                # before our ack, so a single stability check suffices.
                if self._load()[0] == write_seq:
                    self._read_seq = write_seq
                    self._store_ack(write_seq)
                    if payload == _CLOSE:
                        raise ChannelClosed(self.name)
                    return serialization.loads(payload)
            if deadline is not None and time.time() > deadline:
                raise ChannelTimeout(f"no value arrived in {self.name}")
            spins += 1
            if spins > _TIGHT_SPINS:
                time.sleep(min(_SPIN_S * (1 << min(spins // 64, 6)), _SPIN_MAX_S))

    def close(self) -> None:
        """Wake the reader with a poison pill (teardown path)."""
        try:
            self._write_payload(_CLOSE, timeout=0.5)
        except (ChannelTimeout, ValueError):
            # Reader never drained the last value; force-publish the pill.
            write_seq, _, _ = self._load()
            base = write_seq if write_seq % 2 == 0 else write_seq + 1
            self._store_write_seq(base + 1)
            self._mm[HEADER_SIZE:HEADER_SIZE + len(_CLOSE)] = _CLOSE
            struct.pack_into("<Q", self._mm, 16, len(_CLOSE))
            self._store_write_seq(base + 2)

    def destroy(self) -> None:
        try:
            self._mm.close()
            self._f.close()
        except OSError:
            pass
        try:
            os.unlink(f"/dev/shm/{self.name}")
        except OSError:
            pass

    def __reduce__(self):
        # Cross-process handle: reattach by name.
        return (Channel, (self.name, self.capacity, False))
