"""Mutable channels — reusable zero-allocation transport for compiled DAGs.

Analog of the reference's mutable plasma channels
(``python/ray/experimental/channel.py:51 Channel``, backed by
``experimental_mutable_object_manager.cc`` — seqlock-style mutable shm
objects): a fixed shm region written in place per DAG step instead of a
fresh sealed object per call. That removes the per-call allocate/seal/
locate/fetch round trips that dominate fine-grained pipelined execution.

The channel is an N-slot ring (``dag_channel_slots`` knob). Each slot is an
independent seqlock cell; writer and reader walk the ring with private
cursors, so up to N values can be in flight on one edge before the writer
blocks on the reader's ack — burst submission pipelines through a compiled
DAG's stages instead of serializing on per-value hand-offs. ``slots=1``
restores the strict capacity-1 lock-step of the original design.

Layout (one mmap'd file under /dev/shm, works in- and cross-process)::

    [0:8)    nslots (stamped by the creator; attach verifies)
    per slot, at 8 + i * stride (stride = 64-byte-aligned header+capacity):
      [0:8)   write_seq  — odd while a write is in progress (seqlock)
      [8:16)  ack_seq    — last write_seq the (single) reader consumed
      [16:24) payload_len
      [24:..) payload

Writer blocks when the ring is full (its next slot's previous value is not
yet acked); reader blocks until a new even write_seq appears in its slot.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import uuid
from typing import Any, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.util import flightrec

_FILE_HEADER = struct.Struct("<Q")  # nslots


def _stall_after_s() -> float:
    """How long a channel wait may block before the flight recorder calls
    it a stall — a quarter of the internal wait budget, so the ring names
    a wedged stage well before the ChannelTimeout fires."""
    from ray_tpu.core.config import config

    return max(0.05, float(config().internal_wait_timeout_s) / 4.0)
_SLOT_HEADER = struct.Struct("<QQQ")  # write_seq, ack_seq, payload_len
FILE_HEADER_SIZE = _FILE_HEADER.size
SLOT_HEADER_SIZE = _SLOT_HEADER.size
# Kept for DeviceChannel-era imports; the per-slot payload offset.
HEADER_SIZE = SLOT_HEADER_SIZE


def _spin_params() -> Tuple[int, float, float]:
    """(tight_spins, spin_s, spin_max_s) from the config knobs.

    Resolved per channel instance (not per wait iteration): the knobs are
    process-lifetime settings, and config() is a lock + dict hit.
    """
    from ray_tpu.core.config import config

    cfg = config()
    spin_s = max(1e-6, float(cfg.dag_channel_spin_us) * 1e-6)
    # Idle-poll ceiling: exponential backoff stops at 40x the granularity
    # (2ms at the 50us default) so parked DAG loops stop burning wakeups.
    return int(cfg.dag_channel_tight_spins), spin_s, spin_s * 40.0


def _default_slots() -> int:
    from ray_tpu.core.config import config

    return max(1, int(config().dag_channel_slots))


class ChannelTimeout(TimeoutError):
    pass


class ChannelClosed(Exception):
    pass


_CLOSE = b"\x00__ray_tpu_channel_closed__"


class Channel:
    """Single-writer single-reader mutable ring channel over shm."""

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 4 * 1024 * 1024, create: bool = True,
                 slots: Optional[int] = None):
        self.name = name or f"rtpu-chan-{uuid.uuid4().hex[:12]}"
        self.capacity = capacity
        self.slots = max(1, int(slots)) if slots else _default_slots()
        # 64-byte-align each slot so seqlock headers sit on their own cache
        # lines (writer and reader hammer adjacent slots concurrently).
        self._stride = -(-(SLOT_HEADER_SIZE + capacity) // 64) * 64
        path = f"/dev/shm/{self.name}"
        size = FILE_HEADER_SIZE + self.slots * self._stride
        created = False
        if create and not os.path.exists(path):
            with open(path, "wb") as f:
                f.truncate(size)
            created = True
        self._f = open(path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), size)
        if created:
            _FILE_HEADER.pack_into(self._mm, 0, self.slots)
        else:
            stamped = _FILE_HEADER.unpack_from(self._mm, 0)[0]
            if stamped and stamped != self.slots:
                self._mm.close()
                self._f.close()
                raise ValueError(
                    f"channel {self.name} has {stamped} slots; attach "
                    f"requested {self.slots}")
        self._tight_spins, self._spin_s, self._spin_max_s = _spin_params()
        # Reattached (unpickled) endpoints detach themselves at DAG-loop
        # exit; the creating endpoint's lifecycle belongs to the driver.
        self._attached_endpoint = not create
        # Private cursors: count of completed writes / reads. Slot index is
        # cursor % slots; both endpoints start at 0 (fresh or attach-by-name
        # before first use, the same contract the capacity-1 channel had).
        self._wcursor = 0
        self._rcursor = 0
        # Last write_seq consumed per slot (reader-private).
        self._read_seq = [0] * self.slots
        flightrec.record("channel", self.name[:32],
                         "create" if created else "attach")

    # -- header accessors -----------------------------------------------------

    def _slot_off(self, i: int) -> int:
        return FILE_HEADER_SIZE + i * self._stride

    def _load(self, i: int) -> Tuple[int, int, int]:
        return _SLOT_HEADER.unpack_from(self._mm, self._slot_off(i))

    def _store_write_seq(self, i: int, v: int) -> None:
        struct.pack_into("<Q", self._mm, self._slot_off(i), v)

    def _store_ack(self, i: int, v: int) -> None:
        struct.pack_into("<Q", self._mm, self._slot_off(i) + 8, v)

    def _sleep_poll(self, spins: int) -> None:
        # Exponential backoff to the ceiling: hot hand-offs stay at
        # ~spin_s latency, parked DAG loops stop burning ~20k wakeups/s
        # per stage while idle.
        time.sleep(min(self._spin_s * (1 << min(spins // 64, 6)),
                       self._spin_max_s))

    # -- write half -----------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        # ALWAYS serialize — read() always deserializes; a raw-bytes fast
        # path would misparse user bytes payloads (the close pill goes
        # through _force_publish framing instead).
        self._write_payload(serialization.dumps(value), timeout)

    def _wait_writable(self, timeout: Optional[float]) -> None:
        """Block until this writer's next ring slot is free (its previous
        value acked), then mark a write in progress (odd seq). Split out so
        callers (DeviceChannel) can land payload bytes DIRECTLY in the shm
        region — ``self._wpayload_off`` — between this and ``_publish``,
        no intermediate buffer."""
        started = time.monotonic()
        deadline = (None if timeout is None
                    else started + timeout)
        stall_at = started + _stall_after_s()
        stalled = False
        slot = self._wcursor % self.slots
        spins = 0
        while True:
            write_seq, ack_seq, _ = self._load(slot)
            if write_seq % 2 == 0 and ack_seq == write_seq:
                break  # slot's previous value consumed (or slot fresh)
            now = time.monotonic()
            if deadline is not None and now > deadline:
                raise ChannelTimeout(
                    f"writer blocked on full ring in {self.name} "
                    f"(slot {slot}/{self.slots})")
            if not stalled and now > stall_at:
                stalled = True
                flightrec.record("channel", self.name[:32],
                                 f"write stall slot={slot}")
            spins += 1
            if spins > self._tight_spins:
                self._sleep_poll(spins)
        if stalled:
            flightrec.record(
                "channel", self.name[:32],
                f"write resume after {time.monotonic() - started:.1f}s")
        self._store_write_seq(slot, write_seq + 1)  # mark in-progress (odd)
        self._pending_write_seq = write_seq
        self._wslot = slot
        self._wpayload_off = self._slot_off(slot) + SLOT_HEADER_SIZE

    def _publish(self, length: int) -> None:
        struct.pack_into("<Q", self._mm, self._slot_off(self._wslot) + 16,
                         length)
        self._store_write_seq(self._wslot, self._pending_write_seq + 2)
        self._wcursor += 1

    def _abort_write(self) -> None:
        """Roll a begun (odd) write back to even without advancing the
        cursor — a failed slot fill must not wedge the seqlock."""
        self._store_write_seq(self._wslot, self._pending_write_seq)

    def _write_payload(self, payload: bytes, timeout: Optional[float]) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity}")
        self._wait_writable(timeout)
        off = self._wpayload_off
        self._mm[off:off + len(payload)] = payload
        self._publish(len(payload))

    # -- read half ------------------------------------------------------------

    def _read_view(self, timeout: Optional[float]):
        """Block for the next value; return ``(view, length)`` WITHOUT
        acking or advancing — the bytes stay stable (the writer can't reuse
        the slot before our ack) until the caller's ``_ack_current``. The
        zero-copy read half of the DeviceChannel protocol. Idempotent until
        acked, which is what lets ``read()`` retry a torn copy."""
        started = time.monotonic()
        deadline = (None if timeout is None
                    else started + timeout)
        stall_at = started + _stall_after_s()
        stalled = False
        slot = self._rcursor % self.slots
        spins = 0
        while True:
            write_seq, _ack, length = self._load(slot)
            if write_seq % 2 == 0 and write_seq > self._read_seq[slot]:
                if stalled:
                    flightrec.record(
                        "channel", self.name[:32],
                        f"read resume after "
                        f"{time.monotonic() - started:.1f}s")
                self._pending_read_seq = write_seq
                self._rslot = slot
                off = self._slot_off(slot) + SLOT_HEADER_SIZE
                return memoryview(self._mm)[off:off + length], length
            now = time.monotonic()
            if deadline is not None and now > deadline:
                raise ChannelTimeout(f"no value arrived in {self.name}")
            if not stalled and now > stall_at:
                stalled = True
                flightrec.record("channel", self.name[:32],
                                 f"read stall slot={slot}")
            spins += 1
            if spins > self._tight_spins:
                self._sleep_poll(spins)

    def _ack_current(self) -> None:
        self._ack(self._rslot, self._pending_read_seq)
        self._rcursor += 1

    def _ack(self, slot: int, seq: int) -> None:
        """Release one slot back to the writer (deferred-ack primitive:
        DeviceChannel acks slot k only once k's host->device DMA landed,
        possibly after reading slot k+1)."""
        self._read_seq[slot] = seq
        self._store_ack(slot, seq)

    def _consume_view(self, timeout: Optional[float]):
        """Advancing read for pipelined consumers: returns ``(view, length,
        slot, seq)`` and moves the read cursor on, WITHOUT acking — the
        caller owns the eventual ``_ack(slot, seq)``. Unlike ``_read_view``
        a subsequent call proceeds to the next ring slot immediately."""
        view, length = self._read_view(timeout)
        slot, seq = self._rslot, self._pending_read_seq
        self._read_seq[slot] = seq  # consumed (ack still pending)
        self._rcursor += 1
        return view, length, slot, seq

    def read(self, timeout: Optional[float] = 30.0) -> Any:
        """Block until a value newer than the last read appears; ack it."""
        while True:
            view, length = self._read_view(timeout)
            payload = bytes(view[:length])
            # Stability recheck: a close() FORCE-publish may overwrite the
            # payload mid-copy (the one writer path that skips the ack
            # handshake); a changed seq means the copy is torn — retry and
            # pick up the pill.
            if self._load(self._rslot)[0] == self._pending_read_seq:
                break
        self._ack_current()
        if payload == _CLOSE:
            raise ChannelClosed(self.name)
        return serialization.loads(payload)

    # -- lifecycle ------------------------------------------------------------

    def _force_publish(self, payload: bytes) -> None:
        """Teardown-only: publish ``payload`` into the writer's CURRENT
        ring slot WITHOUT waiting for the reader's ack (used when the ring
        is full because the reader never drained). The pill overwrites one
        undelivered value — readers detect a torn copy via the stability
        recheck, and the bumped seq satisfies their wait when the cursor
        reaches this slot."""
        slot = self._wcursor % self.slots
        write_seq, _, _ = self._load(slot)
        base = write_seq if write_seq % 2 == 0 else write_seq + 1
        self._store_write_seq(slot, base + 1)
        off = self._slot_off(slot) + SLOT_HEADER_SIZE
        self._mm[off:off + len(payload)] = payload
        struct.pack_into("<Q", self._mm, self._slot_off(slot) + 16,
                         len(payload))
        self._store_write_seq(slot, base + 2)

    def close(self) -> None:
        """Wake the reader with a poison pill (teardown path)."""
        try:
            self._write_payload(_CLOSE, timeout=0.5)
        except (ChannelTimeout, ValueError):
            # Ring full (reader never drained); force-publish the pill.
            self._force_publish(_CLOSE)

    def detach(self) -> None:
        """Close THIS endpoint's mmap/fd without unlinking the backing
        file — the worker-side half of teardown (the driver, which created
        the channel, unlinks in ``destroy``). Idempotent."""
        try:
            self._mm.close()
        except (OSError, BufferError):
            # BufferError: a zero-copy view handed out by _read_view is
            # still referenced (e.g. a device array's source buffer whose
            # consumer hasn't been collected yet) — the mmap closes when
            # the last view dies.
            pass
        try:
            self._f.close()  # its own try: the fd must not leak when
        except OSError:      # mm.close() raised above
            pass

    def destroy(self) -> None:
        self.detach()
        try:
            os.unlink(f"/dev/shm/{self.name}")
        except OSError:
            pass

    def __reduce__(self):
        # Cross-process handle: reattach by name (same slot geometry).
        return (Channel, (self.name, self.capacity, False, self.slots))


class SocketChannel:
    """Single-writer single-reader channel ACROSS HOSTS (the reference's
    aDAG channels run cross-node, ``experimental/channel.py:51``; shm can't).

    Same surface and semantics as :class:`Channel` — a ring of in-flight
    values with backpressure — over a TCP stream with CREDIT-BASED acks:
    the writer may run ``dag_socket_window`` frames ahead of the reader's
    acks (the reader acks each frame as its read returns), so burst
    submission pipelines over the wire instead of stalling on a per-frame
    ack round-trip. ``window=1`` restores strict lock-step. Roles bind
    lazily: the first ``read()`` makes this end the reader (it listens and
    publishes its address in the control plane's KV under the channel
    name); the first ``write()`` makes it the writer (it polls the KV and
    connects). Frames are length-prefixed.
    """

    _ACK = b"\x06\x00\x00\x00\x00\x00\x00\x01"

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 4 * 1024 * 1024, create: bool = True,
                 window: Optional[int] = None):
        from ray_tpu.core.config import config

        self.name = name or f"rtpu-schan-{uuid.uuid4().hex[:12]}"
        self.capacity = capacity  # parity with Channel; frames are unbounded
        self.window = (max(1, int(window)) if window
                       else max(1, int(config().dag_socket_window)))
        self._sock = None
        self._listener = None
        self._role: Optional[str] = None
        self._unacked = 0
        self._closed = False
        self._attached_endpoint = not create

    # -- rendezvous -----------------------------------------------------------

    def _kv(self):
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().gcs

    def _become_reader(self, timeout: Optional[float]) -> None:
        import socket as _socket

        self._role = "reader"
        lst = _socket.socket()
        lst.bind(("0.0.0.0", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        self._listener = lst
        # Publish host AND port: the writer may sit on another machine —
        # loopback would only ever work same-host (which auto mode gives
        # to shm anyway). The reader's reachable interface is the one its
        # runtime registered with the control plane.
        self._kv().kv_put(f"dag_channel:{self.name}",
                          f"{self._my_host()}:{port}".encode(),
                          namespace="dag")
        lst.settimeout(timeout if timeout is not None else None)
        try:
            conn, _addr = lst.accept()
        except _socket.timeout as e:
            raise ChannelTimeout(
                f"writer never connected to {self.name}") from e
        conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        conn.settimeout(None)
        self._sock = conn

    @staticmethod
    def _my_host() -> str:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        for attr in ("owner_address", "_node_address"):
            addr = getattr(rt, attr, None)
            if addr:
                return addr.rsplit(":", 1)[0]
        return "127.0.0.1"

    def _become_writer(self, timeout: Optional[float]) -> None:
        import socket as _socket

        self._role = "writer"
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            raw = self._kv().kv_get(f"dag_channel:{self.name}",
                                    namespace="dag")
            if raw:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeout(
                    f"reader of {self.name} never published its address")
            time.sleep(0.02)
        host, port = raw.decode().rsplit(":", 1)
        sock = _socket.create_connection((host, int(port)),
                                         timeout=timeout or 60.0)
        sock.settimeout(None)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._sock = sock

    # -- IO -------------------------------------------------------------------

    def _send_frame(self, payload: bytes) -> None:
        self._sock.sendall(len(payload).to_bytes(8, "big") + payload)

    def _recv_exact(self, n: int, timeout: Optional[float]) -> bytes:
        # Partial data RIDES OVER timeouts in self._rx: a timed-out read
        # must be retryable without desyncing the length-prefixed stream
        # (discarding a half-received payload would make the next read
        # parse payload bytes as a length prefix).
        if not hasattr(self, "_rx"):
            self._rx = bytearray()
        self._sock.settimeout(timeout)
        try:
            while len(self._rx) < n:
                try:
                    chunk = self._sock.recv(65536)
                except TimeoutError as e:
                    raise ChannelTimeout(f"no data in {self.name}") from e
                if not chunk:
                    raise ChannelClosed(self.name)
                self._rx.extend(chunk)
            out = bytes(self._rx[:n])
            del self._rx[:n]
            return out
        finally:
            # Back to blocking mode: a lingering recv timeout would make a
            # later sendall of a large frame fail MID-WRITE and desync the
            # length-prefixed stream.
            self._sock.settimeout(None)

    def _drain_acks(self) -> None:
        """Opportunistically consume every ack already on the wire without
        blocking — the credit-refill half of the windowed protocol. The
        writer's socket only ever carries acks, so buffered bytes parse as
        fixed 8-byte frames."""
        if not hasattr(self, "_rx"):
            self._rx = bytearray()
        try:
            self._sock.settimeout(0.0)
            while True:
                chunk = self._sock.recv(65536)
                if not chunk:
                    raise ChannelClosed(self.name)
                self._rx.extend(chunk)
        except (BlockingIOError, InterruptedError):
            pass
        finally:
            self._sock.settimeout(None)
        while len(self._rx) >= 8 and self._unacked > 0:
            ack = bytes(self._rx[:8])
            del self._rx[:8]
            if ack != self._ACK:
                raise ChannelClosed(self.name)
            self._unacked -= 1

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        self._write_payload(serialization.dumps(value), timeout)

    def _write_payload(self, payload: bytes, timeout: Optional[float]) -> None:
        if self._sock is None:
            self._become_writer(timeout)
        self._drain_acks()
        if self._unacked >= self.window:
            # Window exhausted: block for exactly one credit before
            # publishing the next frame.
            ack = self._recv_exact(8, timeout)
            if ack != self._ACK:
                raise ChannelClosed(self.name)
            self._unacked -= 1
        self._send_frame(payload)
        self._unacked += 1

    def read(self, timeout: Optional[float] = 30.0) -> Any:
        if self._sock is None:
            self._become_reader(timeout)
        length = int.from_bytes(self._recv_exact(8, timeout), "big")
        payload = self._recv_exact(length, timeout)
        if payload == _CLOSE:
            raise ChannelClosed(self.name)
        value = serialization.loads(payload)
        try:
            self._sock.sendall(self._ACK)
        except OSError:
            pass  # writer gone; the value still counts
        return value

    # -- lifecycle ------------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._sock is None:
                self._become_writer(timeout=5.0)
            self._send_frame(_CLOSE)
        except (ChannelTimeout, ChannelClosed, OSError):
            pass

    def detach(self) -> None:
        """Close this endpoint's socket/listener fds without touching the
        KV registration — the worker-side half of teardown. Idempotent."""
        for s in (self._sock, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = self._listener = None

    def destroy(self) -> None:
        self.detach()
        try:
            self._kv().kv_del(f"dag_channel:{self.name}", namespace="dag")
        except Exception:  # noqa: BLE001 — runtime already down
            pass

    def __reduce__(self):
        return (SocketChannel, (self.name, self.capacity, False, self.window))
