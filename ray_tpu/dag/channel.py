"""Mutable channels — reusable zero-allocation transport for compiled DAGs.

Analog of the reference's mutable plasma channels
(``python/ray/experimental/channel.py:51 Channel``, backed by
``experimental_mutable_object_manager.cc`` — seqlock-style mutable shm
objects): a fixed shm region written in place per DAG step instead of a
fresh sealed object per call. That removes the per-call allocate/seal/
locate/fetch round trips that dominate fine-grained pipelined execution.

Layout (one mmap'd file under /dev/shm, works in- and cross-process)::

    [0:8)   write_seq  — odd while a write is in progress (seqlock)
    [8:16)  ack_seq    — last write_seq the (single) reader consumed
    [16:24) payload_len
    [24:..) payload

Writer blocks until the previous value is acked (capacity-1 backpressure,
matching the reference); reader blocks until a new even write_seq appears.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import uuid
from typing import Any, Optional, Tuple

from ray_tpu.core import serialization

_HEADER = struct.Struct("<QQQ")
HEADER_SIZE = _HEADER.size
_SPIN_S = 50e-6
# Busy-spin iterations before falling back to sleep-polling. 0: measured on
# core-constrained hosts, spinning starves the peer process of the CPU it
# needs to make progress (1540µs round trip at 2000 spins vs 190µs at 0);
# sleep granularity bounds added latency at ~2×_SPIN_S on idle cores.
_TIGHT_SPINS = 0
_SPIN_MAX_S = 2e-3  # idle-poll ceiling (backoff)


class ChannelTimeout(TimeoutError):
    pass


class ChannelClosed(Exception):
    pass


_CLOSE = b"\x00__ray_tpu_channel_closed__"


class Channel:
    """Single-writer single-reader mutable channel over shm."""

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 4 * 1024 * 1024, create: bool = True):
        self.name = name or f"rtpu-chan-{uuid.uuid4().hex[:12]}"
        self.capacity = capacity
        path = f"/dev/shm/{self.name}"
        size = HEADER_SIZE + capacity
        if create and not os.path.exists(path):
            with open(path, "wb") as f:
                f.truncate(size)
        self._f = open(path, "r+b")
        self._mm = mmap.mmap(self._f.fileno(), size)
        self._read_seq = 0  # last seq this reader consumed

    # -- header accessors -----------------------------------------------------

    def _load(self) -> Tuple[int, int, int]:
        return _HEADER.unpack_from(self._mm, 0)

    def _store_write_seq(self, v: int) -> None:
        struct.pack_into("<Q", self._mm, 0, v)

    def _store_ack(self, v: int) -> None:
        struct.pack_into("<Q", self._mm, 8, v)

    # -- API ------------------------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        # ALWAYS serialize — read() always deserializes; a raw-bytes fast
        # path would misparse user bytes payloads (the close pill goes
        # through _write_raw instead).
        self._write_payload(serialization.dumps(value), timeout)

    def _wait_writable(self, timeout: Optional[float]) -> None:
        """Block until the previous value is acked, then mark a write in
        progress (odd seq). Split out so callers (DeviceChannel) can land
        payload bytes DIRECTLY in the shm region between this and
        ``_publish`` — no intermediate buffer."""
        deadline = None if timeout is None else time.time() + timeout
        spins = 0
        while True:
            write_seq, ack_seq, _ = self._load()
            if write_seq % 2 == 0 and ack_seq == write_seq:
                break  # previous value consumed (or channel fresh)
            if deadline is not None and time.time() > deadline:
                raise ChannelTimeout(f"writer blocked on unread value in {self.name}")
            spins += 1
            if spins > _TIGHT_SPINS:
                # Exponential backoff to _SPIN_MAX_S: hot hand-offs stay at
                # ~_SPIN_S latency, parked DAG loops stop burning ~20k
                # wakeups/s per stage while idle.
                time.sleep(min(_SPIN_S * (1 << min(spins // 64, 6)), _SPIN_MAX_S))
        self._store_write_seq(write_seq + 1)          # mark in-progress (odd)
        self._pending_write_seq = write_seq

    def _publish(self, length: int) -> None:
        struct.pack_into("<Q", self._mm, 16, length)
        self._store_write_seq(self._pending_write_seq + 2)  # publish (even)

    def _write_payload(self, payload: bytes, timeout: Optional[float]) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity}")
        self._wait_writable(timeout)
        self._mm[HEADER_SIZE:HEADER_SIZE + len(payload)] = payload
        self._publish(len(payload))

    def _read_view(self, timeout: Optional[float]):
        """Block for the next value; return ``(view, length)`` WITHOUT
        acking — the bytes stay stable (the writer can't start a new write
        before our ack) until the caller's ``_ack_current``. The zero-copy
        read half of the DeviceChannel protocol."""
        deadline = None if timeout is None else time.time() + timeout
        spins = 0
        while True:
            write_seq, _ack, length = self._load()
            if write_seq % 2 == 0 and write_seq > self._read_seq:
                self._pending_read_seq = write_seq
                return memoryview(self._mm)[
                    HEADER_SIZE:HEADER_SIZE + length], length
            if deadline is not None and time.time() > deadline:
                raise ChannelTimeout(f"no value arrived in {self.name}")
            spins += 1
            if spins > _TIGHT_SPINS:
                time.sleep(min(_SPIN_S * (1 << min(spins // 64, 6)), _SPIN_MAX_S))

    def _ack_current(self) -> None:
        self._read_seq = self._pending_read_seq
        self._store_ack(self._pending_read_seq)

    def read(self, timeout: Optional[float] = 30.0) -> Any:
        """Block until a value newer than the last read appears; ack it."""
        while True:
            view, length = self._read_view(timeout)
            payload = bytes(view[:length])
            # Stability recheck: a close() FORCE-publish may overwrite the
            # payload mid-copy (the one writer path that skips the ack
            # handshake); a changed seq means the copy is torn — retry and
            # pick up the pill.
            if self._load()[0] == self._pending_read_seq:
                break
        self._ack_current()
        if payload == _CLOSE:
            raise ChannelClosed(self.name)
        return serialization.loads(payload)

    def _force_publish(self, payload: bytes) -> None:
        """Teardown-only: publish ``payload`` WITHOUT waiting for the
        reader's ack (used when the reader never drained the last value).
        Readers detect the overwrite via the stability recheck."""
        write_seq, _, _ = self._load()
        base = write_seq if write_seq % 2 == 0 else write_seq + 1
        self._store_write_seq(base + 1)
        self._mm[HEADER_SIZE:HEADER_SIZE + len(payload)] = payload
        struct.pack_into("<Q", self._mm, 16, len(payload))
        self._store_write_seq(base + 2)

    def close(self) -> None:
        """Wake the reader with a poison pill (teardown path)."""
        try:
            self._write_payload(_CLOSE, timeout=0.5)
        except (ChannelTimeout, ValueError):
            # Reader never drained the last value; force-publish the pill.
            self._force_publish(_CLOSE)

    def destroy(self) -> None:
        try:
            self._mm.close()
        except (OSError, BufferError):
            # BufferError: a zero-copy view handed out by _read_view is
            # still referenced (e.g. a device array's source buffer whose
            # consumer hasn't been collected yet) — the mmap closes when
            # the last view dies; unlink the backing file regardless.
            pass
        try:
            self._f.close()  # its own try: the fd must not leak when
        except OSError:      # mm.close() raised above
            pass
        try:
            os.unlink(f"/dev/shm/{self.name}")
        except OSError:
            pass

    def __reduce__(self):
        # Cross-process handle: reattach by name.
        return (Channel, (self.name, self.capacity, False))


class SocketChannel:
    """Single-writer single-reader channel ACROSS HOSTS (the reference's
    aDAG channels run cross-node, ``experimental/channel.py:51``; shm can't).

    Same surface and semantics as :class:`Channel` — write blocks until the
    previous value was consumed (capacity-1 backpressure), read blocks for
    the next value — over a TCP stream. Roles bind lazily: the first
    ``read()`` makes this end the reader (it listens and publishes its
    address in the control plane's KV under the channel name); the first
    ``write()`` makes it the writer (it polls the KV and connects). Frames
    are length-prefixed; each is acked after the consumer's read returns.
    """

    _ACK = b"\x06\x00\x00\x00\x00\x00\x00\x01"

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 4 * 1024 * 1024, create: bool = True):
        self.name = name or f"rtpu-schan-{uuid.uuid4().hex[:12]}"
        self.capacity = capacity  # parity with Channel; frames are unbounded
        self._sock = None
        self._listener = None
        self._role: Optional[str] = None
        self._unacked = 0
        self._closed = False

    # -- rendezvous -----------------------------------------------------------

    def _kv(self):
        from ray_tpu.core.runtime import get_runtime

        return get_runtime().gcs

    def _become_reader(self, timeout: Optional[float]) -> None:
        import socket as _socket

        self._role = "reader"
        lst = _socket.socket()
        lst.bind(("0.0.0.0", 0))
        lst.listen(1)
        port = lst.getsockname()[1]
        self._listener = lst
        # Publish host AND port: the writer may sit on another machine —
        # loopback would only ever work same-host (which auto mode gives
        # to shm anyway). The reader's reachable interface is the one its
        # runtime registered with the control plane.
        self._kv().kv_put(f"dag_channel:{self.name}",
                          f"{self._my_host()}:{port}".encode(),
                          namespace="dag")
        lst.settimeout(timeout if timeout is not None else None)
        try:
            conn, _addr = lst.accept()
        except _socket.timeout as e:
            raise ChannelTimeout(
                f"writer never connected to {self.name}") from e
        conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        conn.settimeout(None)
        self._sock = conn

    @staticmethod
    def _my_host() -> str:
        from ray_tpu.core.runtime import get_runtime

        rt = get_runtime()
        for attr in ("owner_address", "_node_address"):
            addr = getattr(rt, attr, None)
            if addr:
                return addr.rsplit(":", 1)[0]
        return "127.0.0.1"

    def _become_writer(self, timeout: Optional[float]) -> None:
        import socket as _socket

        self._role = "writer"
        deadline = None if timeout is None else time.time() + timeout
        while True:
            raw = self._kv().kv_get(f"dag_channel:{self.name}",
                                    namespace="dag")
            if raw:
                break
            if deadline is not None and time.time() > deadline:
                raise ChannelTimeout(
                    f"reader of {self.name} never published its address")
            time.sleep(0.02)
        host, port = raw.decode().rsplit(":", 1)
        sock = _socket.create_connection((host, int(port)),
                                         timeout=timeout or 60.0)
        sock.settimeout(None)
        sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._sock = sock

    # -- IO -------------------------------------------------------------------

    def _send_frame(self, payload: bytes) -> None:
        self._sock.sendall(len(payload).to_bytes(8, "big") + payload)

    def _recv_exact(self, n: int, timeout: Optional[float]) -> bytes:
        # Partial data RIDES OVER timeouts in self._rx: a timed-out read
        # must be retryable without desyncing the length-prefixed stream
        # (discarding a half-received payload would make the next read
        # parse payload bytes as a length prefix).
        if not hasattr(self, "_rx"):
            self._rx = bytearray()
        self._sock.settimeout(timeout)
        try:
            while len(self._rx) < n:
                try:
                    chunk = self._sock.recv(65536)
                except TimeoutError as e:
                    raise ChannelTimeout(f"no data in {self.name}") from e
                if not chunk:
                    raise ChannelClosed(self.name)
                self._rx.extend(chunk)
            out = bytes(self._rx[:n])
            del self._rx[:n]
            return out
        finally:
            # Back to blocking mode: a lingering recv timeout would make a
            # later sendall of a large frame fail MID-WRITE and desync the
            # length-prefixed stream.
            self._sock.settimeout(None)

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        self._write_payload(serialization.dumps(value), timeout)

    def _write_payload(self, payload: bytes, timeout: Optional[float]) -> None:
        if self._sock is None:
            self._become_writer(timeout)
        if self._unacked >= 1:
            # capacity-1 backpressure: wait for the reader to consume the
            # previous value (its ack) before publishing the next.
            ack = self._recv_exact(8, timeout)
            if ack != self._ACK:
                raise ChannelClosed(self.name)
            self._unacked -= 1
        self._send_frame(payload)
        self._unacked += 1

    def read(self, timeout: Optional[float] = 30.0) -> Any:
        if self._sock is None:
            self._become_reader(timeout)
        length = int.from_bytes(self._recv_exact(8, timeout), "big")
        payload = self._recv_exact(length, timeout)
        if payload == _CLOSE:
            raise ChannelClosed(self.name)
        value = serialization.loads(payload)
        try:
            self._sock.sendall(self._ACK)
        except OSError:
            pass  # writer gone; the value still counts
        return value

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._sock is None:
                self._become_writer(timeout=5.0)
            self._send_frame(_CLOSE)
        except (ChannelTimeout, ChannelClosed, OSError):
            pass

    def destroy(self) -> None:
        for s in (self._sock, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = self._listener = None
        try:
            self._kv().kv_del(f"dag_channel:{self.name}", namespace="dag")
        except Exception:  # noqa: BLE001 — runtime already down
            pass

    def __reduce__(self):
        return (SocketChannel, (self.name, self.capacity, False))
