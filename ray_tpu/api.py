"""Public API — init/shutdown, remote, get/put/wait, kill/cancel, context.

The analog of the reference's top-level ``ray`` API
(``python/ray/_private/worker.py`` — ``init`` :1214, ``get``/``put``/``wait``
wrappers; ``python/ray/runtime_context.py``). Semantics match the reference:
``get`` re-raises remote exceptions, ``wait`` returns (ready, not_ready),
``kill`` terminates actors, named actors resolve through the GCS.
"""

from __future__ import annotations

import functools
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Sequence

from ray_tpu.core import runtime as _runtime_mod
from ray_tpu.core.actor import ActorClass, ActorHandle, get_actor
from ray_tpu.core.exceptions import RuntimeNotInitializedError
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.remote_function import RemoteFunction
from ray_tpu.core.runtime import get_runtime, init_runtime, shutdown_runtime


def init(
    *,
    address: str | None = None,
    resources: Dict[str, float] | None = None,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    num_nodes: int = 1,
    namespace: str = "default",
    system_config: Dict | None = None,
    labels: Dict[str, str] | None = None,
    ignore_reinit_error: bool = True,
):
    """Start the runtime.

    Without ``address``: head node + N virtual nodes in-process (the fast
    single-process runtime). With ``address="host:port"``: connect this
    process as a driver to a running multiprocess cluster's GCS (the
    ``ray.init(address=...)`` path — see ``ray_tpu.core.cluster``).
    """
    if _runtime_mod._global_runtime is not None:
        if ignore_reinit_error:
            return _runtime_mod._global_runtime
        raise RuntimeError("ray_tpu.init() already called")
    if address is not None:
        # Cluster shape is fixed by the running daemons; reject options that
        # would silently be ignored (the reference raises on this misuse too).
        ignored = {
            "resources": resources, "num_cpus": num_cpus,
            "num_tpus": num_tpus, "labels": labels,
            "system_config": system_config,
        }
        bad = [k for k, v in ignored.items() if v is not None]
        if num_nodes != 1:
            bad.append("num_nodes")
        if bad:
            raise ValueError(
                f"init(address=...) connects to an existing cluster; "
                f"{bad} cannot apply (configure the daemons instead)"
            )
        from ray_tpu.core.cluster import connect

        return connect(address, namespace=namespace)
    res = dict(resources or {})
    if num_cpus is not None:
        res["CPU"] = float(num_cpus)
    if num_tpus is not None:
        res["TPU"] = float(num_tpus)
    return init_runtime(
        resources=res or None,
        num_nodes=num_nodes,
        namespace=namespace,
        system_config=system_config,
        labels=labels,
    )


def shutdown():
    shutdown_runtime()


def is_initialized() -> bool:
    return _runtime_mod._global_runtime is not None


def _ensure_init():
    if _runtime_mod._global_runtime is None:
        init()
    return _runtime_mod._global_runtime


def remote(*args, **options):
    """``@remote`` decorator for functions and classes.

    Mirrors ``ray.remote``: bare (``@remote``) or parameterized
    (``@remote(num_tpus=1, max_retries=5)``).
    """

    def decorate(target):
        if isinstance(target, type):
            return ActorClass(target, options)
        if callable(target):
            return functools.wraps(target)(RemoteFunction(target, options))  # type: ignore[return-value]
        raise TypeError("@remote must decorate a function or class")

    if len(args) == 1 and not options and (callable(args[0]) or isinstance(args[0], type)):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return decorate


def get(refs, *, timeout: float | None = None):
    _ensure_init()
    return get_runtime().get(refs, timeout=timeout)


def put(value) -> ObjectRef:
    _ensure_init()
    return get_runtime().put(value)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
    fetch_local: bool = True,
):
    _ensure_init()
    return get_runtime().wait(refs, num_returns=num_returns, timeout=timeout, fetch_local=fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    get_runtime().kill_actor(actor.actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False):
    get_runtime().cancel(ref, force=force)


def nodes() -> List[dict]:
    rt = get_runtime()
    return [
        {
            "NodeID": n.node_id.hex(),
            "Alive": n.alive,
            "Resources": n.resources,
            "Labels": n.labels,
            "Address": n.address,
        }
        for n in rt.gcs.nodes.values()
    ]


def cluster_resources() -> Dict[str, float]:
    return get_runtime().gcs.cluster_resources()


def available_resources() -> Dict[str, float]:
    return get_runtime().scheduler.available_resources()


class RuntimeContext:
    """Reference: python/ray/runtime_context.py."""

    @property
    def job_id(self):
        return get_runtime().job_id

    @property
    def node_id(self):
        return get_runtime().current_node_id

    @property
    def task_id(self):
        return get_runtime().current_task_id

    @property
    def actor_id(self):
        return get_runtime().current_actor_id

    @property
    def namespace(self):
        return get_runtime().namespace

    def get_resources(self) -> Dict[str, float]:
        return cluster_resources()


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()


def _chrome_entry(e: dict) -> Optional[dict]:
    if e.get("state") not in ("FINISHED", "FAILED"):
        return None
    entry = {
        "name": e["name"],
        "cat": e.get("kind", "task"),
        "ph": "X",
        "ts": (e["time"] - e.get("duration", 0)) * 1e6,
        "dur": e.get("duration", 0) * 1e6,
        "pid": e.get("node_id", "node"),
        "tid": e["task_id"][:8],
    }
    if e.get("trace_id"):
        # span linkage (cross-process trace propagation)
        entry["args"] = {
            "trace_id": e["trace_id"],
            "span_id": e.get("span_id") or e.get("task_id"),
            "parent_span_id": e.get("parent_span_id"),
            "failed": e.get("state") == "FAILED",
        }
    return entry


def _flow_events(events: List[dict], entries: List[dict]) -> List[dict]:
    """Chrome flow events (``ph:"s"``/``ph:"f"``) linking parent→child
    spans across processes — the arrows in the trace viewer."""
    by_span: Dict[str, dict] = {}
    for e, entry in zip(events, entries):
        sid = e.get("span_id") or e.get("task_id")
        if sid:
            by_span[sid] = entry
    flows: List[dict] = []
    for e, entry in zip(events, entries):
        parent = e.get("parent_span_id")
        if not parent or parent not in by_span:
            continue
        sid = e.get("span_id") or e.get("task_id")
        src = by_span[parent]
        flows.append({"name": "span", "cat": "trace", "ph": "s", "id": sid,
                      "pid": src["pid"], "tid": src["tid"],
                      "ts": src["ts"]})
        flows.append({"name": "span", "cat": "trace", "ph": "f", "bp": "e",
                      "id": sid, "pid": entry["pid"], "tid": entry["tid"],
                      "ts": entry["ts"]})
    return flows


class _TimelineFeed:
    """Per-caller rolling chrome-trace cache: each call pulls only the NEW
    task events through cursor-paged ``task_events_since`` reads (the
    dashboard ``/api/events`` pattern) instead of copying and reconverting
    the whole up-to-100k-event log every time."""

    PAGE = 5000
    MAX_ENTRIES = 100_000

    def __init__(self, gcs):
        self.cursor = 0
        self.entries: List[dict] = []
        self.last_seen = time.monotonic()
        # Identity of the GCS this cursor indexes into — a new runtime means
        # a new event log, so a stale feed must restart from zero. A weakref
        # (not id()) so a freed-and-reallocated store can't alias the old.
        self.gcs_ref = weakref.ref(gcs)

    def pull(self, gcs) -> None:
        while True:
            self.cursor, events = gcs.task_events_since(self.cursor,
                                                        self.PAGE)
            for e in events:
                entry = _chrome_entry(e)
                if entry is not None:
                    self.entries.append(entry)
            if len(events) < self.PAGE:
                break
        if len(self.entries) > self.MAX_ENTRIES:
            del self.entries[:len(self.entries) // 2]


_TL_FEEDS: Dict[str, _TimelineFeed] = {}
_TL_LOCK = threading.Lock()
_TL_CLIENT_CAP = 32
_TL_CLIENT_TTL_S = 60.0


def timeline(trace_id: Optional[str] = None,
             client: str = "default") -> List[dict]:
    """Chrome-trace-style task events (reference:
    ``python/ray/_private/state.py:434 chrome_tracing_dump``).

    With ``trace_id``, returns ONE trace's events (an indexed GCS lookup)
    plus flow events linking parent→child spans across processes. Without,
    returns the rolling full timeline; ``client`` names the caller's
    incremental cursor cache."""
    gcs = get_runtime().gcs
    if trace_id is not None:
        events = gcs.trace(trace_id)
        entries = [_chrome_entry(e) for e in events]
        keep = [(e, en) for e, en in zip(events, entries) if en is not None]
        events, entries = [e for e, _ in keep], [en for _, en in keep]
        return entries + _flow_events(events, entries)
    now = time.monotonic()
    with _TL_LOCK:
        feed = _TL_FEEDS.get(client)
        if feed is not None and feed.gcs_ref() is not gcs:
            del _TL_FEEDS[client]
            feed = None
        if feed is None:
            for key, f in list(_TL_FEEDS.items()):
                if now - f.last_seen > _TL_CLIENT_TTL_S:
                    del _TL_FEEDS[key]
            while len(_TL_FEEDS) >= _TL_CLIENT_CAP:
                oldest = min(_TL_FEEDS, key=lambda k: _TL_FEEDS[k].last_seen)
                del _TL_FEEDS[oldest]
            feed = _TL_FEEDS[client] = _TimelineFeed(gcs)
        feed.last_seen = now
        feed.pull(gcs)
        return list(feed.entries)
