"""RuntimeEnv — per-task/actor execution environments.

Analog of the reference's runtime-env system (``python/ray/runtime_env/`` API;
plugins in ``_private/runtime_env/`` — conda/pip/working_dir/py_modules/
container). In-process runtime scope: ``env_vars`` (applied around task
execution under a global lock — one process, so env mutation must be
serialized), ``working_dir``/``py_modules`` (prepended to ``sys.path``);
``pip``/``conda``/``container`` are validated but deferred to process-backed
workers (they require spawning an isolated interpreter, which the in-process
node model doesn't do — the reference builds them in a per-node agent).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
from typing import Any, Dict, List, Optional

_env_lock = threading.Lock()

_SUPPORTED = {"env_vars", "working_dir", "py_modules", "pip", "conda", "container"}
# Isolation-requiring fields the IN-PROCESS runtime cannot honor (they need
# a separate interpreter/namespace); the multiprocess daemon builds all
# three (venv / conda prefix / container wrap — node_daemon.py).
_DEFERRED = {"pip", "conda", "container"}
# Fields that force a FRESH, dedicated worker process on the multiprocess
# runtime (env at spawn / isolated interpreter). ONE definition — the
# submit paths and the daemon all consult this.
_DEDICATED = {"env_vars", "pip", "conda", "container"}


def needs_dedicated_worker(env: Optional[Dict[str, Any]]) -> bool:
    """Whether this runtime env requires a fresh worker process (rather
    than a pooled vanilla one)."""
    return bool(env) and any(env.get(k) for k in _DEDICATED)


class RuntimeEnv(dict):
    """Validated runtime-env spec (reference: ``ray.runtime_env.RuntimeEnv``)."""

    def __init__(
        self,
        *,
        env_vars: Optional[Dict[str, str]] = None,
        working_dir: Optional[str] = None,
        py_modules: Optional[List[str]] = None,
        **kwargs,
    ):
        unknown = set(kwargs) - _SUPPORTED
        if unknown:
            raise ValueError(f"unsupported runtime_env fields: {sorted(unknown)}")
        spec: Dict[str, Any] = dict(kwargs)
        if "conda" in spec and not isinstance(spec["conda"], (str, dict)):
            raise TypeError("conda must be an env name/prefix (str) or an "
                            "environment.yml dict")
        if "container" in spec:
            if (not isinstance(spec["container"], dict)
                    or not spec["container"].get("image")):
                raise TypeError("container must be a dict with 'image'")
        if env_vars:
            if not all(isinstance(k, str) and isinstance(v, str) for k, v in env_vars.items()):
                raise TypeError("env_vars must be Dict[str, str]")
            spec["env_vars"] = dict(env_vars)
        if working_dir:
            if not os.path.isdir(working_dir):
                raise ValueError(f"working_dir {working_dir!r} does not exist")
            spec["working_dir"] = os.path.abspath(working_dir)
        if py_modules:
            spec["py_modules"] = [os.path.abspath(p) for p in py_modules]
        super().__init__(spec)

    def deferred_plugins(self) -> List[str]:
        """Fields requiring process-isolated workers (built by the node agent
        in the reference; inert in the in-process runtime)."""
        return sorted(set(self) & _DEFERRED)


@contextlib.contextmanager
def applied(env: Optional[Dict[str, Any]]):
    """Apply a runtime env around a task/actor execution."""
    if not env:
        yield
        return
    env_vars: Dict[str, str] = env.get("env_vars") or {}
    paths: List[str] = []
    if env.get("working_dir"):
        paths.append(env["working_dir"])
    paths.extend(env.get("py_modules") or [])

    with _env_lock:
        old_vars = {k: os.environ.get(k) for k in env_vars}
        os.environ.update(env_vars)
        old_sys_path = list(sys.path)
        for p in reversed(paths):
            sys.path.insert(0, p)
        try:
            yield
        finally:
            for k, v in old_vars.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            sys.path[:] = old_sys_path
