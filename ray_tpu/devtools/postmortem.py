"""Postmortem timeline assembly — the read side of the flight recorder.

``ray-tpu debug`` lands here: discover every per-process ring file under
the session dir (``util.flightrec``), decode them (the mmap'd pages
survived any SIGKILL), merge them with whatever the GCS still serves —
the task-event/trace side table and the watchdog's health states — into
one causal timeline, then point at the process that died or stalled and
what it had in flight.

Three layers, separable for tests:

- :func:`build_timeline` — pure assembly: rings + optional GCS tables →
  ``{processes, events, traces, diagnosis}`` (JSON-able).
- :func:`format_timeline` — render that structure for humans.
- :func:`parse_prometheus` — tiny exposition parser shared with
  ``ray-tpu status`` (the cluster rollup is the one read model both
  commands work from).
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional

from ray_tpu.util import flightrec

# trace ids embedded in ring event details ("... trace=<id>") — the
# cross-link key between a process's black box and the GCS trace table.
_TRACE_RE = re.compile(r"trace=([0-9a-f-]+)")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False
    return True


def build_timeline(session_dir: Optional[str] = None,
                   gcs_events: Optional[List[dict]] = None,
                   health_states: Optional[List[dict]] = None,
                   now: Optional[float] = None) -> Dict[str, Any]:
    """Merge ring files + GCS side tables into one timeline structure.

    ``gcs_events``/``health_states`` are optional — a postmortem often
    runs after the whole cluster (GCS included) is gone, and the rings
    alone must still tell the story.
    """
    import time as _time

    now = now if now is not None else _time.time()
    processes: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    for path in flightrec.discover_rings(session_dir):
        try:
            ring = flightrec.read_ring(path)
        except (OSError, ValueError):
            continue  # truncated/foreign file — postmortems take what's left
        label = f"{ring['component']}:{ring['pid']}"
        last = ring["events"][-1] if ring["events"] else None
        last_ts = last["ts"] if last else None
        alive = _pid_alive(ring["pid"])
        # flightrec.close() stamps a final "process … shutdown" record;
        # a ring ending any other way belongs to a process that died
        # without getting to say goodbye.
        clean_exit = bool(last and last["category"] == "process"
                          and "shutdown" in last["detail"])
        processes.append({
            "process": label, "component": ring["component"],
            "pid": ring["pid"], "path": path, "alive": alive,
            "clean_exit": clean_exit,
            "start_ts": ring["start_ts"], "written": ring["written"],
            "last_event_ts": last_ts,
            "last_event_age_s": (round(now - last_ts, 3)
                                 if last_ts else None),
        })
        for e in ring["events"]:
            events.append({**e, "process": label})
    for e in gcs_events or []:
        ev = {"ts": e.get("time", 0.0), "category": "gcs",
              "process": "gcs-table",
              "subject": str(e.get("task_id") or e.get("subject") or
                             e.get("name") or ""),
              "detail": _gcs_event_detail(e)}
        if e.get("trace_id"):
            ev["detail"] += f" trace={e['trace_id']}"
        events.append(ev)
    events.sort(key=lambda e: e["ts"])
    traces: Dict[str, List[int]] = {}
    for i, e in enumerate(events):
        for tid in _TRACE_RE.findall(e.get("detail", "")):
            traces.setdefault(tid, []).append(i)
    return {
        "processes": processes,
        "events": events,
        "traces": traces,
        "health": health_states or [],
        "diagnosis": _diagnose(processes, health_states or []),
    }


def _gcs_event_detail(e: dict) -> str:
    if e.get("type") == "health_transition":
        return (f"watchdog {e.get('kind')} {e.get('subject')} "
                f"{e.get('old')}->{e.get('new')}")
    parts = [str(e.get("state") or "")]
    if e.get("name"):
        parts.append(str(e["name"]))
    return " ".join(p for p in parts if p)


def _diagnose(processes: List[dict],
              health_states: List[dict]) -> List[str]:
    """Name the dead/stalled subjects — the sentence the operator came
    for. Ring pid-liveness and watchdog classification each contribute
    (the watchdog sees remote nodes this host can't probe)."""
    out: List[str] = []
    for p in processes:
        if not p["alive"] and not p["clean_exit"]:
            out.append(
                f"{p['process']} is DEAD (pid gone, no shutdown record; "
                f"last ring event {p['last_event_age_s']}s before this "
                "read)"
                if p["last_event_ts"] else
                f"{p['process']} is DEAD (pid gone; empty ring)")
    for s in health_states:
        if s.get("state") in ("stalled", "dead"):
            key = s.get("key") or []
            out.append(f"watchdog: {s.get('kind')} "
                       f"{':'.join(str(k) for k in key[1:])} "
                       f"is {s['state'].upper()}")
    return out


def events_for_trace(timeline: Dict[str, Any],
                     trace_id: str) -> List[dict]:
    """Every merged event cross-linked to one request's trace id."""
    return [timeline["events"][i]
            for i in timeline["traces"].get(trace_id, [])]


def format_timeline(timeline: Dict[str, Any], last_n: int = 25) -> str:
    """Human rendering: diagnosis first, then per-process status, the
    merged tail, and each dead process's final events."""
    import datetime as _dt

    def stamp(ts: float) -> str:
        return _dt.datetime.fromtimestamp(ts).strftime("%H:%M:%S.%f")[:-3]

    lines: List[str] = []
    if timeline["diagnosis"]:
        lines.append("== diagnosis ==")
        lines.extend(f"  {d}" for d in timeline["diagnosis"])
    else:
        lines.append("== diagnosis ==")
        lines.append("  all recorded processes alive; no watchdog alarms")
    lines.append("")
    lines.append("== processes ==")
    for p in timeline["processes"]:
        state = ("alive" if p["alive"]
                 else "exited" if p["clean_exit"] else "DEAD")
        last = (f"last event {p['last_event_age_s']}s ago"
                if p["last_event_ts"] else "no events")
        lines.append(f"  {p['process']:<24} {state:<6} "
                     f"{p['written']:>6} events  {last}")
    lines.append("")
    lines.append(f"== merged timeline (last {last_n}) ==")
    for e in timeline["events"][-last_n:]:
        lines.append(f"  {stamp(e['ts'])}  {e['process']:<22} "
                     f"[{e['category']}] {e['subject']} {e['detail']}")
    dead = [p for p in timeline["processes"]
            if not p["alive"] and not p["clean_exit"]]
    for p in dead:
        lines.append("")
        lines.append(f"== last events of {p['process']} (DEAD) ==")
        tail = [e for e in timeline["events"]
                if e["process"] == p["process"]][-last_n:]
        for e in tail:
            lines.append(f"  {stamp(e['ts'])}  [{e['category']}] "
                         f"{e['subject']} {e['detail']}")
    if timeline["traces"]:
        lines.append("")
        lines.append("== linked traces ==")
        for tid, idxs in sorted(timeline["traces"].items(),
                                key=lambda kv: -len(kv[1]))[:10]:
            procs = sorted({timeline['events'][i]['process']
                            for i in idxs})
            lines.append(f"  trace {tid}: {len(idxs)} events across "
                         f"{', '.join(procs)}")
    return "\n".join(lines)


# -- exposition parsing (shared with `ray-tpu status`) ------------------------

_SERIES_RE = re.compile(
    r"^(?P<name>[A-Za-z_:][A-Za-z0-9_:]*)"
    r"(?:\{(?P<tags>[^}]*)\})?\s+(?P<value>[^\s]+)$")
_TAG_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_prometheus(text: str) -> List[dict]:
    """``[{name, tags, value}]`` from an exposition body — enough of the
    format for our own output (which never emits escapes or exemplars)."""
    out: List[dict] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if not m:
            continue
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        tags = dict(_TAG_RE.findall(m.group("tags") or ""))
        out.append({"name": m.group("name"), "tags": tags, "value": value})
    return out


def select(series: List[dict], name: str, **tags: str) -> List[dict]:
    """Series of ``name`` whose tags contain ``tags`` as a subset."""
    return [s for s in series if s["name"] == name
            and all(s["tags"].get(k) == v for k, v in tags.items())]
