"""raylint — concurrency & contract static analysis for the ray_tpu tree.

The runtime got concurrent faster than anything checks it: coalescing frame
senders, fan-out batched gets, striped pulls, hierarchical collectives — all
of it hinges on lock discipline and string-resolved contracts (RPC methods
dispatched by ``getattr`` in ``core/rpc.py``, config knobs resolved by
``_Flag`` name). At pod scale one lock inversion or one silently-swallowed
daemon exception is a hung training step. This module is the correctness
floor: an AST pass over the whole tree, run as a tier-1 test.

Checks
======
``lock-order``
    Per-class nested-acquisition graph (interprocedural through ``self``
    method calls) with cycle detection: a cycle means two code paths take
    the same locks in opposite orders — a potential deadlock. Re-entering a
    plain (non-R) ``Lock`` while holding it is reported as a guaranteed
    self-deadlock.
``blocking-under-lock``
    Socket ``send*``/``recv*``/``accept``/``connect``, RPC ``.call(...)``,
    ``.wait(...)`` on a condition that does NOT wrap the held lock,
    ``time.sleep``, ``subprocess`` use, ``open(...)`` and ``Future.result``
    reached while a ``with <lock>`` frame is open. (Waiting on the held
    lock's own condition is fine — ``wait`` releases it.)
``untimed-wait``
    ``Condition.wait()`` / ``Event.wait()`` with no timeout and
    ``Future.result()`` with no timeout: a lost peer parks the thread
    forever.
``swallowed-exception``
    ``except Exception: pass`` (and bare/BaseException variants) — in a
    daemon or thread body this turns a real failure into a silent hang.
``rpc-surface``
    Every method name a client proxy dispatches as a string
    (``.call("name")`` / ``.call_async`` / ``.notify``) must resolve to a
    public method on an RPC service handler class (discovered from
    ``RpcServer(handler)`` instantiations, refined by a client→service
    table).
``config-knob``
    Every ``cfg.<name>`` / ``config().<name>`` access must resolve to a
    declared ``_Flag``; every declared ``_Flag`` must be referenced
    somewhere and carry a doc comment.
``rpc-cycle``
    Cross-process wait-cycle analysis. The rpc-surface pass already knows
    which service class each string-dispatched client call lands on; this
    check lifts those edges to the INTER-process call graph — nodes are
    ``Service.handler`` methods, an edge means "while serving this handler
    the process issues a blocking ``.call`` that the target service's
    handler serves" (interprocedural through ``self`` calls, like the
    lock-order pass). Flagged:

    - handler→handler cycles: A's handler blocks on an RPC whose serving
      handler can call back into A — when both sides serve synchronously
      this is a distributed deadlock (each process is parked in ``.call``
      waiting for the other's reply);
    - blocking RPC edges issued while holding a lock, when that edge
      participates in such a cycle OR the remote handler chain can RPC
      back into a method of the caller's class that needs the held lock
      (the per-class lock graph composed with the RPC edges).

    One-way ``notify`` / ``call_async`` dispatches don't park the caller
    and do not create wait edges.
``thread-leak``
    Every ``threading.Thread(...)`` must either be daemonized
    (``daemon=True`` at the ctor or ``t.daemon = True`` before start) or
    have a reachable ``join()``: for ``self._t``-stored threads a join in
    a method reachable from a shutdown-path entry point (``close`` /
    ``shutdown`` / ``stop`` / ``__exit__`` / ...); for function-local
    threads a join in the same function. A non-daemon thread with no
    reachable join outlives its owner and wedges interpreter exit.
``resource-leak``
    Every OS-resource acquire site stored on the owner — sockets, mmaps,
    ``os.open`` fds (including dict fd-caches), shm segments /
    ``NativeObjectStore`` handles — must have a release (``close`` /
    ``destroy`` / ``unlink`` / ``os.close``) reachable from a
    shutdown-path method, or be ``with``-managed. Function-local sockets/
    fds/mmaps that neither escape nor close in-function are flagged too.

``jit-churn`` / ``host-sync`` / ``key-reuse`` / ``donate-uaf``
    The JAX-aware tier — per-call ``jax.jit`` reconstruction,
    data-derived static arguments, implicit device→host syncs inside the
    declared hot scopes, PRNG key reuse, and reads of donated buffers.
    Implemented in ``ray_tpu.devtools.jaxlint`` (same AST cache, pragmas
    and baseline; runtime counterpart: ``ray_tpu.devtools.jitcheck``).

Baseline workflow
=================
Findings are fingerprinted WITHOUT line numbers
(``check|path|scope|detail[#k]``) so unrelated edits don't churn, and
diffed against ``lint_baseline.txt`` next to this module: only findings
absent from the baseline fail the run. Intentionally accepted findings are
recorded with ``--update-baseline``; fixed findings disappear from the
rewritten baseline automatically.

Usage::

    python -m ray_tpu.devtools.lint                 # whole tree vs baseline
    python -m ray_tpu.devtools.lint --check-baseline  # same, explicit (CI)
    python -m ray_tpu.devtools.lint --update-baseline
    python -m ray_tpu.devtools.lint --no-baseline path/  # raw findings
    python -m ray_tpu.devtools.lint --profile       # per-check wall time
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------

#: attribute names that read as "this is a lock / condition / semaphore"
#: even when we can't see the ``threading.X()`` construction (locks on other
#: objects: ``with st["lock"]``, ``with state.generator_cv`` ...).
_LOCKISH_NAMES = {"lock", "cv", "cond", "condition", "mutex", "mu", "sem",
                  "slots"}
_LOCKISH_SUFFIXES = ("_lock", "_cv", "_cond", "_mutex", "_sem", "_slots")

_SOCKET_METHODS = {"send", "sendall", "sendmsg", "recv", "recv_into",
                   "recvmsg", "accept", "connect", "connect_ex"}

#: dispatch methods whose first string argument is an RPC method name
_DISPATCH_METHODS = {"call", "call_async", "notify"}

#: method names RpcServer resolves outside getattr dispatch
_RPC_SPECIAL = {"register_spec_template", "on_client_opened",
                "on_client_closed"}

#: receiver-substring → service-class-name refinement for the rpc-surface
#: check.  Applied only when the named service class was actually discovered
#: in the scanned tree; otherwise the union of all services is used.
_CLIENT_TABLE: List[Tuple[str, str]] = [
    ("_gcs", "GcsService"),
    ("gcs_rpc", "GcsService"),
    ("_daemons", "NodeDaemon"),
    ("daemon", "NodeDaemon"),
    ("_owner", "_OwnerService"),
    ("owner", "_OwnerService"),
    ("_peers", "_MemberService"),
    ("peer", "_MemberService"),
    ("worker.client", "WorkerService"),
]

#: config attribute accesses that are API, not knobs
_CONFIG_NON_FLAGS = {"to_dict"}

#: method names that read as "this is a shutdown path" for the lifecycle
#: checks: joins/releases reachable from one of these count as reachable.
_SHUTDOWN_ENTRY_NAMES = {"close", "shutdown", "stop", "join", "destroy",
                         "disconnect", "teardown", "terminate", "kill",
                         "cleanup", "clear", "drain", "release", "reset",
                         "__exit__", "__del__", "__aexit__", "close_all",
                         "uninstall", "abort"}

#: method names that release the resource they're called on
_RELEASE_METHODS = {"close", "shutdown", "unlink", "destroy", "release",
                    "terminate", "stop", "detach", "munmap", "closerange",
                    "close_all"}


@dataclass
class Finding:
    check: str
    path: str  # scan-root-relative, '/'-separated
    line: int
    scope: str
    message: str
    detail: str  # stable fingerprint component (no line numbers)
    fingerprint: str = ""  # filled after dedup-counter assignment

    def render(self) -> str:
        return (f"{self.path}:{self.line}: [{self.check}] {self.scope}: "
                f"{self.message}")


@dataclass(frozen=True)
class _RpcSite:
    """One string-dispatched client call observed inside a method body."""
    recv: str  # receiver expression text (client lookup chain)
    method: str  # dispatched RPC method name
    kind: str  # 'call' | 'call_async' | 'notify'
    held: Optional[str]  # canonical lock token held at the site, if any
    line: int
    via: str  # self-call chain from the summarized method to the site


@dataclass
class _MethodSummary:
    """What one method does with locks, for the interprocedural pass."""
    acquires: Set[str] = field(default_factory=set)  # canonical lock tokens
    calls: Set[str] = field(default_factory=set)  # self.X() / module fn names
    # direct nested acquisitions observed: (held, acquired, line)
    nested: List[Tuple[str, str, int]] = field(default_factory=list)
    # self-calls made while holding a lock: (held, callee, line)
    held_calls: List[Tuple[str, str, int]] = field(default_factory=list)
    # string-dispatched RPC client calls made directly in this method
    rpc_calls: List[_RpcSite] = field(default_factory=list)


@dataclass
class _ThreadSite:
    """One ``threading.Thread(...)`` construction stored on the owner."""
    attr: str  # self attribute the thread is assigned to
    line: int
    scope: str
    daemon: bool  # daemon=True at the ctor


@dataclass
class _ResourceSite:
    """One OS-resource acquire assigned to an owner attribute."""
    attr: str
    line: int
    scope: str
    kind: str  # 'socket' | 'fd' | 'mmap' | 'shm' | 'file'
    is_dict: bool  # acquired into self.attr[key] (an fd/handle cache)


@dataclass
class _ClassInfo:
    name: str
    path: str
    line: int = 0
    locks: Dict[str, str] = field(default_factory=dict)  # attr -> kind
    cond_alias: Dict[str, str] = field(default_factory=dict)  # cond -> lock
    methods: Dict[str, _MethodSummary] = field(default_factory=dict)
    public_methods: Set[str] = field(default_factory=set)
    # lifecycle bookkeeping (thread-leak / resource-leak)
    thread_sites: List[_ThreadSite] = field(default_factory=list)
    resource_sites: List[_ResourceSite] = field(default_factory=list)
    # method -> thread attrs it joins / resource attrs it releases
    joins: Dict[str, Set[str]] = field(default_factory=dict)
    releases: Dict[str, Set[str]] = field(default_factory=dict)
    daemon_attrs: Set[str] = field(default_factory=set)  # self.X.daemon=True
    # coarse release evidence: methods containing ANY close-ish call, and
    # every self attr each method references (release of a dict fd-cache
    # rarely names `self._fds.close()` — it pops entries and os.close's
    # the values, so "mentions the attr + closes something" must count)
    release_methods: Set[str] = field(default_factory=set)
    method_refs: Dict[str, Set[str]] = field(default_factory=dict)


def _is_threading_ctor(node: ast.expr) -> Optional[str]:
    """'lock' | 'rlock' | 'cond' | 'event' | 'sem' if node constructs a
    threading primitive, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return {"Lock": "lock", "RLock": "rlock", "Condition": "cond",
            "Event": "event", "Semaphore": "sem",
            "BoundedSemaphore": "sem"}.get(name)


def _self_attr(node: ast.expr) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _expr_text(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — unparse is best-effort for messages
        return "<expr>"


def _lockish(node: ast.expr) -> bool:
    """Heuristic: does this expression look like a lock/cv/semaphore?"""
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Subscript):
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            name = sl.value
    if name is None:
        return False
    low = name.lower()
    return low in _LOCKISH_NAMES or low.endswith(_LOCKISH_SUFFIXES)


def _assign_targets(stmt: ast.stmt) -> List[Tuple[ast.expr, ast.expr]]:
    """(target, value) pairs for plain, annotated, and chained assignments
    — `self._t: Thread = Thread(...)` and `self.a = self.b = ctor()` must
    be visible to the lifecycle checks like any other acquire."""
    if isinstance(stmt, ast.Assign):
        return [(t, stmt.value) for t in stmt.targets]
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return [(stmt.target, stmt.value)]
    return []


def _is_thread_ctor(node: ast.expr) -> Optional[bool]:
    """Whether daemon=True was passed at a Thread construction. None when
    the node is not a Thread ctor — or when ``daemon=`` is a non-constant
    expression (statically unknown: skip rather than flag a thread that
    may well be daemonized at runtime)."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = fn.attr if isinstance(fn, ast.Attribute) else (
        fn.id if isinstance(fn, ast.Name) else None)
    if name != "Thread":
        return None
    for kw in node.keywords:
        if kw.arg == "daemon":
            if isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
            return None
    return False


def _resource_ctor(node: ast.expr) -> Optional[str]:
    """Resource kind ('socket'|'fd'|'mmap'|'shm'|'file') if the expression
    acquires an OS resource needing an explicit release, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
        recv, attr = fn.value.id, fn.attr
        if recv == "socket" and attr in ("socket", "create_connection",
                                         "create_server", "socketpair"):
            return "socket"
        if recv == "mmap" and attr == "mmap":
            return "mmap"
        if recv == "os" and attr in ("open", "fdopen", "dup",
                                     "memfd_create", "eventfd"):
            return "fd"
        if attr == "SharedMemory":
            return "shm"
        if recv == "NativeObjectStore" and attr == "open":
            return "shm"
    elif isinstance(fn, ast.Name):
        if fn.id == "SharedMemory":
            return "shm"
        if fn.id == "NativeObjectStore":
            return "shm"
        if fn.id == "open":
            return "file"
    return None


# ---------------------------------------------------------------------------
# per-function walker
# ---------------------------------------------------------------------------


class _FunctionWalker:
    """Walks one function body tracking the stack of held locks."""

    def __init__(self, linter: "Linter", path: str, cls: _ClassInfo,
                 scope: str, summary: _MethodSummary):
        self.linter = linter
        self.path = path
        self.cls = cls  # class (or module pseudo-class) we're inside
        self.scope = scope
        self.summary = summary
        self.held: List[str] = []  # canonical tokens, outermost first
        # local var -> canonical lock token (x = threading.Condition(self._y))
        self.local_alias: Dict[str, str] = {}

    # -- canonicalization ---------------------------------------------------

    def _canon(self, node: ast.expr) -> Optional[str]:
        """Canonical token for a lock expression, resolving condition
        aliases; None when the expression isn't a self/module/local lock."""
        attr = _self_attr(node)
        if attr is not None and attr in self.cls.locks:
            attr = self.cls.cond_alias.get(attr, attr)
            return f"{self.cls.name}.{attr}"
        if isinstance(node, ast.Name):
            if node.id in self.local_alias:
                return self.local_alias[node.id]
            if node.id in self.cls.locks and self.cls.name == "<module>":
                attr = self.cls.cond_alias.get(node.id, node.id)
                return f"<module>.{attr}"
        return None

    def _kind(self, token: str) -> str:
        attr = token.split(".", 1)[1]
        return self.cls.locks.get(attr, "lock")

    # -- statement walk -----------------------------------------------------

    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.With):
            self._with(stmt)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs execute later, under unknown locks
        if isinstance(stmt, ast.Assign):
            kind = _is_threading_ctor(stmt.value)
            if kind == "cond":
                args = stmt.value.args  # type: ignore[union-attr]
                wrapped = self._canon(args[0]) if args else None
                if wrapped is not None:
                    for tgt in stmt.targets:
                        if isinstance(tgt, ast.Name):
                            self.local_alias[tgt.id] = wrapped
        if isinstance(stmt, ast.Try):
            self.walk(stmt.body)
            for h in stmt.handlers:
                self._except(h)
                self.walk(h.body)
            self.walk(stmt.orelse)
            self.walk(stmt.finalbody)
            return
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.expr):
                self._expr_scan(node)
            elif isinstance(node, ast.stmt):
                self._stmt(node)
            elif isinstance(node, (ast.ExceptHandler,)):
                self._except(node)
                self.walk(node.body)

    def _with(self, stmt: ast.With) -> None:
        pushed = 0
        for item in stmt.items:
            ctx = item.context_expr
            self._expr_scan(ctx, is_with_ctx=True)
            token = self._canon(ctx)
            if token is not None:
                self._on_acquire(token, ctx.lineno)
                self.held.append(token)
                pushed += 1
            elif _lockish(ctx):
                # A lock on another object: counts as "a lock is held" for
                # blocking-under-lock, but takes no part in this class's
                # order graph.
                self.held.append(f"?{_expr_text(ctx)}")
                pushed += 1
        self.walk(stmt.body)
        for _ in range(pushed):
            self.held.pop()

    def _except(self, handler: ast.ExceptHandler) -> None:
        is_broad = handler.type is None or (
            isinstance(handler.type, ast.Name)
            and handler.type.id in ("Exception", "BaseException"))
        body_is_pass = all(isinstance(s, ast.Pass) for s in handler.body)
        if is_broad and body_is_pass:
            what = (handler.type.id if isinstance(handler.type, ast.Name)
                    else "bare except")
            self.linter.add(Finding(
                "swallowed-exception", self.path, handler.lineno, self.scope,
                f"`except {what}: pass` swallows failures silently — use "
                "log_swallowed(logger, context) or narrow the except",
                "except-pass"))

    # -- acquisition & call handling ----------------------------------------

    def _on_acquire(self, token: str, line: int) -> None:
        self.summary.acquires.add(token)
        if self.held:
            top = self.held[-1]
            if not top.startswith("?"):
                self.summary.nested.append((top, token, line))
                if token == top and self._kind(token) == "lock":
                    self.linter.add(Finding(
                        "lock-order", self.path, line, self.scope,
                        f"re-acquiring non-reentrant {token} while already "
                        "held: guaranteed self-deadlock",
                        f"self-deadlock:{token}"))

    def _expr_scan(self, node: ast.expr, is_with_ctx: bool = False) -> None:
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            self._call(call)

    def _call(self, call: ast.Call) -> None:
        fn = call.func
        fn_name = None
        recv = None
        if isinstance(fn, ast.Attribute):
            fn_name = fn.attr
            recv = fn.value
        elif isinstance(fn, ast.Name):
            fn_name = fn.id

        # explicit .acquire() counts as an acquisition for the graph
        if fn_name == "acquire" and recv is not None:
            token = self._canon(recv)
            if token is not None:
                self._on_acquire(token, call.lineno)

        # interprocedural bookkeeping: self.m(...) / module fn(...)
        callee = None
        if recv is not None and isinstance(recv, ast.Name) and \
                recv.id == "self":
            callee = fn_name
        elif isinstance(fn, ast.Name) and self.cls.name == "<module>":
            callee = fn_name
        if callee is not None and callee in self.cls.methods:
            self.summary.calls.add(callee)
            if self.held and not self.held[-1].startswith("?"):
                self.summary.held_calls.append(
                    (self.held[-1], callee, call.lineno))

        # RPC dispatch surface (+ wait-cycle edge bookkeeping)
        if fn_name in _DISPATCH_METHODS and recv is not None and call.args:
            arg0 = call.args[0]
            if isinstance(arg0, ast.Constant) and isinstance(arg0.value, str):
                self.linter.rpc_sites.append(
                    (self.path, call.lineno, self.scope,
                     _expr_text(recv), arg0.value))
                held = next((h for h in reversed(self.held)
                             if not h.startswith("?")), None)
                self.summary.rpc_calls.append(_RpcSite(
                    _expr_text(recv), arg0.value, fn_name, held,
                    call.lineno, self.scope))

        # untimed waits (held or not)
        self._untimed(call, fn_name, recv)

        # blocking calls under a held lock
        if self.held:
            self._blocking(call, fn_name, recv)

    def _untimed(self, call: ast.Call, fn_name, recv) -> None:
        if recv is None or fn_name not in ("wait", "result"):
            return
        if call.args or call.keywords:
            return
        if fn_name == "wait":
            self.linter.add(Finding(
                "untimed-wait", self.path, call.lineno, self.scope,
                f"`{_expr_text(recv)}.wait()` has no timeout — a lost peer "
                "parks this thread forever (use internal_wait_timeout_s / "
                "collective_timeout_s)",
                f"wait:{_expr_text(recv)}"))
        elif fn_name == "result":
            self.linter.add(Finding(
                "untimed-wait", self.path, call.lineno, self.scope,
                f"`{_expr_text(recv)}.result()` has no timeout — a lost "
                "peer parks this thread forever",
                f"result:{_expr_text(recv)}"))

    def _blocking(self, call: ast.Call, fn_name, recv) -> None:
        held_txt = self.held[-1].lstrip("?")

        def flag(kind: str, msg: str) -> None:
            self.linter.add(Finding(
                "blocking-under-lock", self.path, call.lineno, self.scope,
                f"{msg} while holding {held_txt}",
                f"{kind}:{_expr_text(call.func)}"))

        if fn_name == "sleep":
            # `time.sleep`, `_time.sleep` (import alias), bare `sleep`
            is_time_sleep = recv is None or (
                isinstance(recv, ast.Name) and "time" in recv.id.lower())
            if is_time_sleep:
                flag("sleep", "time.sleep()")
            return
        if fn_name in _SOCKET_METHODS and recv is not None:
            flag("socket", f"socket `{fn_name}`")
            return
        if fn_name == "call" and recv is not None:
            flag("rpc", "blocking RPC `.call(...)`")
            return
        if fn_name == "result" and recv is not None:
            flag("future", "`Future.result(...)`")
            return
        if fn_name == "wait" and recv is not None:
            token = self._canon(recv)
            held_real = [h for h in self.held if not h.startswith("?")]
            if token is not None and token in held_real:
                return  # waiting on the held lock's own condition: releases
            if token is None and _expr_text(recv) in (
                    h.lstrip("?") for h in self.held):
                return  # `with st["lock"]: ... st["lock"].wait()` style
            flag("wait", f"`{_expr_text(recv)}.wait(...)` on a condition "
                         "that does not wrap the held lock")
            return
        if isinstance(recv, ast.Name) and recv.id == "subprocess":
            flag("subprocess", f"subprocess.{fn_name}()")
            return
        if fn_name == "Popen":
            flag("subprocess", "subprocess.Popen()")
            return
        if fn_name == "open" and recv is None:
            flag("file-io", "file `open(...)`")
            return


# ---------------------------------------------------------------------------
# linter driver
# ---------------------------------------------------------------------------


#: (abspath) -> (stat key, parsed tree, source) — shared across Linter
#: instances (each check family used to re-read and re-parse the tree; the
#: tests alone construct dozens of Linters over the same files)
_AST_CACHE: Dict[str, Tuple[Tuple[int, int], ast.Module, str]] = {}


def _parse_cached(path: str) -> Tuple[ast.Module, str]:
    st = os.stat(path)
    key = (st.st_mtime_ns, st.st_size)
    hit = _AST_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1], hit[2]
    with open(path, "r", encoding="utf-8") as fh:
        src = fh.read()
    tree = ast.parse(src, filename=path)
    _AST_CACHE[path] = (key, tree, src)
    return tree, src


class Linter:
    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.timings: Dict[str, float] = {}
        self.findings: List[Finding] = []
        # (path, line, scope, receiver_text, method_name)
        self.rpc_sites: List[Tuple[str, int, str, str, str]] = []
        self.services: Dict[str, _ClassInfo] = {}  # class name -> info
        self.classes: List[_ClassInfo] = []
        # config flags: name -> (line, documented)
        self.flags: Dict[str, Tuple[int, bool]] = {}
        self.flag_path: str = ""
        # (path, line, scope, attr) accesses on config objects
        self.cfg_accesses: List[Tuple[str, int, str, str]] = []
        # path -> source lines, for pragma suppression
        self.src_lines: Dict[str, List[str]] = {}

    def add(self, f: Finding) -> None:
        if self._suppressed(f):
            return
        self.findings.append(f)

    def _suppressed(self, f: Finding) -> bool:
        """`# raylint: ignore` / `# raylint: ignore[check-a,check-b]` on the
        finding's line or an immediately preceding comment line suppresses
        it — for reviewed FALSE POSITIVES; accepted real findings belong in
        the baseline instead."""
        lines = self.src_lines.get(f.path)
        if not lines or not (1 <= f.line <= len(lines)):
            return False
        i = f.line - 1
        candidates = [lines[i]]
        while i > 0 and lines[i - 1].lstrip().startswith("#"):
            i -= 1
            candidates.append(lines[i])
        for text in candidates:
            idx = text.find("raylint: ignore")
            if idx < 0:
                continue
            rest = text[idx + len("raylint: ignore"):]
            if not rest.startswith("["):
                return True  # blanket ignore
            checks = rest[1:rest.find("]")] if "]" in rest else ""
            if f.check in {c.strip() for c in checks.split(",")}:
                return True
        return False

    # -- scan ---------------------------------------------------------------

    def _timed(self, phase: str, fn) -> None:
        t0 = time.perf_counter()
        fn()
        self.timings[phase] = self.timings.get(phase, 0.0) \
            + time.perf_counter() - t0

    def run(self) -> List[Finding]:
        t0 = time.perf_counter()
        files = self._collect_files()
        parsed: List[Tuple[str, ast.Module, str]] = []
        for path in files:
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                tree, src = _parse_cached(path)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                self.add(Finding("parse-error", rel, getattr(e, "lineno", 0)
                                 or 0, "<file>", f"cannot parse: {e}",
                                 "parse-error"))
                continue
            parsed.append((rel, tree, src))
            self.src_lines[rel] = src.splitlines()
        self.timings["parse"] = time.perf_counter() - t0

        def scan():
            for rel, tree, src in parsed:
                self._scan_config_decls(rel, tree, src)
            for rel, tree, _src in parsed:
                self._scan_module(rel, tree)

        # The per-file scan feeds every check from the cached ASTs in two
        # traversals per function (the lock walker + one lifecycle
        # bucketing walk); inline checks — blocking-under-lock,
        # untimed-wait, swallowed-exception, local lifecycle leaks — fire
        # during it, the graph checks below reuse its summaries.
        self._timed("scan", scan)
        self._timed("lock-order", self._check_lock_order)
        self._timed("rpc-surface", self._check_rpc_surface)
        self._timed("rpc-cycle", self._check_rpc_cycle)
        self._timed("thread-leak", self._check_thread_leaks)
        self._timed("resource-leak", self._check_resource_leaks)
        self._timed("config-knob", self._check_config_knobs)
        # The JAX-aware checks live in devtools.jaxlint (imported lazily:
        # jaxlint imports Finding from this module at its top level) and
        # ride the same AST cache, pragmas and baseline.
        from ray_tpu.devtools import jaxlint
        self._timed("jit-churn",
                    lambda: jaxlint.check_jit_churn(self, parsed))
        self._timed("host-sync",
                    lambda: jaxlint.check_host_sync(self, parsed))
        self._timed("key-reuse",
                    lambda: jaxlint.check_key_reuse(self, parsed))
        self._timed("donate-uaf",
                    lambda: jaxlint.check_donate_uaf(self, parsed))
        self._assign_fingerprints()
        self.findings.sort(key=lambda f: (f.path, f.line, f.check, f.detail))
        self.timings["total"] = time.perf_counter() - t0
        return self.findings

    def _collect_files(self) -> List[str]:
        if os.path.isfile(self.root):
            path = self.root
            self.root = os.path.abspath(os.path.dirname(path) or ".")
            return [path]
        out = []
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "_native", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
        return out

    # -- config declarations ------------------------------------------------

    def _scan_config_decls(self, rel: str, tree: ast.Module, src: str) -> None:
        """Find the _Flag registry: a class named Config whose body assigns
        ``name = _Flag(...)``. ``documented`` = a comment line directly
        above the assignment."""
        lines = src.splitlines()
        for node in tree.body:
            if not isinstance(node, ast.ClassDef) or node.name != "Config":
                continue
            found = {}
            for stmt in node.body:
                if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and isinstance(stmt.value, ast.Call)
                        and isinstance(stmt.value.func, ast.Name)
                        and stmt.value.func.id == "_Flag"):
                    name = stmt.targets[0].id
                    prev = lines[stmt.lineno - 2].strip() \
                        if stmt.lineno >= 2 else ""
                    documented = prev.startswith("#") or prev.startswith("...")
                    found[name] = (stmt.lineno, documented)
            if found:
                self.flags = found
                self.flag_path = rel

    # -- per-module scan ----------------------------------------------------

    def _scan_module(self, rel: str, tree: ast.Module) -> None:
        # module pseudo-class: top-level functions + module-level locks
        mod = _ClassInfo(name="<module>", path=rel)
        for node in tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _is_threading_ctor(node.value)
                if kind:
                    self._register_lock(mod, node.targets[0].id, kind,
                                        node.value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod.methods.setdefault(node.name, _MethodSummary())

        classes = [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]
        for cls_node in classes:
            info = _ClassInfo(name=cls_node.name, path=rel,
                              line=cls_node.lineno)
            for item in cls_node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods.setdefault(item.name, _MethodSummary())
                    if not item.name.startswith("_"):
                        info.public_methods.add(item.name)
            # lock attributes: any `self.X = threading.Lock()` in any method
            for sub in ast.walk(cls_node):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    attr = _self_attr(sub.targets[0])
                    kind = _is_threading_ctor(sub.value)
                    if attr is not None and kind:
                        self._register_lock(info, attr, kind, sub.value)
            self.classes.append(info)
            # walk method bodies
            for item in cls_node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    scope = f"{cls_node.name}.{item.name}"
                    walker = _FunctionWalker(self, rel, info, scope,
                                             info.methods[item.name])
                    walker.walk(item.body)
                    self._scan_fn_lifecycle(rel, info, item.name, scope, item)
            # service discovery: RpcServer(self, ...) inside the class
            for sub in ast.walk(cls_node):
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "RpcServer" and sub.args
                        and isinstance(sub.args[0], ast.Name)
                        and sub.args[0].id == "self"):
                    self.services[cls_node.name] = info

        # module-level function bodies (pseudo-class walk)
        self.classes.append(mod)
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = node.name
                walker = _FunctionWalker(self, rel, mod, scope,
                                         mod.methods[node.name])
                walker.walk(node.body)
                self._scan_fn_lifecycle(rel, mod, node.name, scope, node)

        # service discovery: RpcServer(<Name or Call>, ...) anywhere
        by_name = {c.name: c for c in self.classes if c.path == rel}
        assigned: Dict[str, str] = {}  # var -> class name (x = Cls(...))
        for sub in ast.walk(tree):
            if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)
                    and isinstance(sub.value, ast.Call)
                    and isinstance(sub.value.func, ast.Name)):
                assigned[sub.targets[0].id] = sub.value.func.id
        for sub in ast.walk(tree):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "RpcServer" and sub.args):
                continue
            arg0 = sub.args[0]
            cls_name = None
            if isinstance(arg0, ast.Call) and isinstance(arg0.func, ast.Name):
                cls_name = arg0.func.id
            elif isinstance(arg0, ast.Name):
                cls_name = assigned.get(arg0.id, arg0.id)
            if cls_name and cls_name in by_name:
                self.services[cls_name] = by_name[cls_name]

        # config accesses in this module
        self._scan_config_accesses(rel, tree)

    def _register_lock(self, info: _ClassInfo, attr: str, kind: str,
                       ctor: ast.expr) -> None:
        info.locks[attr] = kind
        if kind == "cond" and isinstance(ctor, ast.Call) and ctor.args:
            wrapped = _self_attr(ctor.args[0])
            if wrapped is None and isinstance(ctor.args[0], ast.Name) and \
                    info.name == "<module>":
                wrapped = ctor.args[0].id
            if wrapped is not None:
                info.cond_alias[attr] = wrapped
                # the condition's kind follows the wrapped lock where known
                if wrapped in info.locks:
                    info.locks[attr] = info.locks[wrapped]

    # -- lifecycle scan (thread-leak / resource-leak raw material) -----------

    def _scan_fn_lifecycle(self, rel: str, info: _ClassInfo, name: str,
                           scope: str, fn: ast.AST) -> None:
        """Collect thread/resource acquire, join, daemonize and release
        evidence from one function body (class method or module function),
        and flag function-LOCAL leaks immediately."""
        local_threads: Dict[str, Dict] = {}  # var -> {daemon, joined, line}
        local_res: Dict[str, Dict] = {}  # var -> {kind, line, closed}
        escaped: Set[str] = set()
        refs: Set[str] = set()

        # ONE traversal buckets everything the passes below need: with-
        # managed context ids, (target, value) assignment pairs, calls,
        # self-attr reads, and returned/yielded expressions.
        with_ctxs: Set[int] = set()
        assigns: List[Tuple[ast.expr, ast.expr, int]] = []
        calls: List[ast.Call] = []
        escape_exprs: List[ast.expr] = []
        for sub in ast.walk(fn):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    with_ctxs.add(id(item.context_expr))
            elif isinstance(sub, ast.Call):
                calls.append(sub)
            elif isinstance(sub, ast.Attribute):
                a = _self_attr(sub)
                if a is not None:
                    refs.add(a)
            elif isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                if sub.value is not None:
                    escape_exprs.append(sub.value)
            for tgt, val in _assign_targets(sub):
                assigns.append((tgt, val, sub.lineno))

        # pass 1: direct constructions
        for tgt, val, lineno in assigns:
            if id(val) in with_ctxs:
                continue
            daemon = _is_thread_ctor(val)
            kind = _resource_ctor(val) if daemon is None else None
            if daemon is None and kind is None:
                continue
            attr = _self_attr(tgt)
            sub_attr = (_self_attr(tgt.value)
                        if isinstance(tgt, ast.Subscript) else None)
            if daemon is not None:
                if attr is not None:
                    info.thread_sites.append(
                        _ThreadSite(attr, lineno, scope, daemon))
                elif isinstance(tgt, ast.Name):
                    local_threads[tgt.id] = {"daemon": daemon, "joined": False,
                                             "line": lineno}
            else:
                if attr is not None:
                    info.resource_sites.append(_ResourceSite(
                        attr, lineno, scope, kind, is_dict=False))
                elif sub_attr is not None:
                    info.resource_sites.append(_ResourceSite(
                        sub_attr, lineno, scope, kind, is_dict=True))
                elif isinstance(tgt, ast.Name) and kind != "file":
                    # plain local `open()` file handles are everywhere and
                    # usually short-lived; flag only kernel-object locals
                    local_res[tgt.id] = {"kind": kind, "line": lineno,
                                         "closed": False}
        # pass 2a: stores of tracked locals onto self + daemonization
        for tgt, val, _lineno in assigns:
            # self.X = t / self.X[k] = fd promotes a local to an attr site
            if isinstance(val, ast.Name):
                attr = _self_attr(tgt)
                sub_attr = (_self_attr(tgt.value)
                            if isinstance(tgt, ast.Subscript) else None)
                if val.id in local_threads and attr is not None:
                    t = local_threads.pop(val.id)
                    info.thread_sites.append(
                        _ThreadSite(attr, t["line"], scope, t["daemon"]))
                elif val.id in local_res and (attr is not None
                                              or sub_attr is not None):
                    r = local_res.pop(val.id)
                    info.resource_sites.append(_ResourceSite(
                        attr or sub_attr, r["line"], scope, r["kind"],
                        is_dict=attr is None))
                elif val.id in local_threads or val.id in local_res:
                    escaped.add(val.id)  # aliased somewhere we can't see
            # t.daemon = True / self.X.daemon = True
            if (isinstance(tgt, ast.Attribute) and tgt.attr == "daemon"
                    and isinstance(val, ast.Constant) and val.value):
                inner = tgt.value
                a = _self_attr(inner)
                if a is not None:
                    info.daemon_attrs.add(a)
                elif isinstance(inner, ast.Name) and \
                        inner.id in local_threads:
                    local_threads[inner.id]["daemon"] = True
        # pass 2b: joins, releases, escapes through calls
        for call in calls:
            f = call.func
            if isinstance(f, ast.Attribute):
                recv = f.value
                recv_attr = _self_attr(recv)
                recv_name = recv.id if isinstance(recv, ast.Name) else None
                # `Thread(...).start()` never bound anywhere: joinable by
                # nobody — must be a daemon
                if f.attr == "start" and _is_thread_ctor(recv) is False:
                    self.add(Finding(
                        "thread-leak", rel, call.lineno, scope,
                        "anonymous non-daemon `Thread(...).start()` — no "
                        "reference survives to join it; pass daemon=True "
                        "or keep a handle and join on shutdown",
                        "anonymous-thread"))
                # os.close/os.closerange BEFORE the generic release branch
                # ("close" is in _RELEASE_METHODS): the released object is
                # the ARGUMENT here, not the receiver
                if recv_name == "os" and f.attr in ("close", "closerange"):
                    info.release_methods.add(name)
                    for arg in call.args:
                        for deep in ast.walk(arg):
                            da = _self_attr(deep)
                            if da is not None:
                                info.releases.setdefault(name,
                                                         set()).add(da)
                            if isinstance(deep, ast.Name) and \
                                    deep.id in local_res:
                                local_res[deep.id]["closed"] = True
                    continue  # os.close(v) is a release, not an escape
                if f.attr == "join":
                    if recv_attr is not None:
                        info.joins.setdefault(name, set()).add(recv_attr)
                    elif recv_name in local_threads:
                        local_threads[recv_name]["joined"] = True
                elif f.attr in _RELEASE_METHODS:
                    info.release_methods.add(name)
                    # precise: the release call's receiver names self.X
                    for deep in ast.walk(f.value):
                        da = _self_attr(deep)
                        if da is not None:
                            info.releases.setdefault(name, set()).add(da)
                    if recv_name in local_res:
                        local_res[recv_name]["closed"] = True
            # a tracked local passed as an ARGUMENT may be retained by the
            # callee — ownership is unclear, don't flag
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                for deep in ast.walk(arg):
                    if isinstance(deep, ast.Name) and (
                            deep.id in local_threads or deep.id in local_res):
                        escaped.add(deep.id)
        for expr in escape_exprs:
            for deep in ast.walk(expr):
                if isinstance(deep, ast.Name):
                    escaped.add(deep.id)
        info.method_refs[name] = refs

        for var, t in local_threads.items():
            if var in escaped or t["daemon"] or t["joined"]:
                continue
            self.add(Finding(
                "thread-leak", rel, t["line"], scope,
                f"local thread `{var}` is neither daemonized nor joined in "
                "this function — it outlives its owner and wedges "
                "interpreter exit",
                f"local:{var}"))
        for var, r in local_res.items():
            if var in escaped or r["closed"]:
                continue
            self.add(Finding(
                "resource-leak", rel, r["line"], scope,
                f"local {r['kind']} `{var}` is never closed in this "
                "function and does not escape — leaked on every call",
                f"local:{r['kind']}:{var}"))

    # -- lock-order graph ----------------------------------------------------

    def _lock_closure(self, info: _ClassInfo) -> Dict[str, Set[str]]:
        """Interprocedural (through ``self`` calls) closure of the lock
        tokens each method's call tree can acquire."""
        closure: Dict[str, Set[str]] = {
            m: set(s.acquires) for m, s in info.methods.items()}
        changed = True
        while changed:
            changed = False
            for m, s in info.methods.items():
                for callee in s.calls:
                    extra = closure.get(callee, set()) - closure[m]
                    if extra:
                        closure[m] |= extra
                        changed = True
        return closure

    def _check_lock_order(self) -> None:
        for info in self.classes:
            edges: Dict[str, Set[str]] = {}
            edge_site: Dict[Tuple[str, str], Tuple[int, str]] = {}
            # interprocedural closure: all locks a method's call tree takes
            closure = self._lock_closure(info)
            for m, s in info.methods.items():
                for held, acquired, line in s.nested:
                    if held != acquired:
                        edges.setdefault(held, set()).add(acquired)
                        edge_site.setdefault((held, acquired),
                                             (line, f"{info.name}.{m}"))
                for held, callee, line in s.held_calls:
                    for acquired in closure.get(callee, ()):  # transitive
                        if acquired != held:
                            edges.setdefault(held, set()).add(acquired)
                            edge_site.setdefault(
                                (held, acquired),
                                (line, f"{info.name}.{m}→{callee}"))
            # cycle detection (DFS)
            for cycle in _find_cycles(edges):
                line, scope = edge_site.get((cycle[0], cycle[1]), (info.line,
                                                                   info.name))
                pretty = " -> ".join(cycle + [cycle[0]])
                self.add(Finding(
                    "lock-order", info.path, line, scope,
                    f"lock-order cycle (potential deadlock): {pretty}",
                    "cycle:" + "->".join(sorted(set(cycle)))))

    # -- rpc surface ---------------------------------------------------------

    def _check_rpc_surface(self) -> None:
        if not self.services:
            return
        union: Set[str] = set(_RPC_SPECIAL)
        for svc in self.services.values():
            union |= svc.public_methods
        for path, line, scope, recv, method in self.rpc_sites:
            svc_name = None
            for pattern, candidate in _CLIENT_TABLE:
                if pattern in recv and candidate in self.services:
                    svc_name = candidate
                    break
            if svc_name is not None:
                surface = (self.services[svc_name].public_methods
                           | _RPC_SPECIAL)
                where = f"service {svc_name}"
            else:
                surface = union
                where = "any known RPC service"
            if method.startswith("_"):
                self.add(Finding(
                    "rpc-surface", path, line, scope,
                    f"dispatching private method '{method}' — RpcServer "
                    "refuses names starting with '_'",
                    f"private:{method}"))
            elif method not in surface:
                self.add(Finding(
                    "rpc-surface", path, line, scope,
                    f"'{method}' (via `{recv}`) does not resolve to a "
                    f"public method on {where}",
                    f"unknown:{method}"))

    # -- cross-process wait cycles -------------------------------------------

    def _resolve_service(self, recv: str) -> Optional[str]:
        for pattern, candidate in _CLIENT_TABLE:
            if pattern in recv and candidate in self.services:
                return candidate
        return None

    def _service_rpc_closure(self, info: _ClassInfo) \
            -> Dict[str, List[_RpcSite]]:
        """Per-method set of RPC dispatch sites reachable through ``self``
        calls, propagating the held-lock context: a site reached via a
        call made under lock L inherits L when the site itself recorded
        no held lock."""
        closure: Dict[str, Dict[Tuple, _RpcSite]] = {
            m: {(r.recv, r.method, r.kind, r.held): r
                for r in s.rpc_calls}
            for m, s in info.methods.items()}
        changed = True
        while changed:
            changed = False
            for m, s in info.methods.items():
                held_by_callee: Dict[str, str] = {}
                for held, callee, _line in s.held_calls:
                    held_by_callee.setdefault(callee, held)
                for callee in s.calls:
                    for site in list(closure.get(callee, {}).values()):
                        held = site.held or held_by_callee.get(callee)
                        key = (site.recv, site.method, site.kind, held)
                        if key not in closure[m]:
                            closure[m][key] = _RpcSite(
                                site.recv, site.method, site.kind, held,
                                site.line, f"{info.name}.{m} → {site.via}")
                            changed = True
        return {m: list(d.values()) for m, d in closure.items()}

    def _check_rpc_cycle(self) -> None:
        if not self.services:
            return
        # node = "Service.handler"; edge = blocking .call issued while
        # serving the source handler, landing on the target handler. One
        # representative site per (src, dst, held) — a second call site on
        # the same edge under a DIFFERENT lock is a distinct deadlock
        # candidate and must not be collapsed away.
        edges: Dict[str, Set[str]] = {}
        edge_sites: Dict[Tuple[str, str],
                         Dict[Optional[str], Tuple[str, _RpcSite]]] = {}
        lock_closures: Dict[str, Dict[str, Set[str]]] = {}
        for svc, info in self.services.items():
            lock_closures[svc] = self._lock_closure(info)
            sites = self._service_rpc_closure(info)
            for m in sorted(info.public_methods):
                for site in sites.get(m, ()):
                    if site.kind != "call":
                        continue  # notify/call_async don't park the caller
                    target = self._resolve_service(site.recv)
                    if target is None:
                        continue
                    if site.method not in \
                            self.services[target].public_methods:
                        continue
                    src, dst = f"{svc}.{m}", f"{target}.{site.method}"
                    edges.setdefault(src, set()).add(dst)
                    edge_sites.setdefault((src, dst), {}).setdefault(
                        site.held, (svc, site))

        in_cycle_edges: Set[Tuple[str, str]] = set()
        for cycle in _find_cycles(edges):
            pairs = list(zip(cycle, cycle[1:] + [cycle[0]]))
            in_cycle_edges.update(pairs)
            svc, site = next(iter(edge_sites[pairs[0]].values()))
            pretty = " -> ".join(cycle + [cycle[0]])
            self.add(Finding(
                "rpc-cycle", self.services[svc].path, site.line, cycle[0],
                f"cross-process RPC wait cycle: {pretty} — each handler "
                "blocks in .call until the next replies; when the chain "
                "lands back on the origin process both sides park forever "
                "(make one hop a notify/call_async, or move the work off "
                "the handler)",
                "cycle:" + "->".join(sorted(set(cycle)))))
        # lock-held blocking edges: flagged when the edge participates in a
        # handler cycle, or when the remote handler chain can RPC back into
        # a method of the CALLER's class that needs the held lock (the
        # per-class lock graph composed with the RPC edges)
        for (src, dst), by_held in sorted(edge_sites.items()):
            for held, (svc, site) in sorted(
                    by_held.items(), key=lambda kv: kv[0] or ""):
                if held is None:
                    continue
                path = self.services[svc].path
                if (src, dst) in in_cycle_edges:
                    self.add(Finding(
                        "rpc-cycle", path, site.line, src,
                        f"blocking RPC to {dst} issued while holding "
                        f"{held} participates in a handler wait cycle — "
                        "the reply this thread is parked on can itself "
                        f"need {held}",
                        f"lock-held:{held}->{dst}"))
                    continue
                for node in self._reachable(edges, dst):
                    tsvc, tm = node.split(".", 1)
                    if tsvc == svc and held in \
                            lock_closures[svc].get(tm, ()):
                        self.add(Finding(
                            "rpc-cycle", path, site.line, src,
                            f"blocking RPC to {dst} issued while holding "
                            f"{held}; the serving side can call back "
                            f"into {node}, which acquires {held} — "
                            "distributed deadlock when both block",
                            f"lock-rpc:{held}:{dst}=>{node}"))
                        break

    @staticmethod
    def _reachable(edges: Dict[str, Set[str]], start: str) -> Set[str]:
        out, stack = {start}, [start]
        while stack:
            for nxt in edges.get(stack.pop(), ()):
                if nxt not in out:
                    out.add(nxt)
                    stack.append(nxt)
        return out

    # -- thread / resource lifecycle -----------------------------------------

    def _shutdown_reachable(self, info: _ClassInfo) -> Set[str]:
        """Methods reachable (via ``self`` calls) from a shutdown-path
        entry point."""
        reach = {m for m in info.methods
                 if m in _SHUTDOWN_ENTRY_NAMES
                 or any(k in m for k in ("shutdown", "close", "stop",
                                         "teardown", "cleanup", "clear",
                                         "destroy"))}
        changed = True
        while changed:
            changed = False
            for m in list(reach):
                for callee in info.methods[m].calls:
                    if callee in info.methods and callee not in reach:
                        reach.add(callee)
                        changed = True
        return reach

    def _check_thread_leaks(self) -> None:
        for info in self.classes:
            if not info.thread_sites:
                continue
            reach = self._shutdown_reachable(info)
            joined_reachable: Set[str] = set()
            joined_anywhere: Set[str] = set()
            for m, attrs in info.joins.items():
                joined_anywhere |= attrs
                if m in reach:
                    joined_reachable |= attrs
            for site in info.thread_sites:
                if site.daemon or site.attr in info.daemon_attrs:
                    continue
                if site.attr in joined_reachable:
                    continue
                if site.attr in joined_anywhere:
                    msg = (f"non-daemon thread `self.{site.attr}` is "
                           "joined, but not from any shutdown-path method "
                           f"({'/'.join(sorted(reach)) or 'none found'}) — "
                           "a shutdown that skips that path leaks it")
                else:
                    msg = (f"non-daemon thread `self.{site.attr}` has no "
                           "reachable join() — pass daemon=True or join "
                           "it from close()/shutdown()")
                self.add(Finding("thread-leak", info.path, site.line,
                                 site.scope, msg, f"unjoined:{site.attr}"))

    def _check_resource_leaks(self) -> None:
        for info in self.classes:
            if not info.resource_sites or info.name == "<module>":
                continue
            reach = self._shutdown_reachable(info)
            seen: Set[str] = set()
            for site in info.resource_sites:
                if site.attr in seen:
                    continue  # one finding per attr, not per acquire site
                seen.add(site.attr)
                released = False
                for m in reach:
                    if site.attr in info.releases.get(m, ()):
                        released = True  # precise: self.X.close() et al.
                        break
                    if m in info.release_methods and \
                            site.attr in info.method_refs.get(m, ()):
                        released = True  # coarse: fd-cache drain loops
                        break
                if released:
                    continue
                what = (f"{site.kind} cache `self.{site.attr}`" if
                        site.is_dict else
                        f"{site.kind} `self.{site.attr}`")
                self.add(Finding(
                    "resource-leak", info.path, site.line, site.scope,
                    f"{what} has no release reachable from a shutdown-path "
                    "method (close/shutdown/stop/__exit__ ...) — leaked "
                    "on owner teardown",
                    f"unreleased:{site.kind}:{site.attr}"))

    # -- config knobs --------------------------------------------------------

    def _scan_config_accesses(self, rel: str, tree: ast.Module) -> None:
        # names assigned from config() calls — and names assigned from
        # anything else (a conflicted name is skipped entirely)
        cfg_names: Set[str] = set()
        other_names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                v = node.value
                if (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                        and v.func.id in ("config", "Config",
                                          "_get_config")):
                    cfg_names.add(name)
                else:
                    other_names.add(name)
        cfg_names -= other_names
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            v = node.value
            is_cfg = (isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                      and v.func.id == "config")
            is_cfg = is_cfg or (isinstance(v, ast.Name) and v.id in cfg_names)
            if is_cfg:
                self.cfg_accesses.append(
                    (rel, node.lineno, "<module>", node.attr))

    def _check_config_knobs(self) -> None:
        if not self.flags:
            return
        used: Set[str] = set()
        for path, line, scope, attr in self.cfg_accesses:
            if path == self.flag_path:
                continue  # the registry's own reflection
            if attr in self.flags:
                used.add(attr)
                continue
            if attr in _CONFIG_NON_FLAGS or attr.startswith("_"):
                continue
            self.add(Finding(
                "config-knob", path, line, scope,
                f"`cfg.{attr}` does not resolve to any declared _Flag "
                f"(see {self.flag_path})",
                f"unknown:{attr}"))
        for name, (line, documented) in sorted(self.flags.items()):
            if name not in used:
                self.add(Finding(
                    "config-knob", self.flag_path, line, "Config",
                    f"_Flag '{name}' is declared but never referenced",
                    f"unused:{name}"))
            if not documented:
                self.add(Finding(
                    "config-knob", self.flag_path, line, "Config",
                    f"_Flag '{name}' has no doc comment above its "
                    "declaration",
                    f"undocumented:{name}"))

    # -- fingerprints --------------------------------------------------------

    def _assign_fingerprints(self) -> None:
        counts: Dict[str, int] = {}
        for f in sorted(self.findings, key=lambda x: (x.path, x.line)):
            base = f"{f.check}|{f.path}|{f.scope}|{f.detail}"
            n = counts.get(base, 0)
            counts[base] = n + 1
            f.fingerprint = base if n == 0 else f"{base}#{n + 1}"


def _find_cycles(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Simple cycles in a small digraph, each reported once (rotated so the
    lexicographically-smallest node leads)."""
    seen: Set[Tuple[str, ...]] = set()
    out: List[List[str]] = []

    def dfs(start: str, node: str, path: List[str], visiting: Set[str]):
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                cycle = path[:]
                i = cycle.index(min(cycle))
                canon = tuple(cycle[i:] + cycle[:i])
                if canon not in seen:
                    seen.add(canon)
                    out.append(list(canon))
            elif nxt not in visiting and nxt > start:
                # only explore nodes > start: each cycle found exactly once
                # from its smallest node
                visiting.add(nxt)
                dfs(start, nxt, path + [nxt], visiting)
                visiting.discard(nxt)

    for start in sorted(edges):
        dfs(start, start, [start], {start})
    return out


# ---------------------------------------------------------------------------
# baseline + CLI
# ---------------------------------------------------------------------------

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                "lint_baseline.txt")


def load_baseline(path: str) -> Set[str]:
    if not os.path.exists(path):
        return set()
    with open(path, "r", encoding="utf-8") as fh:
        return {line.strip() for line in fh
                if line.strip() and not line.startswith("#")}


def write_baseline(path: str, findings: List[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# raylint baseline — accepted findings, one fingerprint "
                 "per line.\n")
        fh.write("# Regenerate with: python -m ray_tpu.devtools.lint "
                 "--update-baseline\n")
        for fp in sorted({f.fingerprint for f in findings}):
            fh.write(fp + "\n")


def lint_tree(root: str) -> List[Finding]:
    """Programmatic entry point: all findings for a tree (no baseline)."""
    return Linter(root).run()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu.devtools.lint",
        description="raylint: concurrency & contract static analysis")
    parser.add_argument("paths", nargs="*",
                        help="files/dirs to scan (default: the ray_tpu tree)")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite the baseline with current findings")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding; exit 1 if any")
    parser.add_argument("--check-baseline", action="store_true",
                        help="explicit CI mode: diff findings against the "
                             "baseline and exit 1 on anything new (this is "
                             "also the default behavior)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline file (default: %(default)s)")
    parser.add_argument("--profile", action="store_true",
                        help="print per-check wall time")
    parser.add_argument("-q", "--quiet", action="store_true")
    args = parser.parse_args(argv)

    roots = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    for root in roots:
        linter = Linter(root)
        findings.extend(linter.run())
        for phase, dt in linter.timings.items():
            timings[phase] = timings.get(phase, 0.0) + dt

    if args.profile:
        for phase, dt in sorted(timings.items(), key=lambda kv: -kv[1]):
            print(f"  {phase:<14} {dt * 1000:8.1f} ms", file=sys.stderr)

    if args.update_baseline:
        write_baseline(args.baseline, findings)
        print(f"baseline updated: {len(findings)} accepted findings -> "
              f"{args.baseline}")
        return 0

    if args.no_baseline:
        new = findings
    else:
        accepted = load_baseline(args.baseline)
        new = [f for f in findings if f.fingerprint not in accepted]

    if not args.quiet:
        for f in new:
            print(f.render())
    by_check: Dict[str, int] = {}
    for f in findings:
        by_check[f.check] = by_check.get(f.check, 0) + 1
    summary = ", ".join(f"{k}={v}" for k, v in sorted(by_check.items()))
    if new:
        print(f"raylint: {len(new)} NEW finding(s) "
              f"({len(findings)} total: {summary})", file=sys.stderr)
        print("(accept intentionally with --update-baseline)",
              file=sys.stderr)
        return 1
    if not args.quiet:
        print(f"raylint: clean ({len(findings)} baselined: {summary or '0'})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
