"""Runtime lock-order validator — the dynamic half of raylint.

The static pass (``ray_tpu.devtools.lint``) sees nesting it can prove from
the AST; this module catches what it can't: orders established across
threads, through callbacks, and through locks on other objects. Enabled, it
replaces ``threading.Lock`` / ``RLock`` / ``Condition`` with instrumented
wrappers that

- record each thread's **held-set** (which locks it currently holds),
- maintain a process-global **acquisition-order graph** keyed by the lock's
  allocation site (``file:line`` of construction — the lockdep "lock class"
  trick: one edge per code-level ordering, not per instance pair),
- on every acquire with locks held, add ``held → acquiring`` edges and
  check for a path in the REVERSE direction: if some other thread ever
  acquired these locks in the opposite order, the program contains a
  potential deadlock — report it NOW, deterministically, instead of hanging
  one run in a thousand at pod scale,
- detect guaranteed self-deadlock (re-acquiring a held non-reentrant Lock).

Violations raise :class:`LockOrderError` at the acquire site AND are
recorded in a process-global list (``violations()``) so test harnesses can
assert emptiness even when a daemon thread swallowed the raise.

Enable with the ``lock_order_check_enabled`` config knob
(``RAY_TPU_LOCK_ORDER_CHECK_ENABLED=1`` — the env form propagates to every
spawned cluster process, whose entry points call :func:`maybe_install`).
``tests/conftest.py`` installs it for the whole tier-1 run when the env var
is set, and fails any test that recorded a violation.

Caveats (by design):

- Locks created BEFORE :func:`install` (module-import locks of the stdlib)
  are not instrumented — install as early as possible. With the env knob
  set, ``ray_tpu/__init__`` installs at the very top of the package import,
  so every ray_tpu module-level lock is covered in every process.
- Edges between two instances from the SAME allocation site are skipped:
  many-instance classes (per-connection senders, per-actor mailboxes) would
  otherwise self-cycle on instance order no analysis can fix. Same-site
  ordering bugs need the static pass or an explicit two-site repro.
- The graph only grows; a once-seen order is never forgotten. That is the
  point: an inversion is reported even if the two orders never overlap in
  time in this run.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple

__all__ = [
    "LockOrderError", "install", "uninstall", "maybe_install",
    "violations", "clear_violations", "Lock", "RLock", "Condition",
]

_ENV_KNOB = "RAY_TPU_LOCK_ORDER_CHECK_ENABLED"

# Originals captured at import so wrappers survive install/uninstall cycles.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition


class LockOrderError(RuntimeError):
    """A lock acquisition that inverts a previously-observed order (or
    re-acquires a held non-reentrant lock)."""


# -- global state -----------------------------------------------------------

# site -> set of successor sites (edges: "site A held while B acquired").
_graph: Dict[str, Set[str]] = {}
# (a, b) -> human-readable provenance of the first observation of that edge
_edge_where: Dict[Tuple[str, str], str] = {}
_violations: List[str] = []
# Guards _graph/_edge_where/_violations. Deliberately a REAL lock (never
# instrumented) and always a leaf: nothing else is ever acquired under it.
_state_lock = _REAL_LOCK()

_tls = threading.local()  # .held: List[_CheckedBase] (outermost first)


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _caller_site() -> str:
    """file:line of the first stack frame outside this module."""
    here = os.path.normcase(__file__)
    for frame in traceback.extract_stack()[::-1]:
        if os.path.normcase(frame.filename) != here:
            return f"{os.path.basename(frame.filename)}:{frame.lineno}"
    return "<unknown>"


def _has_path(src: str, dst: str) -> bool:
    """DFS reachability src -> dst in the order graph (caller holds
    _state_lock)."""
    stack, seen = [src], {src}
    while stack:
        node = stack.pop()
        if node == dst:
            return True
        for nxt in _graph.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append(nxt)
    return False


def violations() -> List[str]:
    """Messages of every inversion observed so far in this process."""
    with _state_lock:
        return list(_violations)


def clear_violations() -> None:
    with _state_lock:
        _violations.clear()


def _reset_graph() -> None:
    with _state_lock:
        _graph.clear()
        _edge_where.clear()
        _violations.clear()


# -- instrumented primitives -------------------------------------------------


class _CheckedBase:
    """Shared acquire/release bookkeeping for Lock and RLock wrappers."""

    _reentrant = False

    def __init__(self):
        self._site = _caller_site()
        self._inner = self._make_inner()

    def _make_inner(self):
        raise NotImplementedError

    # -- the check ----------------------------------------------------------

    def _check_order(self) -> None:
        held = _held()
        if not held:
            return
        if held[-1] is self and not self._reentrant:
            msg = (f"self-deadlock: re-acquiring non-reentrant lock "
                   f"{self._site} already held by this thread")
            with _state_lock:
                _violations.append(msg)
            raise LockOrderError(msg)
        me = self._site
        new_edges = []
        for other in held:
            if other is self or other._site == me:
                continue  # same site: skip (see module docstring)
            new_edges.append(other._site)
        if not new_edges:
            return
        where = _where()
        with _state_lock:
            for prev in new_edges:
                if me in _graph.get(prev, ()):  # edge already known
                    continue
                if _has_path(me, prev):
                    first = _edge_where.get(self._first_back_edge(me, prev),
                                            "<earlier>")
                    msg = (f"lock-order inversion: acquiring {me} while "
                           f"holding {prev} at {where}, but the opposite "
                           f"order was established at {first}")
                    _violations.append(msg)
                    raise LockOrderError(msg)
                _graph.setdefault(prev, set()).add(me)
                _edge_where[(prev, me)] = where

    @staticmethod
    def _first_back_edge(me: str, prev: str) -> Tuple[str, str]:
        """Best-effort provenance: the direct back edge if present, else any
        edge out of `me` on a path to `prev` (caller holds _state_lock)."""
        if prev in _graph.get(me, ()):
            return (me, prev)
        for nxt in _graph.get(me, ()):
            if nxt == prev or _has_path(nxt, prev):
                return (me, nxt)
        return (me, prev)

    def _note_acquired(self) -> None:
        _held().append(self)

    def _note_released(self) -> None:
        held = _held()
        # release in any order (condition _release_save, manual release)
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                return

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1):
        # Order-check BEFORE the (possibly blocking) inner acquire: a real
        # deadlock must be reported, not merely entered.
        if blocking:
            self._check_order()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._note_acquired()
        return ok

    def release(self) -> None:
        self._inner.release()
        self._note_released()

    __enter__ = acquire

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def _at_fork_reinit(self) -> None:
        # stdlib protocol (os.register_at_fork users): fresh inner lock in
        # the child; the child's held-set starts empty anyway (new thread).
        self._inner = self._make_inner()

    def __repr__(self):
        return f"<{type(self).__name__} site={self._site}>"


def _where() -> str:
    here = os.path.normcase(os.path.dirname(__file__))
    for frame in traceback.extract_stack()[::-1]:
        d = os.path.normcase(os.path.dirname(frame.filename))
        if d != here and "threading" not in os.path.basename(frame.filename):
            return (f"{os.path.basename(frame.filename)}:{frame.lineno} "
                    f"in {frame.name}")
    return "<unknown>"


class CheckedLock(_CheckedBase):
    _reentrant = False

    def _make_inner(self):
        return _REAL_LOCK()

    # threading.Condition support: full release/restore + ownership probe.
    def _release_save(self):
        self.release()

    def _acquire_restore(self, _state) -> None:
        self.acquire()

    def _is_owned(self) -> bool:
        # Mirrors threading's plain-Lock heuristic ("held by someone").
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True


class CheckedRLock(_CheckedBase):
    _reentrant = True

    def __init__(self):
        super().__init__()
        self._owner: Optional[int] = None
        self._count = 0

    def _make_inner(self):
        return _REAL_RLOCK()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = threading.get_ident()
        if self._owner != me and blocking:
            self._check_order()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            if self._count == 0:
                self._owner = me
                self._note_acquired()
            self._count += 1
        return ok

    __enter__ = acquire

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._note_released()
        self._inner.release()

    def __exit__(self, *exc) -> None:
        self.release()

    # threading.Condition support (full-depth release, exactly like the
    # stdlib _RLock._release_save).
    def _release_save(self):
        count = self._count
        self._count = 0
        self._owner = None
        self._note_released()
        for _ in range(count):
            self._inner.release()
        return count

    def _acquire_restore(self, count: int) -> None:
        # Waiters re-acquiring after a wait() re-check order like any fresh
        # acquire.
        self._check_order()
        for _ in range(count):
            self._inner.acquire()
        self._count = count
        self._owner = threading.get_ident()
        self._note_acquired()

    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _at_fork_reinit(self) -> None:
        self._inner = self._make_inner()
        self._owner = None
        self._count = 0


def Lock():  # noqa: N802 — drop-in for threading.Lock
    return CheckedLock()


def RLock():  # noqa: N802 — drop-in for threading.RLock
    return CheckedRLock()


def Condition(lock=None):  # noqa: N802 — drop-in for threading.Condition
    """A real threading.Condition over a checked lock: wait() releases the
    lock through `_release_save` (held-set stays truthful through the park)
    and the re-acquire after wakeup is order-checked like any other."""
    if lock is None:
        lock = CheckedRLock()
    return _REAL_CONDITION(lock)


# -- install / uninstall ------------------------------------------------------

_installed = False


def install(fresh_graph: bool = True) -> None:
    """Monkeypatch ``threading.Lock/RLock/Condition`` with the checked
    versions. Locks created before this call stay plain. Idempotent."""
    global _installed
    if _installed:
        return
    if fresh_graph:
        _reset_graph()
    threading.Lock = Lock
    threading.RLock = RLock
    threading.Condition = Condition
    _installed = True


def uninstall() -> None:
    """Restore the real primitives (already-created checked locks keep
    working — they wrap real locks)."""
    global _installed
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    _installed = False


def installed() -> bool:
    return _installed


def maybe_install() -> bool:
    """Install iff the ``lock_order_check_enabled`` knob is on. Called from
    process entry points (gcs_server / node_daemon / worker_main mains) so
    spawned cluster processes self-instrument when the env var propagates.
    Reads the env var directly first — entry points call this BEFORE the
    config table exists."""
    on = os.environ.get(_ENV_KNOB)
    if on is not None:
        enabled = on.lower() in ("1", "true", "yes", "on")
    else:
        try:
            from ray_tpu.core.config import config

            enabled = config().lock_order_check_enabled
        except Exception:  # noqa: BLE001 — config unavailable: stay off
            enabled = False
    if enabled:
        install()
    return enabled
