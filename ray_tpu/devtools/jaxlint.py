"""jaxlint — the JAX-aware raylint checks (static half of the compile-churn
and host-sync tier; ``ray_tpu.devtools.jitcheck`` is the runtime half).

raylint (``ray_tpu.devtools.lint``) covers locks, RPC contracts and
resource lifecycles but is blind to the JAX side of the tree, where the
costly mistakes are invisible to every functional test: a ``jax.jit``
constructed per call compiles from scratch every time, one stray
``.item()`` in the decode loop serializes the device pipeline, a reused
PRNG key silently correlates samples, and reading a donated buffer after
the call is garbage on real accelerators. These four checks run as extra
phases inside :class:`ray_tpu.devtools.lint.Linter` — same AST cache,
same ``# raylint: ignore[...]`` pragmas, same fingerprint/baseline
machinery, same ``ray-tpu-lint`` CLI and CI gate.

Checks
======
``jit-churn``
    A ``jax.jit(...)`` / ``functools.partial(jax.jit, ...)`` constructed
    in FUNCTION scope (so: re-executed per call) whose result neither
    escapes (returned / yielded — the one-shot builder pattern), nor is
    cached (assigned to a ``self.`` / module attribute or container
    slot), nor is handed to another call (registered elsewhere). Each
    call to the enclosing function then pays a fresh trace + XLA compile.
    Also: call sites that feed DATA-DERIVED Python scalars (``len(x)``,
    ``x.shape[i]``, ``int(...)``, ``x.size`` and arithmetic on them)
    into ``static_argnums`` / ``static_argnames`` positions of a
    resolved jitted callable — one full compile per distinct value.
``host-sync``
    Inside the declared hot-path scopes (:data:`HOT_SCOPES` — the engine
    step/decode path, the token generator, the RL sample/update loops;
    coverage-guarded so a rename can't silently retire a scope), any
    implicit device→host synchronization on a value the intra-function
    taint walk proves device-resident: ``np.asarray`` / ``np.array``,
    ``float()`` / ``int()`` / ``bool()`` coercion, ``.item()`` /
    ``.tolist()``, and truthiness tests. The sanctioned exit is an
    EXPLICIT batched ``jax.device_get`` — its results are host values
    and untainted.
``key-reuse``
    Intra-function dataflow: a PRNG key binding (``jax.random.key`` /
    ``PRNGKey`` / ``split`` / ``fold_in`` result, or a parameter named
    like a key) consumed by ≥ 2 ``jax.random.*`` calls with no
    intervening ``split`` / reassignment — the second draw repeats the
    first's randomness. ``fold_in(key, i)`` is the sanctioned
    derive-many pattern and does not count as consumption.
``donate-uaf``
    A binding passed at a ``donate_argnums`` position of a resolved
    jitted callable and READ again afterwards without rebinding. The
    donated buffer is dead after dispatch on real accelerators;
    ``x = f(x)`` (rebind-through) is the sanctioned shape.

All findings fingerprint without line numbers (baseline-stable) and obey
the standard pragma on the finding line or the comment lines above it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.devtools.lint import Finding

__all__ = ["JAX_CHECKS", "HOT_SCOPES", "DEVICE_FN_NAMES",
           "check_jit_churn", "check_host_sync", "check_key_reuse",
           "check_donate_uaf"]

JAX_CHECKS = ("jit-churn", "host-sync", "key-reuse", "donate-uaf")

#: The hot-path scopes host-sync patrols: scan-root-relative path suffix →
#: function/method names that constitute the per-step / per-token path.
#: Coverage-guarded: when the file is in the scan set, every named scope
#: must exist, so a rename retires the declaration loudly, not silently
#: (the PR 6 hot-module discipline).
HOT_SCOPES: Dict[str, Tuple[str, ...]] = {
    "serve/llm.py": ("_step_inner", "_run_decode"),
    "models/generate.py": ("generate",),
    "rllib/env_runner.py": ("sample",),
    "rllib/learner.py": ("update",),
    "rllib/inference.py": ("_run_batch",),
}

#: Method names whose call results are device values wherever they appear
#: (the model forward surface used by the RL stack).
DEVICE_FN_NAMES = {"forward_inference", "forward_train", "sample_action",
                   "init_params"}

#: Dotted-call prefixes that produce device-resident values.
_TAINT_PREFIXES = ("jnp.", "jax.numpy.", "jax.lax.", "jax.random.",
                   "jax.nn.", "jax.scipy.", "jax.ops.")

#: jax.* calls that return HOST values (never taint).
_JAX_HOST_SAFE = {
    "jax.device_get", "jax.device_count", "jax.local_device_count",
    "jax.devices", "jax.local_devices", "jax.process_index",
    "jax.process_count", "jax.default_backend", "jax.eval_shape",
}

_NP_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "onp.asarray", "onp.array"}

_KEY_MAKERS = {"key", "PRNGKey", "split", "fold_in", "wrap_key_data",
               "clone"}


def _dotted(node: ast.expr) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.expr) -> bool:
    """node is the callable ``jax.jit`` (or bare ``jit`` imported from
    jax)."""
    d = _dotted(node)
    return d in ("jax.jit", "jit")


def _jit_call(node: ast.expr) -> Optional[ast.Call]:
    """The ``jax.jit(...)`` call if node is one, directly or through
    ``functools.partial(jax.jit, ...)``. Returns the call whose keywords
    carry static/donate info."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jax_jit(node.func):
        return node
    d = _dotted(node.func)
    if d in ("partial", "functools.partial") and node.args \
            and _is_jax_jit(node.args[0]):
        return node
    return None


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg == name:
            return k.value
    return None


def _int_tuple(node: Optional[ast.expr]) -> Tuple[int, ...]:
    """Literal ints out of ``(0, 2)`` / ``0`` / ``[1]``; () if dynamic."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _str_tuple(node: Optional[ast.expr]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def _walk_functions(tree: ast.Module):
    """Yield (qualname, func_node, at_module_level) for every function/
    method, in source order, including nested defs."""
    def rec(node, prefix: str, module_level: bool):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield q, child, module_level
                yield from rec(child, q, False)
            elif isinstance(child, ast.ClassDef):
                q = f"{prefix}.{child.name}" if prefix else child.name
                yield from rec(child, q, module_level)
    yield from rec(tree, "", True)


def _is_data_derived(node: ast.expr) -> bool:
    """Expression yields a Python scalar computed FROM runtime data —
    ``len(x)``, ``int(x)``, ``x.shape[i]``, ``x.size``, ``x.ndim``, and
    arithmetic over those. One distinct value = one XLA compile when fed
    to a static argument."""
    if isinstance(node, ast.Call):
        d = _dotted(node.func)
        if d in ("len", "int"):
            return True
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "item", "__len__"):
            return True
        return False
    if isinstance(node, ast.Attribute):
        return node.attr in ("size", "ndim", "nbytes")
    if isinstance(node, ast.Subscript):
        return (isinstance(node.value, ast.Attribute)
                and node.value.attr == "shape")
    if isinstance(node, ast.BinOp):
        return _is_data_derived(node.left) or _is_data_derived(node.right)
    if isinstance(node, ast.UnaryOp):
        return _is_data_derived(node.operand)
    return False


# ---------------------------------------------------------------------------
# jit-churn
# ---------------------------------------------------------------------------


class _JittedBinding:
    """A resolved jitted callable visible at module scope (or a decorated
    def): call sites can be checked against its static/donate positions."""

    __slots__ = ("name", "static_nums", "static_names", "donate_nums",
                 "self_offset")

    def __init__(self, name: str, call: ast.Call, self_offset: int = 0):
        self.name = name
        self.static_nums = _int_tuple(_kw(call, "static_argnums"))
        self.static_names = _str_tuple(_kw(call, "static_argnames"))
        self.donate_nums = _int_tuple(_kw(call, "donate_argnums"))
        self.self_offset = self_offset


def _collect_jitted_bindings(tree: ast.Module) -> Dict[str, _JittedBinding]:
    """name → binding for jitted callables resolvable by name: module-level
    ``f = jax.jit(g, ...)`` and ``@jax.jit`` / ``@partial(jax.jit, ...)``
    decorated defs (any nesting — resolution at call sites is by bare
    name, which is how the tree calls them)."""
    out: Dict[str, _JittedBinding] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            call = _jit_call(node.value)
            if call is not None:
                out[node.targets[0].id] = _JittedBinding(
                    node.targets[0].id, call)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                call = _jit_call(dec) if isinstance(dec, ast.Call) else None
                if call is None and _is_jax_jit(dec):
                    call = ast.Call(func=dec, args=[], keywords=[])
                if call is not None:
                    out[node.name] = _JittedBinding(node.name, call)
    return out


def check_jit_churn(linter, parsed: Sequence[Tuple[str, ast.Module, str]],
                    ) -> None:
    for rel, tree, _src in parsed:
        bindings = _collect_jitted_bindings(tree)
        for qual, fn, _mod in _walk_functions(tree):
            _jit_churn_in_function(linter, rel, qual, fn)
            _static_arg_calls(linter, rel, qual, fn, bindings)


def _jit_churn_in_function(linter, rel: str, qual: str, fn) -> None:
    """Per-call jit constructions inside ``fn`` whose result never
    escapes."""
    # name → construction line for local `n = jax.jit(...)` bindings
    local: Dict[str, int] = {}
    escaped: Set[str] = set()
    nested_defs = {c for c in ast.walk(fn)
                   if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and c is not fn}

    def in_nested(node) -> bool:
        return any(node in ast.walk(d) for d in nested_defs)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            call = _jit_call(node.value)
            if call is None:
                continue
            tgt = node.targets[0] if len(node.targets) == 1 else None
            if isinstance(tgt, ast.Name) and not in_nested(node):
                local[tgt.id] = node.lineno
            # self.x = jax.jit(...) / cache[k] = jax.jit(...): cached.
        elif isinstance(node, ast.Call):
            inner = _jit_call(node.func)
            if inner is not None:
                # jax.jit(f)(args): compiled and thrown away, every call.
                linter.add(Finding(
                    "jit-churn", rel, node.lineno, qual,
                    "jax.jit(...) constructed and called in one expression"
                    " — a fresh trace+compile on every call of this"
                    " function; cache the jitted callable",
                    f"immediate-jit-call:{_dotted(inner.args[0].func) if inner.args and isinstance(inner.args[0], ast.Call) else ast.dump(inner.args[0]) if inner.args else '?'}"))
            # name escaping into another call exempts it
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in local:
                    escaped.add(arg.id)
        elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
            val = node.value
            if val is None:
                continue
            if _jit_call(val) is not None:
                continue  # `return jax.jit(...)`: the one-shot builder shape
            # a name escapes if returned ITSELF; `return [fwd(x) ...]`
            # only returns call results — fwd still dies with the frame
            func_pos = {id(sub.func) for sub in ast.walk(val)
                        if isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)}
            for sub in ast.walk(val):
                if isinstance(sub, ast.Name) and sub.id in local \
                        and id(sub) not in func_pos:
                    escaped.add(sub.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            for dec in node.decorator_list:
                if _is_jax_jit(dec) or (isinstance(dec, ast.Call)
                                        and _jit_call(dec) is not None):
                    local[node.name] = node.lineno

    # names stored into attributes / subscripts (caches) also escape
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                    for sub in ast.walk(node.value):
                        if isinstance(sub, ast.Name) and sub.id in local:
                            escaped.add(sub.id)

    for name, line in sorted(local.items(), key=lambda kv: kv[1]):
        if name in escaped:
            continue
        linter.add(Finding(
            "jit-churn", rel, line, qual,
            f"'{name}' rebuilds jax.jit on every call of this function"
            " (the compile cache dies with the binding); cache it on"
            " self/module or return it from a builder",
            f"local-jit:{name}"))


def _static_arg_calls(linter, rel: str, qual: str, fn,
                      bindings: Dict[str, _JittedBinding]) -> None:
    """Call sites of resolved jitted callables feeding data-derived
    scalars into static positions."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = node.func.id if isinstance(node.func, ast.Name) else None
        if name is None and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self":
            name = node.func.attr
        b = bindings.get(name) if name else None
        if b is None:
            continue
        for pos in b.static_nums:
            i = pos - b.self_offset
            if 0 <= i < len(node.args) and _is_data_derived(node.args[i]):
                linter.add(Finding(
                    "jit-churn", rel, node.lineno, qual,
                    f"data-derived scalar fed to static_argnums position"
                    f" {pos} of '{b.name}' — one full XLA compile per"
                    " distinct value; bucket it or make the arg traced",
                    f"static-data:{b.name}:{pos}"))
        for k in node.keywords:
            if k.arg in b.static_names and _is_data_derived(k.value):
                linter.add(Finding(
                    "jit-churn", rel, node.lineno, qual,
                    f"data-derived scalar fed to static argname"
                    f" '{k.arg}' of '{b.name}' — one full XLA compile per"
                    " distinct value; bucket it or make the arg traced",
                    f"static-data:{b.name}:{k.arg}"))


# ---------------------------------------------------------------------------
# host-sync
# ---------------------------------------------------------------------------


class _TaintWalk:
    """Linear taint walk over one hot function: which bindings hold
    device-resident values, and where they leak to the host implicitly.
    Loops are walked twice so cross-iteration flows surface; findings
    dedupe on (line, kind)."""

    def __init__(self, linter, rel: str, qual: str,
                 device_methods: Optional[Set[str]] = None):
        self.linter = linter
        self.rel = rel
        self.qual = qual
        self.taints: Set[str] = set()
        self.jit_names: Set[str] = set()
        self.seen: Set[Tuple[int, str]] = set()
        #: self-method names whose results are device values: the file's
        #: other hot scopes (`self._run_decode(...)`) plus every attr the
        #: class caches a jax.jit under (`self._sample_many = jax.jit(...)`)
        self.device_methods = device_methods or set()

    # -- tokens -------------------------------------------------------------

    @staticmethod
    def _token(node: ast.expr) -> Optional[str]:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute) and isinstance(node.value,
                                                          ast.Name) \
                and node.value.id == "self":
            return f"self.{node.attr}"
        return None

    # -- taint evaluation ---------------------------------------------------

    def tainted(self, node: Optional[ast.expr]) -> bool:
        if node is None:
            return False
        tok = self._token(node)
        if tok is not None:
            return tok in self.taints
        if isinstance(node, ast.Call):
            return self._call_taints(node)
        if isinstance(node, ast.Attribute):
            # array metadata is host-resident — reading it never syncs
            if node.attr in ("shape", "dtype", "ndim", "size", "nbytes",
                             "sharding"):
                return False
            return self.tainted(node.value)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self.tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.tainted(node.left) or self.tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.tainted(node.operand)
        if isinstance(node, ast.Compare):
            return self.tainted(node.left) or any(
                self.tainted(c) for c in node.comparators)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self.tainted(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return self.tainted(node.body) or self.tainted(node.orelse)
        if isinstance(node, ast.NamedExpr):
            return self.tainted(node.value)
        return False

    def _call_taints(self, node: ast.Call) -> bool:
        d = _dotted(node.func)
        if d is not None:
            if d in _JAX_HOST_SAFE or d in _NP_SYNC_CALLS:
                return False
            if d.startswith(_TAINT_PREFIXES) or d in ("jax.jit", "jax.vmap",
                                                      "jax.pmap",
                                                      "jax.grad"):
                return True
        if isinstance(node.func, ast.Name) and (
                node.func.id in self.jit_names
                or node.func.id in self.taints):
            # a call of a tainted binding: `df = self._pg.decode_fn(c)`
            # then `df(...)` — the callable came off the device path
            return True
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            # self._decode_fn(...), self._fns[b](...), model.forward_*(...)
            if attr.endswith("_fn") or attr in DEVICE_FN_NAMES:
                return True
            if attr in self.device_methods and isinstance(
                    node.func.value, ast.Name) \
                    and node.func.value.id == "self":
                return True
            if attr in ("item", "tolist"):
                return False  # host scalars (flagged as sinks separately)
            # method call on a tainted object stays on device
            return self.tainted(node.func.value)
        if isinstance(node.func, ast.Subscript):
            base = self._token(node.func.value)
            if base is not None and (base.endswith("_fns")
                                     or base.endswith("_fn")):
                return True
            return self.tainted(node.func.value)
        return False

    # -- findings -----------------------------------------------------------

    def _emit(self, line: int, kind: str, message: str) -> None:
        if (line, kind) in self.seen:
            return
        self.seen.add((line, kind))
        self.linter.add(Finding("host-sync", self.rel, line, self.qual,
                                message, kind))

    def check_sinks(self, expr: Optional[ast.expr]) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in _NP_SYNC_CALLS and node.args \
                        and self.tainted(node.args[0]):
                    self._emit(node.lineno, f"np-sync:{d}",
                               f"{d}() on a device value inside a hot scope"
                               " — an implicit blocking sync; batch into"
                               " one jax.device_get per step")
                elif d in ("float", "int", "bool", "complex") and node.args \
                        and self.tainted(node.args[0]):
                    self._emit(node.lineno, f"coerce:{d}",
                               f"{d}() coercion of a device value inside a"
                               " hot scope syncs the pipeline; device_get"
                               " once, then coerce on host")
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("item", "tolist") \
                        and self.tainted(node.func.value):
                    self._emit(node.lineno, f"item:{node.func.attr}",
                               f".{node.func.attr}() on a device value"
                               " inside a hot scope syncs the pipeline;"
                               " device_get once, then read on host")
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                self._comprehension(node)

    def _comprehension(self, node) -> None:
        added: List[str] = []
        for gen in node.generators:
            if self.tainted(gen.iter):
                for sub in ast.walk(gen.target):
                    tok = self._token(sub)
                    if tok and tok not in self.taints:
                        self.taints.add(tok)
                        added.append(tok)
            for cond in gen.ifs:
                self.truthiness(cond)
        if isinstance(node, ast.DictComp):
            self.check_sinks(node.key)
            self.check_sinks(node.value)
        else:
            self.check_sinks(node.elt)
        for tok in added:
            self.taints.discard(tok)

    def truthiness(self, test: ast.expr) -> None:
        self.check_sinks(test)
        probe = test
        while isinstance(probe, ast.UnaryOp) and isinstance(probe.op,
                                                            ast.Not):
            probe = probe.operand
        if isinstance(probe, ast.BoolOp):
            for v in probe.values:
                self.truthiness(v)
            return
        if self.tainted(probe):
            self._emit(test.lineno, "truthiness",
                       "truthiness test on a device value inside a hot"
                       " scope forces a blocking sync; device_get first")

    # -- statement walk -----------------------------------------------------

    def assign_to(self, target: ast.expr, is_tainted: bool) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign_to(elt, is_tainted)
            return
        if isinstance(target, ast.Starred):
            self.assign_to(target.value, is_tainted)
            return
        tok = self._token(target)
        if tok is None:
            return
        if is_tainted:
            self.taints.add(tok)
        else:
            self.taints.discard(tok)

    def block(self, stmts: Sequence[ast.stmt]) -> None:
        for stmt in stmts:
            self.stmt(stmt)

    def stmt(self, s: ast.stmt) -> None:  # noqa: C901 — a dispatch table
        if isinstance(s, ast.Assign):
            self.check_sinks(s.value)
            t = self.tainted(s.value)
            if isinstance(s.value, ast.Call):
                if _jit_call(s.value) is not None and s.targets \
                        and isinstance(s.targets[0], ast.Name):
                    self.jit_names.add(s.targets[0].id)
            for tgt in s.targets:
                self.assign_to(tgt, t)
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            self.check_sinks(s.value)
            self.assign_to(s.target, self.tainted(s.value))
        elif isinstance(s, ast.AugAssign):
            self.check_sinks(s.value)
            if self.tainted(s.value):
                self.assign_to(s.target, True)
        elif isinstance(s, ast.Expr):
            self.check_sinks(s.value)
        elif isinstance(s, ast.Return):
            self.check_sinks(s.value)
        elif isinstance(s, ast.If):
            self.truthiness(s.test)
            self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, ast.While):
            for _ in range(2):
                self.truthiness(s.test)
                self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, ast.For):
            self.check_sinks(s.iter)
            it_tainted = self.tainted(s.iter)
            for _ in range(2):
                self.assign_to(s.target, it_tainted)
                self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self.check_sinks(item.context_expr)
                if item.optional_vars is not None:
                    self.assign_to(item.optional_vars,
                                   self.tainted(item.context_expr))
            self.block(s.body)
        elif isinstance(s, ast.Try):
            self.block(s.body)
            for h in s.handlers:
                self.block(h.body)
            self.block(s.orelse)
            self.block(s.finalbody)
        elif isinstance(s, ast.Assert):
            self.truthiness(s.test)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def (e.g. the generator closure inside `generate`):
            # walk it with the closure's taint state — per-token reads in
            # the inner loop are exactly what this check is for.
            self.block(s.body)
        # Import/Pass/Break/Continue/Raise/Delete/Global: nothing to taint


def _jit_cache_attrs(tree: ast.Module) -> Set[str]:
    """Attr names the file's classes cache jitted callables under:
    ``self.X = jax.jit(...)`` / ``partial(jax.jit, ...)`` anywhere."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and _jit_call(node.value) is not None:
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) \
                        and isinstance(tgt.value, ast.Name) \
                        and tgt.value.id == "self":
                    out.add(tgt.attr)
    return out


def check_host_sync(linter, parsed: Sequence[Tuple[str, ast.Module, str]],
                    ) -> None:
    for rel, tree, _src in parsed:
        scopes = None
        for key, names in HOT_SCOPES.items():
            if rel == key or rel.endswith("/" + key):
                scopes = set(names)
                break
        if scopes is None:
            continue
        device_methods = scopes | _jit_cache_attrs(tree)
        found: Set[str] = set()
        for qual, fn, _mod in _walk_functions(tree):
            if fn.name not in scopes:
                continue
            found.add(fn.name)
            walk = _TaintWalk(linter, rel, qual, device_methods)
            walk.block(fn.body)
        for missing in sorted(scopes - found):
            linter.add(Finding(
                "host-sync", rel, 1, "<file>",
                f"declared hot scope '{missing}' not found — update"
                " jaxlint.HOT_SCOPES so the decode path stays patrolled",
                f"hot-scope-missing:{missing}"))


# ---------------------------------------------------------------------------
# key-reuse
# ---------------------------------------------------------------------------


class _KeyWalk:
    """Count jax.random.* consumptions per key binding; ≥ 2 without an
    intervening split/rebind is reuse. Loop bodies run twice so
    once-per-iteration draws from a key bound OUTSIDE the loop flag."""

    def __init__(self, linter, rel: str, qual: str):
        self.linter = linter
        self.rel = rel
        self.qual = qual
        self.uses: Dict[str, int] = {}
        self.flagged: Set[str] = set()

    @staticmethod
    def _token(node: ast.expr) -> Optional[str]:
        return _TaintWalk._token(node)

    @staticmethod
    def _random_fn(call: ast.Call) -> Optional[str]:
        d = _dotted(call.func)
        if d is None:
            return None
        if d.startswith("jax.random.") or d.startswith("jrandom.") \
                or d.startswith("random_jax."):
            return d.rsplit(".", 1)[1]
        return None

    def _key_maker(self, value: ast.expr) -> bool:
        if isinstance(value, ast.Call):
            fn = self._random_fn(value)
            return fn in _KEY_MAKERS
        return False

    def bind(self, target: ast.expr) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.bind(elt)
            return
        if isinstance(target, ast.Starred):
            self.bind(target.value)
            return
        tok = self._token(target)
        if tok is not None:
            self.uses[tok] = 0
            self.flagged.discard(tok)

    def unbind(self, target: ast.expr) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.unbind(elt)
            return
        tok = self._token(target)
        if tok is not None:
            self.uses.pop(tok, None)

    def consume(self, call: ast.Call) -> None:
        fn = self._random_fn(call)
        if fn is None or fn == "fold_in":
            # fold_in(key, i) is the sanctioned derive-many pattern
            return
        for arg in call.args:
            tok = self._token(arg)
            if tok is None or tok not in self.uses:
                continue
            self.uses[tok] += 1
            if self.uses[tok] >= 2 and tok not in self.flagged:
                self.flagged.add(tok)
                self.linter.add(Finding(
                    "key-reuse", self.rel, call.lineno, self.qual,
                    f"PRNG key '{tok}' consumed by ≥2 jax.random calls"
                    " with no intervening split — the second draw repeats"
                    " the first's randomness",
                    f"key-reuse:{tok}"))

    def scan_calls(self, expr: Optional[ast.expr]) -> None:
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self.consume(node)

    def block(self, stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            self.stmt(s)

    def stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.Assign, ast.AnnAssign)):
            value = s.value
            targets = s.targets if isinstance(s, ast.Assign) else [s.target]
            self.scan_calls(value)
            if value is not None and self._key_maker(value):
                for tgt in targets:
                    self.bind(tgt)
            else:
                for tgt in targets:
                    self.unbind(tgt)
        elif isinstance(s, ast.Expr):
            self.scan_calls(s.value)
        elif isinstance(s, ast.Return):
            self.scan_calls(s.value)
        elif isinstance(s, ast.If):
            # mutually exclusive branches: one draw per branch is NOT
            # reuse — walk each from the same snapshot, keep the max
            self.scan_calls(s.test)
            snap = dict(self.uses)
            self.block(s.body)
            after_body = self.uses
            self.uses = dict(snap)
            self.block(s.orelse)
            merged = dict(self.uses)
            for tok, n in after_body.items():
                merged[tok] = max(merged.get(tok, 0), n)
            self.uses = merged
        elif isinstance(s, (ast.While, ast.For)):
            if isinstance(s, ast.For):
                self.scan_calls(s.iter)
            for _ in range(2):
                if isinstance(s, ast.While):
                    self.scan_calls(s.test)
                self.block(s.body)
            self.block(s.orelse)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self.scan_calls(item.context_expr)
            self.block(s.body)
        elif isinstance(s, ast.Try):
            self.block(s.body)
            for h in s.handlers:
                self.block(h.body)
            self.block(s.orelse)
            self.block(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # inline the nested def, but its parameters SHADOW outer keys
            # (`def nrm(key, ...)` gets a fresh key per call); closure
            # reads of non-shadowed keys still count.
            params = {a.arg for a in (s.args.posonlyargs + s.args.args
                                      + s.args.kwonlyargs)}
            shadowed = {tok: self.uses.pop(tok) for tok in list(self.uses)
                        if tok in params}
            self.block(s.body)
            for tok in params:
                self.uses.pop(tok, None)
            self.uses.update(shadowed)


_KEY_PARAM_HINTS = ("key", "rng")


def check_key_reuse(linter, parsed: Sequence[Tuple[str, ast.Module, str]],
                    ) -> None:
    for rel, tree, _src in parsed:
        for qual, fn, _mod in _walk_functions(tree):
            walk = _KeyWalk(linter, rel, qual)
            for arg in (fn.args.posonlyargs + fn.args.args
                        + fn.args.kwonlyargs):
                low = arg.arg.lower()
                if low in _KEY_PARAM_HINTS or low.endswith("_key") \
                        or low.endswith("_rng"):
                    walk.uses[arg.arg] = 0
            walk.block(fn.body)


# ---------------------------------------------------------------------------
# donate-uaf
# ---------------------------------------------------------------------------


def check_donate_uaf(linter, parsed: Sequence[Tuple[str, ast.Module, str]],
                     ) -> None:
    for rel, tree, _src in parsed:
        bindings = {n: b for n, b in _collect_jitted_bindings(tree).items()
                    if b.donate_nums}
        if not bindings:
            continue
        for qual, fn, _mod in _walk_functions(tree):
            _donate_in_function(linter, rel, qual, fn, bindings)


def _donate_in_function(linter, rel: str, qual: str, fn,
                        bindings: Dict[str, _JittedBinding]) -> None:
    stmts = list(fn.body)
    flat: List[ast.stmt] = []

    def flatten(block):
        for s in block:
            flat.append(s)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(s, field, None)
                if sub:
                    flatten(sub)
            for h in getattr(s, "handlers", ()) or ():
                flatten(h.body)

    flatten(stmts)

    for i, s in enumerate(flat):
        for call in [n for n in ast.walk(s)
                     if isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Name)
                     and n.func.id in bindings]:
            b = bindings[call.func.id]
            rebound_here: Set[str] = set()
            if isinstance(s, ast.Assign):
                for tgt in s.targets:
                    for sub in ast.walk(tgt):
                        if isinstance(sub, ast.Name):
                            rebound_here.add(sub.id)
            for pos in b.donate_nums:
                if not (0 <= pos < len(call.args)):
                    continue
                arg = call.args[pos]
                if not isinstance(arg, ast.Name):
                    continue
                if arg.id in rebound_here:
                    continue  # x = f(x): rebind-through, the sanctioned shape
                _scan_after(linter, rel, qual, flat[i + 1:], arg.id,
                            b.name, call.lineno)


def _scan_after(linter, rel: str, qual: str, rest: Sequence[ast.stmt],
                name: str, callee: str, call_line: int) -> None:
    for s in rest:
        if isinstance(s, ast.Assign):
            # a full rebind of the name kills the dangling reference —
            # but only if the VALUE doesn't read it first
            reads_in_value = any(isinstance(n, ast.Name) and n.id == name
                                 and isinstance(n.ctx, ast.Load)
                                 for n in ast.walk(s.value))
            if reads_in_value:
                linter.add(Finding(
                    "donate-uaf", rel, s.lineno, qual,
                    f"'{name}' was donated to '{callee}'"
                    " (donate_argnums) and read afterwards — the buffer"
                    " is dead after dispatch on real accelerators",
                    f"donate-uaf:{callee}:{name}"))
                return
            for tgt in s.targets:
                if isinstance(tgt, ast.Name) and tgt.id == name:
                    return
                if isinstance(tgt, (ast.Tuple, ast.List)) and any(
                        isinstance(e, ast.Name) and e.id == name
                        for e in tgt.elts):
                    return
            continue
        for n in ast.walk(s):
            if isinstance(n, ast.Name) and n.id == name \
                    and isinstance(n.ctx, ast.Load):
                linter.add(Finding(
                    "donate-uaf", rel, n.lineno, qual,
                    f"'{name}' was donated to '{callee}' (donate_argnums)"
                    " and read afterwards — the buffer is dead after"
                    " dispatch on real accelerators",
                    f"donate-uaf:{callee}:{name}"))
                return
            if isinstance(n, ast.Name) and n.id == name \
                    and isinstance(n.ctx, ast.Store):
                return
