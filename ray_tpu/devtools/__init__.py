"""Developer tooling: static analysis (`python -m ray_tpu.devtools.lint`)
and the opt-in runtime lock-order validator (`ray_tpu.devtools.lockcheck`).

Nothing in this package is imported by the runtime unless explicitly
enabled (the `lock_order_check_enabled` config knob) — shipping code pays
zero cost for it.
"""
