"""Runtime resource-leak validator — the dynamic half of raylint's
lifecycle checks.

The static passes (``thread-leak`` / ``resource-leak`` in
``ray_tpu.devtools.lint``) prove that every acquire SITE has a reachable
release; this module proves the release actually RAN: snapshot the
process's live threads, open fds, and native-store shm segments before a
test, diff after teardown, and name every survivor with its allocation
site. Daemon threads pass the static check (they can't wedge interpreter
exit) but still hold sockets, fds, and GCS poll slots — at pod scale a
daemon-restart path that abandons one exporter thread per restart is a
slow OOM. The diff is what keeps shutdown paths honest.

Enabled, :func:`install`

- wraps ``threading.Thread.__init__`` to stamp every thread with the
  ``file:line`` that constructed it (``_leakcheck_site``),
- wraps ``os.open`` / ``os.pipe`` and ``socket.socket`` to record fd
  allocation sites in a best-effort fd→site table (fd numbers recycle;
  the table is advisory, the ``/proc/self/fd`` diff is ground truth),
- leaves everything else untouched — snapshots read ``threading
  .enumerate()``, ``/proc/self/fd`` and ``/dev/shm``.

Enable with the ``leak_check_enabled`` knob
(``RAY_TPU_LEAK_CHECK_ENABLED=1`` — the env form propagates to spawned
cluster processes; ``ray_tpu/__init__`` installs at the very top of the
package import, mirroring lockcheck, so threads created during module
import are stamped too). ``tests/conftest.py`` adds an autouse fixture
that snapshots at test start and fails the test naming every leaked
resource at teardown.

Caveats (by design):

- fd sites are recorded only for ``os.open``/``os.pipe``/``socket``
  constructions that happen after install; other acquires (dup, accept,
  mmap, C extensions) are still CAUGHT by the ``/proc/self/fd`` diff but
  identified only by their readlink target.
- Asynchronous teardown (executor workers draining, daemon pollers
  noticing a closed connection) is real shutdown, not a leak —
  :func:`check` polls the diff for a settle window before declaring one.
- Child processes are out of scope: each cluster process self-installs
  off the propagated env var and polices its own resources.
"""

from __future__ import annotations

import os
import socket
import threading
import traceback
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

__all__ = [
    "install", "uninstall", "installed", "maybe_install",
    "Snapshot", "snapshot", "diff", "check",
]

_ENV_KNOB = "RAY_TPU_LEAK_CHECK_ENABLED"

_REAL_THREAD_INIT = threading.Thread.__init__
_REAL_OS_OPEN = os.open
_REAL_OS_PIPE = os.pipe
_REAL_SOCKET = socket.socket

#: fd -> human-readable allocation site (best effort; fds recycle)
_fd_sites: Dict[int, str] = {}

_SHM_DIR = "/dev/shm"


def _caller_site() -> str:
    """file:line of the first stack frame outside this module (and outside
    threading/socket internals)."""
    here = os.path.normcase(__file__)
    for frame in traceback.extract_stack()[::-1]:
        fn = os.path.normcase(frame.filename)
        base = os.path.basename(fn)
        if fn != here and base not in ("threading.py", "socket.py"):
            return f"{base}:{frame.lineno} in {frame.name}"
    return "<unknown>"


# -- instrumentation ---------------------------------------------------------


def _thread_init(self, *args, **kwargs):
    _REAL_THREAD_INIT(self, *args, **kwargs)
    self._leakcheck_site = _caller_site()


def _os_open(path, flags, *args, **kwargs):
    fd = _REAL_OS_OPEN(path, flags, *args, **kwargs)
    _fd_sites[fd] = f"os.open({path!r}) at {_caller_site()}"
    return fd


def _os_pipe():
    r, w = _REAL_OS_PIPE()
    site = _caller_site()
    _fd_sites[r] = f"os.pipe()[read] at {site}"
    _fd_sites[w] = f"os.pipe()[write] at {site}"
    return r, w


class _CheckedSocket(_REAL_SOCKET):
    """socket.socket that records its allocation site. Subclassing (not
    wrapping) keeps isinstance checks, accept()'s re-construction and
    ssl-wrapping working unchanged."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        try:
            _fd_sites[self.fileno()] = f"socket at {_caller_site()}"
        except OSError:  # already detached/closed
            pass


_installed = False


def install() -> None:
    """Stamp allocation sites onto threads/fds/sockets. Idempotent."""
    global _installed
    if _installed:
        return
    threading.Thread.__init__ = _thread_init
    os.open = _os_open
    os.pipe = _os_pipe
    socket.socket = _CheckedSocket
    _installed = True


def uninstall() -> None:
    global _installed
    threading.Thread.__init__ = _REAL_THREAD_INIT
    os.open = _REAL_OS_OPEN
    os.pipe = _REAL_OS_PIPE
    socket.socket = _REAL_SOCKET
    _installed = False


def installed() -> bool:
    return _installed


def maybe_install() -> bool:
    """Install iff the ``leak_check_enabled`` knob is on (env var first —
    process entry points run before the config table exists)."""
    on = os.environ.get(_ENV_KNOB)
    if on is not None:
        enabled = on.lower() in ("1", "true", "yes", "on")
    else:
        try:
            from ray_tpu.core.config import config

            enabled = config().leak_check_enabled
        except Exception:  # noqa: BLE001 — config unavailable: stay off
            enabled = False
    if enabled:
        install()
    return enabled


# -- snapshots ---------------------------------------------------------------


@dataclass
class Snapshot:
    """Live resources of this process at one instant. Thread objects are
    held by STRONG reference for the snapshot's lifetime: an id()-only set
    would let a start-time thread die, its address recycle onto a leaked
    thread, and the leak pass as clean."""
    threads: Set[threading.Thread] = field(default_factory=set)
    fds: Set[int] = field(default_factory=set)
    shm: Set[str] = field(default_factory=set)  # /dev/shm names we own


def _own_shm_names() -> Set[str]:
    """Names under /dev/shm whose embedded owner pid is THIS process
    (``rtpu_store_<pid>_...`` — the native store's naming scheme)."""
    marker = f"_{os.getpid()}_"
    try:
        return {n for n in os.listdir(_SHM_DIR)
                if n.startswith("rtpu_") and marker in n}
    except OSError:
        return set()


def snapshot() -> Snapshot:
    fds: Set[int] = set()
    try:
        for name in os.listdir("/proc/self/fd"):
            try:
                fd = int(name)
            except ValueError:
                continue
            # Drop the listing's own transient fd (closed by now): baking
            # it into `before` would mask a later acquire that recycles
            # the same number.
            try:
                os.readlink(f"/proc/self/fd/{fd}")
            except OSError:
                continue
            fds.add(fd)
    except OSError:
        pass
    return Snapshot(
        threads=set(threading.enumerate()),
        fds=fds,
        shm=_own_shm_names(),
    )


def _describe_thread(t: threading.Thread) -> str:
    site = getattr(t, "_leakcheck_site", None)
    kind = "daemon thread" if t.daemon else "non-daemon thread"
    return (f"{kind} '{t.name}' (started at {site})" if site
            else f"{kind} '{t.name}'")


def _describe_fd(fd: int) -> Optional[str]:
    """None when the fd no longer exists (a transient — not a leak)."""
    try:
        target = os.readlink(f"/proc/self/fd/{fd}")
    except OSError:
        return None
    site = _fd_sites.get(fd)
    return (f"fd {fd} -> {target} (opened {site})" if site
            else f"fd {fd} -> {target}")


def diff(before: Snapshot) -> List[str]:
    """Resources live NOW that were not live at ``before`` — each rendered
    with its allocation site where known."""
    leaks: List[str] = []
    for t in threading.enumerate():
        if t not in before.threads and t.is_alive():
            leaks.append(_describe_thread(t))
    now = snapshot()
    for fd in sorted(now.fds - before.fds):
        desc = _describe_fd(fd)  # re-verify: listdir's own fd is transient
        if desc is not None:
            leaks.append(desc)
    for name in sorted(now.shm - before.shm):
        leaks.append(f"shm segment /dev/shm/{name}")
    return leaks


def check(before: Snapshot, settle_s: float = 3.0,
          poll_s: float = 0.05) -> List[str]:
    """Diff against ``before``, giving asynchronous teardown (executor
    workers draining, daemon pollers noticing a closed socket) up to
    ``settle_s`` to finish. Returns the leaks that survived the window."""
    import time

    deadline = time.monotonic() + settle_s
    leaks = diff(before)
    while leaks and time.monotonic() < deadline:
        time.sleep(poll_s)
        leaks = diff(before)
    return leaks
