"""Runtime JAX compile-churn + steady-state guard — the dynamic half of
jaxlint.

The static passes (``jit-churn`` / ``host-sync`` in
``ray_tpu.devtools.jaxlint``) prove that every ``jax.jit`` SITE is cached
and every hot-path host read is explicit; this module proves it at
runtime: every compilation is counted and attributed to the ``file:line``
that constructed the jitted callable, and :func:`steady_state` turns the
serving/training contract — ZERO new XLA compilations, ZERO implicit
device→host reads after warmup — into recorded violations instead of a
silent 10–100× per-token tax.

Enabled, :func:`install`

- wraps ``jax.jit`` so every jitted callable is stamped with the
  ``file:line`` that constructed it; each call runs with that site on a
  thread-local stack, so compile events are attributed to their site and
  per-``(site, abstract signature)`` compile counts accumulate,
- registers a ``jax.monitoring`` duration listener on
  ``/jax/core/compile/backend_compile_duration`` — the ground truth for
  "an XLA compile happened" (tracing without compiling does not fire
  it) — feeding the ``ray_tpu_jit_compiles_total{site}`` /
  ``ray_tpu_jit_compile_seconds_total{site}`` counters and a
  ``jit.compile`` flight-recorder event per compile,
- wraps the implicit-read surface of ``jax.Array``
  (``__array__``/``__float__``/``__int__``/``__bool__``/``__index__``/
  ``item``) with a guard that is inert outside :func:`steady_state`;
  inside it, any implicit device→host read records a violation with its
  call site. ``jax.device_get`` is wrapped to mark itself as the ONE
  sanctioned read, so "batch host reads into one device_get" is
  enforceable even on the CPU backend, where JAX's own
  ``transfer_guard`` never fires (host-resident arrays transfer
  zero-copy).

:func:`steady_state` is a thread-local scope: the paged engine enters it
around every scheduler step once warmed, IMPALA around every training
iteration after the first. Inside it a new compilation or an implicit
host read is recorded in :func:`violations` (and raised at scope exit
with ``strict=True``); ``jax.transfer_guard_device_to_host("disallow")``
is layered on for real accelerators, where it also catches reads this
module cannot see.

Enable with the ``jit_check_enabled`` knob
(``RAY_TPU_JIT_CHECK_ENABLED=1`` — the env form propagates to spawned
cluster processes; ``ray_tpu/__init__`` installs at the very top of the
package import, mirroring lockcheck/leakcheck, so module-level jits are
stamped too). ``tests/conftest.py`` adds an autouse guard that fails any
test during which a steady-state violation was recorded.

Caveats (by design):

- jits constructed BEFORE install (jax-internal, third-party library
  jits) still have their compiles counted, attributed to
  ``<untracked>``.
- The abstract signature is computed only for calls that actually
  compiled — signatures are read off the operands lazily, so the
  per-call overhead of an installed-but-idle jitcheck is one thread-
  local push/pop and an integer read.
- Implicit reads through APIs that bypass the wrapped dunders
  (``memoryview``, buffer-protocol C extensions) are caught on real
  devices by the transfer guard, not on CPU.
"""

from __future__ import annotations

import contextlib
import os
import threading
import traceback
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "install", "uninstall", "installed", "maybe_install",
    "steady_state", "SteadyStateViolation",
    "violations", "clear_violations",
    "compile_counts", "compile_seconds_by_site",
    "total_compiles", "total_compile_seconds",
]

_ENV_KNOB = "RAY_TPU_JIT_CHECK_ENABLED"

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

#: guards every module-global table below (leaf lock: nothing is acquired
#: under it and it is never held across user code)
_lock = threading.Lock()

#: (site, abstract signature) -> number of XLA compiles observed
_compiles: Dict[Tuple[str, str], int] = {}
#: site -> cumulative XLA compile seconds
_compile_seconds: Dict[str, float] = {}
_total_compiles = 0
_total_compile_seconds = 0.0

#: recorded steady-state violations (compiles / implicit reads), rendered
_violations: List[str] = []

_tls = threading.local()

_installed = False
_listener_registered = False

_REAL_JIT = None
_REAL_DEVICE_GET = None
_REAL_ARRAY_METHODS: Dict[str, Any] = {}

#: implicit-read dunders guarded inside steady_state
_GUARDED_READS = ("__array__", "__float__", "__int__", "__bool__",
                  "__index__", "item")


class SteadyStateViolation(AssertionError):
    """A steady-state scope saw a new XLA compilation or an implicit
    device→host read (raised at scope exit when ``strict=True``)."""


def _caller_site() -> str:
    """file:line of the first stack frame outside this module and outside
    jax/numpy internals — the user code that triggered the event."""
    here = os.path.normcase(__file__)
    for frame in traceback.extract_stack()[::-1]:
        fn = os.path.normcase(frame.filename)
        if fn == here:
            continue
        parts = fn.replace(os.sep, "/").split("/")
        if "jax" in parts or "jaxlib" in parts or "numpy" in parts:
            continue
        return f"{os.path.basename(fn)}:{frame.lineno} in {frame.name}"
    return "<unknown>"


def _site_stack() -> List[str]:
    st = getattr(_tls, "sites", None)
    if st is None:
        st = _tls.sites = []
    return st


def _steady_depth() -> int:
    return getattr(_tls, "steady", 0)


def _reads_allowed() -> bool:
    return getattr(_tls, "allow_reads", 0) > 0


def _record_violation(text: str) -> None:
    with _lock:
        _violations.append(text)
    try:
        from ray_tpu.util import flightrec

        flightrec.record("jit", "steady_state", text)
    # raylint: ignore[swallowed-exception] — deliberate: flight-recorder
    # unavailability must never break the guarded operation
    except Exception:  # noqa: BLE001
        pass


def _abstract_sig(args: tuple, kwargs: dict) -> str:
    """Short dtype[shape] rendering of the call's array operands."""
    parts: List[str] = []

    def leaf(x: Any) -> None:
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{dtype}[{','.join(str(d) for d in shape)}]")
        elif isinstance(x, (int, float, bool)):
            parts.append(repr(x))

    try:
        import jax

        for leafval in jax.tree_util.tree_leaves((args, kwargs)):
            leaf(leafval)
            if len(parts) >= 16:  # keep fingerprints bounded
                parts.append("...")
                break
    except Exception:  # noqa: BLE001 — a sig failure must not break the call
        return "<unavailable>"
    return f"({', '.join(parts)})"


# -- compile accounting ------------------------------------------------------


def _on_duration_event(name: str, dur: float, **_kw) -> None:
    global _total_compiles, _total_compile_seconds
    if not _installed or name != _COMPILE_EVENT:
        return
    sites = _site_stack()
    site = sites[-1] if sites else "<untracked>"
    with _lock:
        _total_compiles += 1
        _total_compile_seconds += dur
        _compile_seconds[site] = _compile_seconds.get(site, 0.0) + dur
    try:
        from ray_tpu.util import flightrec

        flightrec.record("jit", site, f"compile {dur * 1e3:.1f}ms")
    # raylint: ignore[swallowed-exception] — deliberate: observability is
    # best-effort; a metrics/flightrec failure must not fail the compile
    except Exception:  # noqa: BLE001
        pass
    try:
        from ray_tpu.core.metrics_export import (jit_compile_seconds_total,
                                                 jit_compiles_total,
                                                 metrics_enabled)

        if metrics_enabled():
            jit_compiles_total().inc(1, {"site": site})
            jit_compile_seconds_total().inc(dur, {"site": site})
    # raylint: ignore[swallowed-exception] — deliberate: observability is
    # best-effort; a metrics/flightrec failure must not fail the compile
    except Exception:  # noqa: BLE001
        pass
    if _steady_depth() > 0:
        _record_violation(
            f"XLA compilation inside steady_state (site {site}, "
            f"{dur * 1e3:.1f}ms) — every program must be compiled at warmup")


class _TrackedJit:
    """A jitted callable stamped with its construction site. Calls run with
    the site on a thread-local stack (compile attribution); attribute
    access (``lower``/``trace``/``eval_shape``/…) passes through."""

    __slots__ = ("_jitted", "_site", "__dict__")

    def __init__(self, jitted: Any, site: str):
        self._jitted = jitted
        self._site = site
        for attr in ("__name__", "__qualname__", "__doc__", "__wrapped__"):
            try:
                object.__setattr__(self, "__dict__", self.__dict__)
                self.__dict__[attr] = getattr(jitted, attr)
            except AttributeError:
                pass

    def __call__(self, *args, **kwargs):
        global _total_compiles
        sites = _site_stack()
        sites.append(self._site)
        n0 = _total_compiles
        try:
            return self._jitted(*args, **kwargs)
        finally:
            sites.pop()
            if _total_compiles > n0:
                key = (self._site, _abstract_sig(args, kwargs))
                with _lock:
                    _compiles[key] = _compiles.get(key, 0) + 1

    def __getattr__(self, name: str):
        return getattr(self._jitted, name)

    def __repr__(self) -> str:
        return f"<jitcheck-tracked {self._jitted!r} from {self._site}>"


def _jit(fun=None, *args, **kwargs):
    site = _caller_site()
    if fun is None:
        # jax.jit(static_argnums=...) partial form: defer, stamp on apply.
        def apply(f):
            return _TrackedJit(_REAL_JIT(f, *args, **kwargs), site)

        return apply
    return _TrackedJit(_REAL_JIT(fun, *args, **kwargs), site)


# -- implicit-read guard -----------------------------------------------------


def _guarded(name: str, orig):
    def guard(self, *args, **kwargs):
        if _steady_depth() > 0 and not _reads_allowed():
            _record_violation(
                f"implicit device->host read ({name}) inside steady_state "
                f"at {_caller_site()} — use jax.device_get")
        return orig(self, *args, **kwargs)

    guard.__name__ = name
    return guard


def _device_get(x):
    _tls.allow_reads = getattr(_tls, "allow_reads", 0) + 1
    try:
        return _REAL_DEVICE_GET(x)
    finally:
        _tls.allow_reads -= 1


# -- install / uninstall -----------------------------------------------------


def install() -> None:
    """Stamp jit sites, count compiles, arm the steady-state guard.
    Idempotent."""
    global _installed, _listener_registered, _REAL_JIT, _REAL_DEVICE_GET
    if _installed:
        return
    import jax
    import jax.monitoring
    from jax._src import array as _jarray

    _REAL_JIT = jax.jit
    _REAL_DEVICE_GET = jax.device_get
    jax.jit = _jit
    jax.device_get = _device_get
    for name in _GUARDED_READS:
        orig = getattr(_jarray.ArrayImpl, name, None)
        if orig is None:
            continue
        _REAL_ARRAY_METHODS[name] = orig
        setattr(_jarray.ArrayImpl, name, _guarded(name, orig))
    if not _listener_registered:
        # jax.monitoring has no per-listener unregister; register once and
        # gate on _installed so uninstall/reinstall never double-counts.
        jax.monitoring.register_event_duration_secs_listener(
            _on_duration_event)
        _listener_registered = True
    _installed = True


def uninstall() -> None:
    global _installed
    if not _installed:
        return
    import jax
    from jax._src import array as _jarray

    jax.jit = _REAL_JIT
    jax.device_get = _REAL_DEVICE_GET
    for name, orig in _REAL_ARRAY_METHODS.items():
        setattr(_jarray.ArrayImpl, name, orig)
    _REAL_ARRAY_METHODS.clear()
    _installed = False


def installed() -> bool:
    return _installed


def maybe_install() -> bool:
    """Install iff the ``jit_check_enabled`` knob is on (env var first —
    process entry points run before the config table exists)."""
    on = os.environ.get(_ENV_KNOB)
    if on is not None:
        enabled = on.lower() in ("1", "true", "yes", "on")
    else:
        try:
            from ray_tpu.core.config import config

            enabled = config().jit_check_enabled
        except Exception:  # noqa: BLE001 — config unavailable: stay off
            enabled = False
    if enabled:
        install()
    return enabled


# -- steady state ------------------------------------------------------------


@contextlib.contextmanager
def steady_state(strict: bool = False):
    """Scope in which new XLA compilations and implicit device→host reads
    are contract violations. Thread-local and reentrant; a no-op unless
    :func:`install` ran. Violations are recorded in :func:`violations`
    (tests fail via the conftest guard); with ``strict=True`` the scope
    ALSO raises :class:`SteadyStateViolation` at exit."""
    if not _installed:
        yield
        return
    import jax

    with _lock:
        n0 = len(_violations)
    _tls.steady = _steady_depth() + 1
    try:
        with jax.transfer_guard_device_to_host("disallow"):
            yield
    finally:
        _tls.steady -= 1
        if strict:
            with _lock:
                new = _violations[n0:]
            if new:
                raise SteadyStateViolation(
                    "steady-state contract violated:\n  " + "\n  ".join(new))


# -- introspection -----------------------------------------------------------


def violations() -> List[str]:
    with _lock:
        return list(_violations)


def clear_violations() -> None:
    with _lock:
        _violations.clear()


def compile_counts() -> Dict[Tuple[str, str], int]:
    """(site, abstract signature) -> compiles observed through tracked
    jits. Untracked compiles appear only in :func:`total_compiles`."""
    with _lock:
        return dict(_compiles)


def compile_seconds_by_site() -> Dict[str, float]:
    with _lock:
        return dict(_compile_seconds)


def total_compiles() -> int:
    return _total_compiles


def total_compile_seconds() -> float:
    return _total_compile_seconds


def reset_counters() -> None:
    """Zero the compile tables (bench harness bookkeeping)."""
    global _total_compiles, _total_compile_seconds
    with _lock:
        _compiles.clear()
        _compile_seconds.clear()
        _total_compiles = 0
        _total_compile_seconds = 0.0
