"""Pipeline parallelism — GPipe microbatch schedule over a mesh axis.

The reference delegates PP to DeepSpeed/Accelerate (SURVEY §2.4) and offers
only the compiled-DAG primitive (``python/ray/dag/compiled_dag_node.py``) for
cross-actor pipelining. TPU-native, the pipeline is a mesh axis: every device
holds one stage's parameters (leading ``layers`` dim sharded on ``pipe``),
activations hand off to the next stage via ``ppermute`` each tick, and the
whole schedule is one compiled XLA program — no per-tick host round-trips.

Schedule: classic GPipe fill-drain. For M microbatches on S stages the loop
runs M + S - 1 ticks; at tick t stage 0 ingests microbatch t (if any) and
stage S-1 emits microbatch t - (S - 1).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def make_pipeline(
    stage_fn: Callable,
    mesh: Mesh,
    *,
    num_microbatches: int,
    pipe_axis: str = "pipe",
    batch_axes=("data", "fsdp"),
):
    """Build a jittable pipelined forward pass.

    ``stage_fn(stage_params, x) -> y`` is the per-stage computation; activations
    must have the same shape as inputs (transformer blocks qualify).

    Arguments to the returned function:
    - ``stage_params``: pytree whose leaves have leading dim = n_stages,
      sharded on ``pipe_axis``.
    - ``x``: [num_microbatches, microbatch, ...] input, replicated over pipe.

    Returns [num_microbatches, microbatch, ...] outputs (replicated over pipe).
    """
    n_stages = mesh.shape[pipe_axis]
    ticks = num_microbatches + n_stages - 1

    def body(stage_params, x):
        # Local leaves have leading dim 1 (our stage); drop it.
        params = jax.tree.map(lambda p: jnp.squeeze(p, axis=0), stage_params)
        stage = lax.axis_index(pipe_axis)
        is_first = stage == 0
        is_last = stage == n_stages - 1
        mb_shape = x.shape[1:]

        out0 = jnp.zeros_like(x)
        carry0 = jnp.zeros(mb_shape, x.dtype)  # activation arriving this tick

        def tick(t, state):
            carry, out = state
            mb_index = jnp.clip(t, 0, num_microbatches - 1)
            fresh = lax.dynamic_index_in_dim(x, mb_index, axis=0, keepdims=False)
            inp = jnp.where(is_first, fresh, carry)
            y = stage_fn(params, inp)
            # Only ticks where this stage holds live data matter; dead ticks
            # compute garbage that is never written out (fill/drain bubbles).
            done_index = t - (n_stages - 1)
            write = jnp.logical_and(is_last, done_index >= 0)
            out = lax.cond(
                write,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.clip(done_index, 0, num_microbatches - 1), axis=0
                ),
                lambda o: o,
                out,
            )
            perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
            carry_next = lax.ppermute(y, pipe_axis, perm)
            return carry_next, out

        _, out = lax.fori_loop(0, ticks, tick, (carry0, out0))
        # Output lives on the last stage only; psum replicates it (all other
        # stages contribute zeros).
        return lax.psum(out, pipe_axis)

    param_spec = P(pipe_axis)
    x_spec = P(None, batch_axes)

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
