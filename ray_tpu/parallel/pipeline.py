"""Pipeline parallelism — differentiable GPipe microbatch schedule on a mesh axis.

The reference delegates PP to DeepSpeed/Accelerate (SURVEY §2.4) and offers
only the compiled-DAG primitive (``python/ray/dag/compiled_dag_node.py``) for
cross-actor pipelining. TPU-native, the pipeline is a mesh axis: every device
group holds one stage's layer stack (leading ``layers`` dim sharded on
``pipe``), activations hand off to the next stage via ``ppermute`` each tick,
and the whole schedule — forward AND backward — is one compiled XLA program
with no per-tick host round-trips. Reverse-mode AD flows through the
schedule: the tick loop is a ``lax.scan`` (checkpointable, transposable) and
``ppermute``'s transpose is the reversed permutation, which IS the backward
pipeline.

Schedule: classic GPipe fill-drain. For M microbatches on S stages the loop
runs M + S - 1 ticks; at tick t stage 0 ingests microbatch t (if any) and
stage S-1 emits microbatch t - (S - 1).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def make_pipeline(
    layer_fn: Callable,
    mesh: Mesh,
    *,
    num_microbatches: int,
    pipe_axis: str = "pipe",
    batch_axes=("data", "fsdp"),
    remat: bool = False,
):
    """Build a jittable, DIFFERENTIABLE pipelined forward pass.

    ``layer_fn(layer_params, x) -> y`` is the per-LAYER computation;
    activations must keep the input shape (transformer blocks qualify).

    Arguments to the returned function:
    - ``layer_params``: pytree whose leaves have leading dim = total layers
      L (sharded on ``pipe_axis``; L must divide evenly into the stage
      count). Each stage scans its local L/S layers per tick.
    - ``x``: [num_microbatches, microbatch, ...] input, replicated over pipe.

    Returns [num_microbatches, microbatch, ...] outputs (replicated over
    pipe). ``jax.grad`` through the result differentiates the whole
    schedule.
    """
    n_stages = mesh.shape[pipe_axis]
    ticks = num_microbatches + n_stages - 1
    fn = jax.checkpoint(layer_fn) if remat else layer_fn

    def body(layer_params, x):
        stage = lax.axis_index(pipe_axis)
        is_first = stage == 0
        is_last = stage == n_stages - 1

        def apply_stage(inp):
            def one(h, lp):
                return fn(lp, h), None

            h, _ = lax.scan(one, inp, layer_params)
            return h

        out0 = jnp.zeros_like(x)
        carry0 = jnp.zeros(x.shape[1:], x.dtype)

        def tick(state, t):
            carry, out = state
            mb_index = jnp.clip(t, 0, num_microbatches - 1)
            fresh = lax.dynamic_index_in_dim(x, mb_index, axis=0,
                                             keepdims=False)
            inp = jnp.where(is_first, fresh, carry)
            y = apply_stage(inp)
            # Only ticks where the LAST stage holds live data write output;
            # fill/drain bubbles compute garbage that is never read (and
            # therefore receives zero cotangent on the backward pass).
            done_index = t - (n_stages - 1)
            write = jnp.logical_and(is_last, done_index >= 0)
            written = lax.dynamic_update_index_in_dim(
                out, y, jnp.clip(done_index, 0, num_microbatches - 1), axis=0)
            out = jnp.where(write, written, out)
            perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
            carry_next = lax.ppermute(y, pipe_axis, perm)
            return (carry_next, out), None

        (_, out), _ = lax.scan(tick, (carry0, out0), jnp.arange(ticks))
        # Output lives on the last stage only; psum replicates it (all other
        # stages contribute zeros).
        return lax.psum(out, pipe_axis)

    param_spec = P(pipe_axis)
    x_spec = P(None, batch_axes)

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_spec, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
