"""Pipeline parallelism — differentiable GPipe microbatch schedule on a mesh axis.

The reference delegates PP to DeepSpeed/Accelerate (SURVEY §2.4) and offers
only the compiled-DAG primitive (``python/ray/dag/compiled_dag_node.py``) for
cross-actor pipelining. TPU-native, the pipeline is a mesh axis: every device
group holds one stage's layer stack (leading ``layers`` dim sharded on
``pipe``), activations hand off to the next stage via ``ppermute`` each tick,
and the whole schedule — forward AND backward — is one compiled XLA program
with no per-tick host round-trips. Reverse-mode AD flows through the
schedule: the tick loop is a ``lax.scan`` (checkpointable, transposable) and
``ppermute``'s transpose is the reversed permutation, which IS the backward
pipeline.

Schedule: classic GPipe fill-drain. For M microbatches on S stages the loop
runs M + S - 1 ticks; at tick t stage 0 ingests microbatch t (if any) and
stage S-1 emits microbatch t - (S - 1).

Input layout: ``x`` is ``[microbatch, num_microbatches, ...]`` — microbatch
members on the LEADING (batch-sharded) dim, the microbatch *index* trailing
it. This ordering matters: reshaping a batch-dim-sharded ``[B, ...]``
activation into ``[B/M, M, ...]`` splits each device's contiguous row block
in place (pure relabeling, zero data movement), whereas the transposed
``[M, B/M, ...]`` layout scatters every device's rows across microbatch
slots — the SPMD partitioner can only realize that as replicate-then-
repartition ("involuntary full rematerialization", a full activation
all-gather per step). The body transposes to schedule order locally
(device-local swapaxes — free of collectives).

The per-layer body runs under ``shard_map`` over the FULL mesh, so it can
compose tensor parallelism (``lax.psum`` over the tensor axis) and ring
attention (``lax.ppermute`` over the seq axis) inside the pipeline —
pipe×seq×tensor×(data/fsdp) in one jitted step.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def make_pipeline(
    layer_fn: Callable,
    mesh: Mesh,
    *,
    num_microbatches: int,
    pipe_axis: str = "pipe",
    batch_axes=("data", "fsdp"),
    x_spec: Optional[P] = None,
    param_specs=None,
    remat: bool = False,
):
    """Build a jittable, DIFFERENTIABLE pipelined forward pass.

    ``layer_fn(layer_params, x) -> y`` is the per-LAYER computation on
    PER-DEVICE local blocks; activations must keep the input shape
    (transformer blocks qualify). It may use mesh collectives (``psum`` on
    the tensor axis, ``ppermute`` on the seq axis) — it runs inside the
    pipeline's ``shard_map``.

    Arguments to the returned function:
    - ``layer_params``: pytree whose leaves have leading dim = total layers
      L (sharded on ``pipe_axis``; L must divide evenly into the stage
      count). Each stage scans its local L/S layers per tick.
      ``param_specs`` (optional pytree of PartitionSpec) shards the
      remaining dims too (tensor-parallel weights); default ``P(pipe)``.
    - ``x``: ``[microbatch, num_microbatches, ...]`` input (see module
      docstring for why the microbatch index trails). ``x_spec`` overrides
      the default ``P(batch_axes, None)`` — pass e.g.
      ``P(batch_axes, None, "seq", None)`` for sequence-parallel
      activations.

    Returns outputs in the same layout/sharding as ``x``. ``jax.grad``
    through the result differentiates the whole schedule.
    """
    n_stages = mesh.shape[pipe_axis]
    ticks = num_microbatches + n_stages - 1
    fn = jax.checkpoint(layer_fn) if remat else layer_fn

    def body(layer_params, x):
        stage = lax.axis_index(pipe_axis)
        is_first = stage == 0
        is_last = stage == n_stages - 1

        # Local reorder to schedule layout [num_micro, mb_local, ...]:
        # a device-local transpose, no collectives.
        xt = jnp.swapaxes(x, 0, 1)

        def apply_stage(inp):
            def one(h, lp):
                return fn(lp, h), None

            h, _ = lax.scan(one, inp, layer_params)
            return h

        out0 = jnp.zeros_like(xt)
        carry0 = jnp.zeros(xt.shape[1:], xt.dtype)

        def tick(state, t):
            carry, out = state
            mb_index = jnp.clip(t, 0, num_microbatches - 1)
            fresh = lax.dynamic_index_in_dim(xt, mb_index, axis=0,
                                             keepdims=False)
            inp = jnp.where(is_first, fresh, carry)
            y = apply_stage(inp)
            # Only ticks where the LAST stage holds live data write output;
            # fill/drain bubbles compute garbage that is never read (and
            # therefore receives zero cotangent on the backward pass).
            done_index = t - (n_stages - 1)
            write = jnp.logical_and(is_last, done_index >= 0)
            written = lax.dynamic_update_index_in_dim(
                out, y, jnp.clip(done_index, 0, num_microbatches - 1), axis=0)
            out = jnp.where(write, written, out)
            perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
            carry_next = lax.ppermute(y, pipe_axis, perm)
            return (carry_next, out), None

        (_, out), _ = lax.scan(tick, (carry0, out0), jnp.arange(ticks))
        # Output lives on the last stage only; psum replicates it (all other
        # stages contribute zeros).
        return jnp.swapaxes(lax.psum(out, pipe_axis), 0, 1)

    if param_specs is None:
        param_specs = P(pipe_axis)
    if x_spec is None:
        x_spec = P(batch_axes, None)

    return jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )
