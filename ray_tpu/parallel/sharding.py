"""Logical-axis sharding rules: model code names *logical* axes, a rule table
maps them onto mesh axes.

This is the TPU-native replacement for everything the reference delegates to
DeepSpeed/Megatron (SURVEY §2.4: TP/PP/SP "not implemented in Ray" — reached
only via launched frameworks). Model parameters and activations are annotated
with logical axis names (``("embed", "mlp")``); a ``ShardingRules`` table maps
each logical name to a mesh axis (or None = replicate); ``jax.jit`` +
``NamedSharding`` then compiles in all collectives.

Default rules implement the standard megatron/fsdp recipe:
- ``vocab``/``mlp``/``heads`` → ``tensor`` (column/row parallel matmuls)
- ``embed`` → ``fsdp`` (parameter sharding, all-gathered on use)
- ``batch`` → ``data``+``fsdp`` (per-device batch)
- ``seq_act`` → ``seq`` (sequence/context parallelism for activations)
- ``layers`` → ``pipe`` (pipeline stage stacking)
- ``experts`` → ``expert``
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Dict, Optional, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

MeshAxis = Union[str, Tuple[str, ...], None]


@dataclass(frozen=True)
class ShardingRules:
    batch: MeshAxis = ("data", "fsdp")
    seq_act: MeshAxis = "seq"          # activation sequence dim
    embed: MeshAxis = "fsdp"           # parameter d_model dim (fsdp-sharded)
    mlp: MeshAxis = "tensor"           # ffn hidden dim
    heads: MeshAxis = "tensor"         # attention heads
    kv_heads: MeshAxis = "tensor"
    vocab: MeshAxis = "tensor"
    head_dim: MeshAxis = None
    layers: MeshAxis = "pipe"
    experts: MeshAxis = "expert"
    unsharded: MeshAxis = None

    def mesh_axes(self, logical: Optional[Tuple[Optional[str], ...]]) -> PartitionSpec:
        """Translate a tuple of logical names to a PartitionSpec."""
        if logical is None:
            return PartitionSpec()
        out = []
        for name in logical:
            if name is None:
                out.append(None)
            else:
                if not hasattr(self, name):
                    raise ValueError(f"unknown logical axis '{name}'")
                out.append(getattr(self, name))
        return PartitionSpec(*out)

    def update(self, **kwargs) -> "ShardingRules":
        return replace(self, **kwargs)


# Rule presets for common topologies.
DP_ONLY = ShardingRules(
    batch="data", seq_act=None, embed=None, mlp=None, heads=None,
    kv_heads=None, vocab=None, layers=None, experts=None,
)
FSDP = ShardingRules(
    batch=("data", "fsdp"), seq_act=None, mlp=None, heads=None,
    kv_heads=None, vocab=None, layers=None, experts=None,
)


def logical_sharding(
    mesh: Mesh, rules: ShardingRules, logical: Optional[Tuple[Optional[str], ...]]
) -> NamedSharding:
    spec = rules.mesh_axes(logical)
    # Drop mesh axes the array dim isn't divisible by? No — surface the error;
    # divisibility is a model-config contract (pad vocab etc.).
    return NamedSharding(mesh, spec)


def shard_pytree(tree, logical_tree, mesh: Mesh, rules: ShardingRules):
    """Device-put a pytree of arrays under its logical annotations.

    ``logical_tree`` mirrors ``tree`` with tuples of logical axis names (or
    None) at the leaves.
    """

    def place(x, logical):
        return jax.device_put(x, logical_sharding(mesh, rules, logical))

    return jax.tree.map(place, tree, logical_tree,
                        is_leaf=lambda x: x is None)


def pytree_shardings(logical_tree, mesh: Mesh, rules: ShardingRules):
    """NamedSharding pytree (for jit in_shardings/out_shardings)."""
    return jax.tree.map(
        lambda logical: logical_sharding(mesh, rules, logical),
        logical_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(isinstance(e, (str, type(None))) for e in x)),
    )


def constrain(x, mesh: Mesh, rules: ShardingRules, logical: Tuple[Optional[str], ...]):
    """with_sharding_constraint under logical names (inside jit)."""
    return jax.lax.with_sharding_constraint(x, logical_sharding(mesh, rules, logical))
