"""Device meshes — the substrate for every parallelism axis.

This replaces the reference's process-group plumbing (torch
``init_process_group`` in ``python/ray/train/torch/config.py:64-100``, NCCL
groups in ``python/ray/util/collective/``) with the TPU-native model: a single
`jax.sharding.Mesh` whose named axes carry all parallelism dimensions —

- ``data``    data parallelism (gradient psum)
- ``fsdp``    parameter-sharded data parallelism (reduce_scatter/all_gather)
- ``tensor``  tensor/model parallelism (megatron-style row/col sharding)
- ``seq``     sequence/context parallelism (ring attention over ICI neighbors)
- ``pipe``    pipeline parallelism (ppermute stage handoff)
- ``expert``  expert parallelism (all_to_all token routing)

Axis ORDER matters on hardware: the innermost axes map to the
torus-contiguous ICI dimensions, so ``tensor``/``seq`` (latency-sensitive
collectives) sit innermost and ``data`` (bandwidth-tolerant psum) outermost,
possibly spanning DCN between slices — the scaling-book recipe.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Shared tier vocabulary with the control plane: the gang scheduler scores
# placements by how many bundle pairs are forced onto TIER_DCN, using the
# same two names these axis assignments use. Defined in core (jax-free) so
# the GCS process can import it; re-exported here for mesh-side callers.
from ray_tpu.core.resources import TIER_DCN, TIER_ICI

# Canonical axis order, outermost → innermost (DCN-tolerant → ICI-hungry).
AXIS_ORDER = ("data", "fsdp", "expert", "pipe", "seq", "tensor")

# Fabric tier of each canonical axis: ``data``/``fsdp`` collectives are
# bandwidth-bound and overlappable, so those axes may span the slow
# inter-slice DCN; every inner axis demands single-slice ICI latency. The
# eager host collectives mirror this two-level split at the process level
# (``ray_tpu.parallel.collectives``: intra-node shm tier + inter-node ring).
AXIS_TIER = {"data": TIER_DCN, "fsdp": TIER_DCN, "expert": TIER_ICI,
             "pipe": TIER_ICI, "seq": TIER_ICI, "tensor": TIER_ICI}


@dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name → size (1 = absent).

    ``MeshSpec(data=2, tensor=4)`` on 8 chips ≡ a (2, 4) mesh. Size ``-1``
    on at most one axis means "fill with remaining devices".
    """

    data: int = 1
    fsdp: int = 1
    expert: int = 1
    pipe: int = 1
    seq: int = 1
    tensor: int = 1

    def sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> "MeshSpec":
        sizes = self.sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one axis may be -1")
        known = int(np.prod([s for s in sizes.values() if s != -1]))
        if wild:
            if n_devices % known:
                raise ValueError(
                    f"cannot fill axis {wild[0]}: {n_devices} devices not divisible by {known}"
                )
            sizes[wild[0]] = n_devices // known
            known = n_devices
        if known != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {known} devices but {n_devices} provided"
            )
        return MeshSpec(**sizes)

    def axis_names(self) -> List[str]:
        return [a for a in AXIS_ORDER if self.sizes()[a] > 1]


def best_devices(n: Optional[int] = None) -> List[jax.Device]:
    """All devices of the best available platform (TPU > CPU)."""
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if not devs:
        devs = jax.devices("cpu")
    if n is not None:
        if len(devs) < n:
            cpu = jax.devices("cpu")
            if len(cpu) >= n:
                devs = cpu  # virtual CPU mesh (tests / dryrun)
            else:
                raise ValueError(f"need {n} devices, have {len(devs)} "
                                 f"(cpu: {len(cpu)})")
        devs = devs[:n]
    return devs


def make_mesh(
    spec: MeshSpec | Dict[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh with canonical axis ordering.

    All six canonical axes are always present (size-1 axes included), so
    sharding rules can name any axis regardless of the active topology —
    size-1 axes cost nothing under XLA.
    """
    if isinstance(spec, dict):
        spec = MeshSpec(**spec)
    devices = list(devices) if devices is not None else best_devices()
    spec = (spec or MeshSpec(data=-1)).resolve(len(devices))
    sizes = spec.sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXIS_ORDER)


def cpu_mesh(spec: MeshSpec | Dict[str, int] | None = None, n: Optional[int] = None) -> Mesh:
    """A virtual CPU mesh for tests and multi-chip dry runs.

    With ``n=None`` the device count is inferred from the spec (fully
    specified spec → its product; wildcard spec → all CPU devices).
    """
    if isinstance(spec, dict):
        spec = MeshSpec(**spec)
    devices = jax.devices("cpu")
    if n is None and spec is not None:
        sizes = spec.sizes().values()
        if -1 not in sizes:
            n = int(np.prod(list(sizes)))
    return make_mesh(spec, devices[:n] if n else devices)


def mesh_shape(mesh: Mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def dp_axes(mesh: Mesh) -> tuple:
    """Axes over which gradients are reduced (data + fsdp)."""
    return tuple(a for a in ("data", "fsdp") if a in mesh.axis_names)


def hierarchy_split(mesh: Mesh) -> Tuple[tuple, tuple]:
    """(dcn_axes, ici_axes) among the mesh's ACTIVE (size>1) axes.

    The compiled-path statement of the same two-level schedule the eager
    collectives run on hosts: reduce over the ICI axes first (fast, inside
    a slice), cross the DCN tier once with the already-reduced partials.
    """
    active = [a for a, s in mesh_shape(mesh).items() if s > 1]
    return (tuple(a for a in active if AXIS_TIER.get(a) == "dcn"),
            tuple(a for a in active if AXIS_TIER.get(a) != "dcn"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
