"""Ring attention — sequence/context parallelism over ICI neighbors.

Absent from the reference entirely (SURVEY §5.7: no ring attention, Ulysses,
or context-parallel code anywhere in it); on TPU it is first-class: the
sequence dimension is a mesh axis, K/V blocks rotate around the ``seq`` ring
via ``ppermute`` (which XLA overlaps with the per-block attention compute on
ICI), and softmax is accumulated online (log-sum-exp), so attention over a
sequence of length L runs on P devices each holding L/P — exact, not
approximate.

Also provides Ulysses-style all-to-all sequence parallelism: swap the
sharded axis from sequence to heads, run local full attention, swap back —
the better choice when head count ≥ ring size and DCN spans make ppermute
latency-bound.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _block_attention(q, k, v, *, scale, mask):
    """One (q-block, kv-block) attention contribution in f32.

    q: [B, Lq, H, D]  k/v: [B, Lk, H, D]  mask: [Lq, Lk] additive or None.
    Returns (scores_max [B,H,Lq], exp_scores [B,H,Lq,Lk], pv [B,H,Lq,D]).
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    scores = scores * scale
    if mask is not None:
        scores = scores + mask[None, None, :, :]
    block_max = jnp.max(scores, axis=-1)
    exp_scores = jnp.exp(scores - block_max[..., None])
    pv = jnp.einsum("bhqk,bkhd->bhqd", exp_scores, v.astype(jnp.float32))
    return block_max, exp_scores, pv


def _ring_attention_local(q, k, v, *, axis_name: str, axis_size: int, causal: bool, scale: Optional[float]):
    """Per-device body under shard_map. Shapes are local blocks:
    q [B, Lq, H, D], k/v [B, Lk, H, D], sharded along L on ``axis_name``."""
    orig_dtype = q.dtype
    b, lq, h, d = q.shape
    lk = k.shape[1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    my_idx = lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32)
    m0 = jnp.full((b, h, lq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, lq), jnp.float32)
    o0 = jnp.zeros((b, h, lq, d), jnp.float32)

    q_pos = my_idx * lq + jnp.arange(lq)

    def step(i, carry):
        m, l, o, k_cur, v_cur = carry
        src_idx = (my_idx - i) % axis_size  # which device this kv came from
        if causal:
            k_pos = src_idx * lk + jnp.arange(lk)
            mask = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, _NEG_INF)
        else:
            mask = None
        block_max, exp_scores, pv = _block_attention(
            q32, k_cur, v_cur, scale=scale, mask=mask
        )
        new_m = jnp.maximum(m, block_max)
        # Guard fully-masked blocks: block_max = NEG_INF there; keep exact 0
        # contribution without NaNs from (-inf) - (-inf).
        corr = jnp.exp(m - new_m)
        block_corr = jnp.exp(block_max - new_m)
        l_new = l * corr + jnp.sum(exp_scores, axis=-1) * block_corr
        o_new = o * corr[..., None] + pv * block_corr[..., None]
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return new_m, l_new, o_new, k_next, v_next

    m, l, o, _, _ = lax.fori_loop(0, axis_size, step, (m0, l0, o0, k, v))
    # Rows with zero mass (fully masked everywhere) produce 0, not NaN.
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(orig_dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = "seq",
    batch_axes=("data", "fsdp"),
    head_axis: str = "tensor",
):
    """Build a jittable ring-attention over ``mesh``.

    Input/output layout: [batch, seq, heads, head_dim] with batch sharded on
    ``batch_axes``, seq on ``seq_axis`` and heads on ``head_axis`` (heads and
    ring compose: each device holds a (seq-block × head-group)).
    """
    axis_size = mesh.shape[seq_axis]
    spec = P(batch_axes, seq_axis, head_axis, None)
    body = functools.partial(
        _ring_attention_local,
        axis_name=seq_axis,
        axis_size=axis_size,
        causal=causal,
        scale=scale,
    )
    return jax.shard_map(
        lambda q, k, v: body(q, k, v),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )


def reference_attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None):
    """Plain full attention (single device) — numerical oracle for tests."""
    b, l, h, d = q.shape
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        mask = jnp.tril(jnp.ones((l, l), bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def make_ulysses_attention(
    mesh: Mesh,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    seq_axis: str = "seq",
    batch_axes=("data", "fsdp"),
):
    """Ulysses sequence parallelism: all_to_all swaps the sharded dim from
    sequence to heads, each device runs full-sequence attention on its head
    group, and a second all_to_all swaps back. Requires heads % ring == 0."""
    axis_size = mesh.shape[seq_axis]
    spec = P(batch_axes, seq_axis, None, None)

    def body(q, k, v):
        # local [B, L/P, H, D] -> [B, L, H/P, D]
        def seq_to_heads(x):
            return lax.all_to_all(x, seq_axis, split_axis=2, concat_axis=1, tiled=True)

        def heads_to_seq(x):
            return lax.all_to_all(x, seq_axis, split_axis=1, concat_axis=2, tiled=True)

        qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        out = reference_attention(qh, kh, vh, causal=causal, scale=scale)
        return heads_to_seq(out)

    return jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
