"""Eager actor-level collectives — the §5.8 API contract.

Analog of the reference's ``ray.util.collective``
(``python/ray/util/collective/collective.py`` — ``init_collective_group``
:120, ``create_collective_group`` :151, ``allreduce`` :258, ``barrier`` :298,
``broadcast`` :373, ``allgather`` :423, ``reducescatter`` :472, ``send``
:531 / ``recv`` :594) re-based for the TPU world:

- **Compiled path (the fast path):** device tensors inside a jitted program
  use XLA collectives over ICI (``psum``/``all_gather``/...) — that path
  lives in the mesh/sharding layer, not here.
- **Eager path (this module):** host-side arrays exchanged between actors in
  a named group — rendezvous through the runtime's control plane exactly the
  way the reference rendezvouses NCCL unique ids through its KV store
  (``nccl_collective_group.py``). The local backend synchronizes ranks with
  barriers and reduces with numpy; it is the Gloo analog and the test
  substrate for multi-host DCN collectives.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.core.runtime import get_runtime
from ray_tpu.utils.logging import get_logger

logger = get_logger("collectives")

_REDUCE_OPS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "prod": lambda arrs: np.prod(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
}


def _device_allreduce(slots: Dict[int, "object"], op: str, world: int):
    """Compiled allreduce over the DEVICES the ranks' arrays already live
    on: a 1-D mesh is built from those devices, the per-rank buffers are
    assembled into one global array (``make_array_from_single_device_
    arrays`` — no host round trip), and a jitted ``shard_map`` psum/pmax/
    pmin reduces over the mesh axis. Each rank gets its result shard back
    ON ITS OWN DEVICE — the single-host multi-chip tier of §5.8 (the
    NCCL-group analog; on TPU hardware the reduction rides ICI)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    ranks = sorted(slots)
    arrs = [slots[r] for r in ranks]
    devices = []
    for a in arrs:
        ds = list(a.devices()) if hasattr(a, "devices") else []
        devices.append(ds[0] if len(ds) == 1 else None)
    distinct = (all(d is not None for d in devices)
                and len(set(devices)) == len(devices))
    if not distinct:
        # Co-located (or host) inputs: still a compiled reduction, just on
        # one device — the mesh path needs one device per rank.
        stacked = jnp.stack([jnp.asarray(a) for a in arrs])
        red = _jnp_reduce_fn(op)(stacked)
        return {r: red for r in ranks}

    mesh_devices = tuple(devices)
    expanded = [a[None] for a in arrs]  # computed on each rank's device
    mesh = Mesh(list(mesh_devices), ("r",))
    global_arr = jax.make_array_from_single_device_arrays(
        (len(arrs),) + tuple(arrs[0].shape),
        NamedSharding(mesh, P("r")),
        expanded)
    fn = _device_allreduce_fn(mesh_devices, op, world)
    out = fn(global_arr)
    per = {}
    for shard in out.addressable_shards:
        idx = devices.index(shard.device)
        per[ranks[idx]] = shard.data[0]
    return per


import functools


@functools.lru_cache(maxsize=64)
def _device_allreduce_fn(mesh_devices: tuple, op: str, world: int):
    """Jitted shard_map reduction, cached by (devices, op, world) — jit's
    own cache is keyed on function identity, so a fresh closure per call
    would retrace+recompile every allreduce."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(list(mesh_devices), ("r",))

    def body(s):
        if op == "sum":
            return lax.psum(s, "r")
        if op == "mean":
            return lax.psum(s, "r") / world
        if op == "max":
            return lax.pmax(s, "r")
        if op == "min":
            return lax.pmin(s, "r")
        g = lax.all_gather(s, "r", axis=0, tiled=True)
        return jnp.prod(g, axis=0, keepdims=True)

    return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("r"),
                                 out_specs=P("r"), check_vma=False))


@functools.lru_cache(maxsize=16)
def _jnp_reduce_fn(op: str):
    import jax
    import jax.numpy as jnp

    fns = {"sum": jnp.sum, "prod": jnp.prod, "min": jnp.min,
           "max": jnp.max, "mean": jnp.mean}
    return jax.jit(functools.partial(fns[op], axis=0))


class _GroupState:
    """Shared rendezvous state for one collective group (local backend)."""

    backend = "local"

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.epoch = 0
        self.slots: Dict[int, np.ndarray] = {}
        self.result = None
        self.arrived = 0
        self.departed = 0
        # Point-to-point mailboxes: (src, dst) -> list of arrays.
        self.p2p: Dict[tuple, List[np.ndarray]] = {}

    def exchange(self, rank: int, value, compute):
        """All ranks deposit, one computes, all withdraw. Returns result."""
        with self.cv:
            # Phase 0: a fast rank can re-enter for the NEXT collective while
            # stragglers are still withdrawing from the previous one; without
            # this drain guard its deposit lands in (and is wiped with) the
            # old round — mixed-epoch corruption.
            while self.arrived == self.world_size or rank in self.slots:
                if not self.cv.wait(timeout=60.0):
                    raise TimeoutError(
                        f"collective drain timed out at rank {rank} "
                        f"(prev round: {self.departed}/{self.world_size} departed)"
                    )
            epoch = self.epoch
            self.slots[rank] = value
            self.arrived += 1
            if self.arrived == self.world_size:
                self.result = compute(self.slots)
                self.cv.notify_all()
            else:
                while self.epoch == epoch and self.arrived < self.world_size:
                    if not self.cv.wait(timeout=60.0):
                        raise TimeoutError(
                            f"collective timed out at rank {rank} "
                            f"({self.arrived}/{self.world_size} arrived)"
                        )
            result = self.result
            self.departed += 1
            if self.departed == self.world_size:
                # Reset for the next collective on this group.
                self.slots = {}
                self.arrived = 0
                self.departed = 0
                self.result = None
                self.epoch += 1
                self.cv.notify_all()
            return result

    # Descriptor-driven surface shared with the distributed backend.
    def exchange_desc(self, rank: int, descriptor: tuple, value):
        return self.exchange(rank, value,
                             _compute_for(descriptor, self.world_size))

    def p2p_send(self, src: int, dst: int, value) -> None:
        with self.cv:
            self.p2p.setdefault((src, dst), []).append(value)
            self.cv.notify_all()

    def p2p_recv(self, src: int, dst: int, timeout: float = 60.0):
        key = (src, dst)
        with self.cv:
            while not self.p2p.get(key):
                if not self.cv.wait(timeout=timeout):
                    raise TimeoutError(f"recv from rank {src} timed out")
            return self.p2p[key].pop(0)


class _DeviceGroupState(_GroupState):
    """In-process group whose allreduce runs COMPILED on the ranks' own
    devices (``backend="device"``). Broadcast/allgather hand device arrays
    through untouched; reducescatter/alltoall fall back to the host
    compute (their payloads coerce via numpy)."""

    backend = "device"

    def exchange_desc(self, rank: int, descriptor: tuple, value):
        if descriptor[0] == "allreduce":
            op = descriptor[1]
            per = self.exchange(
                rank, value,
                lambda slots: _device_allreduce(slots, op, self.world_size))
            return per[rank]
        return self.exchange(rank, value,
                             _compute_for(descriptor, self.world_size))


def _compute_for(descriptor: tuple, world: int):
    """Server-side compute for a descriptor-driven collective round.

    Both backends funnel through this: the local backend calls it in
    process, the "gloo" backend's rank-0 hub calls it after all ranks'
    payloads arrive over RPC — one implementation of the math either way.
    """
    kind = descriptor[0]
    if kind == "allreduce":
        op = descriptor[1]
        return lambda slots: _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
    if kind == "barrier":
        return lambda slots: None
    if kind == "broadcast":
        src = descriptor[1]
        return lambda slots: slots[src]
    if kind == "allgather":
        return lambda slots: [slots[r] for r in sorted(slots)]
    if kind == "reducescatter":
        op = descriptor[1]

        def compute(slots):
            reduced = _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
            return np.array_split(reduced, world, axis=0)

        return compute
    if kind == "alltoall":
        def compute(slots):
            split = {r: np.array_split(slots[r], world, axis=0) for r in slots}
            return {r: np.concatenate(
                [split[s][r] for s in sorted(split)], axis=0)
                for r in range(world)}

        return compute
    raise ValueError(f"unknown collective descriptor {descriptor}")


class _ShmIncoming:
    """A chunk delivered by shm reference: the array is a zero-copy view
    into the node's object store; ``close()`` releases the view and acks
    the origin so it can delete the backing object."""

    __slots__ = ("arr", "key", "origin", "_shm", "_closed")

    def __init__(self, arr, key, origin, shm):
        self.arr = arr
        self.key = key
        self.origin = origin
        self._shm = shm
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.release(self.key)
        except Exception:  # noqa: BLE001 — store gone at shutdown
            pass


class _MemberService:
    """Every rank's RPC surface in the cross-process backend: a tagged
    mailbox. Peers deliver (tag -> payload) messages; the local rank waits
    on its mailbox. Tags are (op_seq, step, src) so concurrent steps of
    pipelined rounds can't mix.

    Same-node peers can deliver big tensors BY SHM REFERENCE
    (``deliver_shm``): the payload crosses as a 16-byte object key; the
    receiver maps a zero-copy view out of the shared arena — the §5.8
    "large host tensors ride the shm object plane" tier."""

    def __init__(self):
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.box: Dict[tuple, object] = {}
        self.shm = None  # set by the group when a node store is reachable
        # Origin-side: shm chunks awaiting consumer acks -> pending count.
        self._outstanding: Dict[bytes, int] = {}

    def deliver(self, tag: tuple, value) -> None:
        with self.cv:
            self.box[tuple(tag)] = value
            self.cv.notify_all()

    def deliver_shm(self, tag: tuple, key: bytes, shape, dtype: str,
                    origin: int) -> None:
        import numpy as _np

        view = self.shm.get(key) if self.shm is not None else None
        if view is None:
            raise RuntimeError(
                f"shm chunk {key.hex()[:12]} not found in local store")
        arr = _np.frombuffer(view, dtype=_np.dtype(dtype)).reshape(shape)
        with self.cv:
            self.box[tuple(tag)] = _ShmIncoming(arr, key, origin, self.shm)
            self.cv.notify_all()

    def note_outstanding(self, key: bytes, consumers: int) -> None:
        with self.lock:
            self._outstanding[key] = consumers

    def shm_done(self, key: bytes) -> None:
        """Consumer ack: delete the backing object once all consumers of
        this chunk have released their views."""
        with self.lock:
            n = self._outstanding.get(key, 1) - 1
            if n > 0:
                self._outstanding[key] = n
                return
            self._outstanding.pop(key, None)
        if self.shm is not None:
            try:
                self.shm.delete(key)
            except Exception:  # noqa: BLE001 — store gone at shutdown
                pass

    def take(self, tag: tuple, timeout: Optional[float] = 120.0):
        import time as _time

        end = None if timeout is None else _time.time() + timeout
        tag = tuple(tag)
        with self.cv:
            while tag not in self.box:
                if end is None:  # block indefinitely (p2p recv contract)
                    self.cv.wait(timeout=1.0)
                    continue
                remaining = end - _time.time()
                if remaining <= 0:
                    raise TimeoutError(f"collective step {tag} never arrived")
                self.cv.wait(timeout=min(remaining, 1.0))
            return self.box.pop(tag)

    def ping(self) -> str:
        return "pong"


class _DistributedGroup:
    """One rank's view of a cross-process group: RING reduce-scatter /
    allgather and a binomial broadcast tree over direct peer-to-peer
    channels — each rank moves O(size) bytes per allreduce regardless of
    world size (the rank-0 hub this replaces concentrated O(N*size) on one
    socket). This is the host-tensor (DCN/gloo) tier of §5.8; device
    tensors inside jitted programs use XLA collectives over ICI instead.
    """

    # Payloads at or above this ride the shm object plane between
    # same-node ranks (below it, the socket path's latency wins).
    SHM_MIN_BYTES = 1 << 20

    def __init__(self, world_size: int, rank: int, addrs: List[str],
                 service: _MemberService, server,
                 stores: Optional[List[Optional[str]]] = None):
        from ray_tpu.core.rpc import RpcClientPool

        self.world_size = world_size
        self.rank = rank
        self._addrs = addrs
        self._service = service
        self._server = server  # keeps the member server alive
        self._peers = RpcClientPool()
        self._op_seq = 0
        self._op_lock = threading.Lock()
        # Same-node shm fast path: ranks publishing the same store name
        # share one arena; big chunks cross as object keys.
        self._stores = stores or [None] * world_size
        # The store handle is opened by _init_distributed_group BEFORE the
        # rank's address is published (a peer may deliver_shm the moment it
        # can see us); here we just adopt it off the service.
        self._shm = service.shm
        if self._shm is None:
            self._stores = [None] * world_size
        # Homogeneous single-node group: broadcast can write once and
        # circulate one key through the whole tree.
        self._all_same_store = bool(
            self._stores[0]
            and all(s == self._stores[0] for s in self._stores))

    # -- plumbing -----------------------------------------------------------

    def _next_seq(self) -> int:
        with self._op_lock:
            self._op_seq += 1
            return self._op_seq

    def _send(self, dst: int, tag: tuple, value) -> None:
        if dst == self.rank:
            self._service.deliver(tag, value)
            return
        self._peers.get(self._addrs[dst]).call(
            "deliver", tag, value, timeout=120.0)

    @staticmethod
    def _bc_subtree_consumers(rel: int, n: int) -> int:
        """How many DESCENDANTS of relative rank ``rel`` in the binomial
        broadcast tree will receive (and ack) a key published by ``rel``.
        Node ``rel`` owns children ``rel + 2^k`` for ``2^k > rel`` while
        ``rel + 2^k < n``; descendants ack recursively. Publishing with
        ``n - 1`` on a non-root republisher (root's publish failed, chunk
        arrived by socket) would leave ``shm_done`` forever short — only
        the republisher's own subtree ever acks."""
        count = 0
        k = 1
        while k < n:
            if rel < k and rel + k < n:
                child = rel + k
                count += 1 + _DistributedGroup._bc_subtree_consumers(child, n)
            k *= 2
        return count

    def _ring_shm_consumers(self, first_dst: int, hops: int) -> int:
        """How many CONSECUTIVE downstream ring receivers (starting at
        ``first_dst``, following +1 for ``hops`` hops) share this rank's
        store. Only those receive the chunk BY KEY and ack; once the ring
        crosses to a different store the chunk continues as socket copies
        — counting those would leave the backing object undeletable."""
        n = self.world_size
        count = 0
        r = first_dst
        for _ in range(hops):
            if self._stores[r % n] != self._stores[self.rank]:
                break
            count += 1
            r += 1
        return count

    def _send_async(self, dst: int, tag: tuple, value, *,
                    consumers: int = 1, holder=None):
        """Fire-and-overlap send: returns a future (or None for self-
        delivery). Ring steps overlap their outgoing transfer with the
        blocking wait for the incoming one — full-duplex links move both
        directions at once instead of serializing on the deliver ack.

        Big numpy payloads to SAME-NODE peers go by shm reference: one
        copy into the shared arena, a 16-byte key over the socket, a
        zero-copy view on the other side. A chunk already BACKED by shm
        (``holder``) is forwarded by key — zero copies on any hop;
        ``consumers`` (total ranks that will ack) is fixed by the
        creator."""
        if dst == self.rank:
            self._service.deliver(tag, value)
            return None
        same_store = (self._shm is not None
                      and self._stores[dst] == self._stores[self.rank])
        if holder is not None and same_store:
            return self._peers.get(self._addrs[dst]).call_async(
                "deliver_shm", tag, holder.key, value.shape,
                value.dtype.str, holder.origin)
        if (same_store
                and isinstance(value, np.ndarray)
                and value.nbytes >= self.SHM_MIN_BYTES
                and consumers > 0):
            key = self._publish_shm(value, consumers)
            if key is not None:
                return self._peers.get(self._addrs[dst]).call_async(
                    "deliver_shm", tag, key, value.shape, value.dtype.str,
                    self.rank)
            # Arena full: fall through to the socket path.
        return self._peers.get(self._addrs[dst]).call_async(
            "deliver", tag, value)

    def _publish_shm(self, arr: np.ndarray, consumers: int) -> Optional[bytes]:
        """Seal one shm object holding ``arr``; returns its key (None when
        the arena is full). The creator expects ``consumers`` acks before
        deleting."""
        import os as _os

        key = _os.urandom(16)
        view = self._shm.create(key, arr.nbytes)
        if view is None:
            return None
        flat = np.frombuffer(view, dtype=arr.dtype)
        flat[:] = np.ascontiguousarray(arr).reshape(-1)
        self._shm.seal(key)
        self._service.note_outstanding(key, consumers)
        return key

    def _materialize(self, incoming):
        """(ndarray, holder) for a received chunk. shm-delivered chunks
        come back as zero-copy views with a non-None holder: the caller
        uses the array, then MUST call ``_finish_consume(holder)`` (a
        caller that keeps the array beyond the step copies it first)."""
        if isinstance(incoming, _ShmIncoming):
            return incoming.arr, incoming
        return np.asarray(incoming), None

    def _ack_shm(self, incoming: "_ShmIncoming") -> None:
        try:
            self._peers.get(self._addrs[incoming.origin]).notify(
                "shm_done", incoming.key)
        except Exception:  # noqa: BLE001 — origin gone; its store reaps
            pass

    def _finish_consume(self, holder) -> None:
        if holder is not None:
            holder.close()
            self._ack_shm(holder)

    def _recv(self, tag: tuple, timeout: float = 120.0):
        return self._service.take(tag, timeout)

    # -- collectives --------------------------------------------------------

    def exchange_desc(self, rank: int, descriptor: tuple, value):
        assert rank == self.rank
        kind = descriptor[0]
        seq = self._next_seq()
        if kind == "allreduce":
            return self._allreduce(seq, value, descriptor[1])
        if kind == "reducescatter":
            reduced = self._reduce_scatter(seq, value, descriptor[1])
            # API contract: caller indexes [rank]; return full split list
            # shape-compatible with the local backend.
            out = [None] * self.world_size
            out[self.rank] = reduced
            return out
        if kind == "allgather":
            return self._allgather(seq, value)
        if kind == "broadcast":
            return self._broadcast(seq, value, descriptor[1])
        if kind == "barrier":
            self._allgather(seq, np.zeros(1, dtype=np.uint8))
            return None
        if kind == "alltoall":
            return {self.rank: self._alltoall(seq, value)}
        raise ValueError(f"unknown collective descriptor {descriptor}")

    def _ring_chunks(self, arr: np.ndarray) -> List[np.ndarray]:
        return np.array_split(arr, self.world_size, axis=0)

    def _allreduce(self, seq: int, value, op: str):
        """Ring allreduce: reduce-scatter then allgather, 2(N-1) steps,
        each moving ~size/N bytes per rank per step."""
        n = self.world_size
        if n == 1:
            return _REDUCE_OPS[op]([np.asarray(value)])
        arr = np.asarray(value)
        orig_shape = arr.shape
        arr = np.atleast_1d(arr)
        mean = op == "mean"
        acc_op = "sum" if mean else op
        chunks = self._ring_chunks(arr)
        nxt, prv = (self.rank + 1) % n, (self.rank - 1) % n
        # Phase 1 — reduce-scatter: after step s, this rank holds the
        # running reduction of chunk (rank - s) % n over s+1 contributors.
        for step in range(n - 1):
            send_idx = (self.rank - step) % n
            recv_idx = (self.rank - step - 1) % n
            fut = self._send_async(nxt, (seq, "rs", step), chunks[send_idx])
            arr, holder = self._materialize(self._recv((seq, "rs", step)))
            chunks[recv_idx] = _REDUCE_OPS[acc_op]([chunks[recv_idx], arr])
            self._finish_consume(holder)
            if fut is not None:
                fut.result(timeout=120.0)
        owned = (self.rank + 1) % n  # fully reduced chunk this rank holds
        if mean:
            chunks[owned] = chunks[owned] / n
        # Phase 2 — allgather the reduced chunks around the ring. Each
        # reduced chunk is written to shm ONCE by its owner and then
        # FORWARDED BY KEY: every rank reads the same backing object
        # (zero-copy views, consumed by the final concatenate) and acks;
        # the owner deletes after all n-1 consumers ack.
        holders: List[Optional[_ShmIncoming]] = [None] * n
        for step in range(n - 1):
            send_idx = (self.rank + 1 - step) % n
            recv_idx = (self.rank - step) % n
            # consumers = the consecutive same-store receivers downstream
            # of THIS send (the chunk has n-1-step hops left; once the
            # ring crosses stores it continues as socket copies that never
            # ack — counting them would leak the backing object).
            fut = self._send_async(
                nxt, (seq, "ag", step), chunks[send_idx],
                consumers=self._ring_shm_consumers(nxt, n - 1 - step),
                holder=holders[send_idx])
            arr, holder = self._materialize(self._recv((seq, "ag", step)))
            chunks[recv_idx] = arr  # shm chunks stay zero-copy views
            holders[recv_idx] = holder
            if fut is not None:
                fut.result(timeout=120.0)
        result = np.concatenate([np.atleast_1d(c) for c in chunks], axis=0)
        for h in holders:
            self._finish_consume(h)
        return result.reshape(orig_shape)

    def _reduce_scatter(self, seq: int, value, op: str):
        n = self.world_size
        arr = np.asarray(value)
        if n == 1:
            return _REDUCE_OPS[op]([arr])
        mean = op == "mean"
        acc_op = "sum" if mean else op
        chunks = self._ring_chunks(arr)
        nxt = (self.rank + 1) % n
        for step in range(n - 1):
            send_idx = (self.rank - step) % n
            recv_idx = (self.rank - step - 1) % n
            fut = self._send_async(nxt, (seq, "rs", step), chunks[send_idx])
            arr, holder = self._materialize(self._recv((seq, "rs", step)))
            chunks[recv_idx] = _REDUCE_OPS[acc_op]([chunks[recv_idx], arr])
            self._finish_consume(holder)
            if fut is not None:
                fut.result(timeout=120.0)
        owned = (self.rank + 1) % n
        res = chunks[owned]
        if mean:
            res = res / n
        # Rotate so the API's slots[rank] convention holds: ring ownership
        # is chunk (rank+1)%n; the contract gives rank its OWN index.
        self._send((self.rank + 1) % n, (seq, "rsrot", 0), res)
        arr, holder = self._materialize(self._recv((seq, "rsrot", 0)))
        if holder is not None:
            arr = np.array(arr)  # returned to the caller: detach from shm
            self._finish_consume(holder)
        return arr

    def _allgather(self, seq: int, value) -> List[np.ndarray]:
        n = self.world_size
        out: List[Optional[np.ndarray]] = [None] * n
        out[self.rank] = np.asarray(value)
        if n == 1:
            return out  # type: ignore[return-value]
        nxt = (self.rank + 1) % n
        carry_idx = self.rank
        for step in range(n - 1):
            fut = self._send_async(nxt, (seq, "ag", step), out[carry_idx])
            carry_idx = (self.rank - step - 1) % n
            arr, holder = self._materialize(self._recv((seq, "ag", step)))
            if holder is not None:
                arr = np.array(arr)
                self._finish_consume(holder)
            out[carry_idx] = arr
            if fut is not None:
                fut.result(timeout=120.0)
        return out  # type: ignore[return-value]

    def _broadcast(self, seq: int, value, src: int):
        """Binomial tree: log2(N) rounds, no rank sends more than
        ceil(log2 N) copies (vs the hub serializing N sends)."""
        n = self.world_size
        rel = (self.rank - src) % n
        holder = None
        if rel != 0:
            arr, holder = self._materialize(self._recv((seq, "bc", rel)))
        else:
            arr = np.asarray(value)
        # Forward to children in the binomial tree over RELATIVE ranks:
        # node `rel` owns children rel + 2^k for 2^k > rel. Sends overlap
        # (async); on a homogeneous same-store group the payload is
        # written to shm ONCE (by the root) and the whole tree circulates
        # its key — every forward hop is a 16-byte message.
        children = []
        k = 1
        while k < n:
            if rel < k and rel + k < n:
                children.append(rel + k)
            k *= 2
        futs = []
        key_holder = holder
        if (children and key_holder is None and self._all_same_store
                and self._shm is not None and isinstance(arr, np.ndarray)
                and arr.nbytes >= self.SHM_MIN_BYTES):
            key = self._publish_shm(
                arr, self._bc_subtree_consumers(rel, n))
            if key is not None:
                # Root-side pseudo-holder: carries the key for forwarding;
                # the root itself never acks/closes it.
                key_holder = _ShmIncoming(arr, key, self.rank, self._shm)
        for child_rel in children:
            if key_holder is not None and self._all_same_store:
                futs.append(self._peers.get(
                    self._addrs[(src + child_rel) % n]).call_async(
                    "deliver_shm", (seq, "bc", child_rel), key_holder.key,
                    arr.shape, arr.dtype.str, key_holder.origin))
            else:
                futs.append(self._send_async(
                    (src + child_rel) % n, (seq, "bc", child_rel), arr))
        for fut in futs:
            if fut is not None:
                fut.result(timeout=120.0)
        if holder is not None:
            arr = np.array(arr)  # result is returned to the caller
            self._finish_consume(holder)
        return arr

    def _alltoall(self, seq: int, value):
        n = self.world_size
        shards = np.array_split(np.asarray(value), n, axis=0)
        futs = []
        for dst in range(n):
            if dst != self.rank:
                futs.append(self._send_async(
                    dst, (seq, "a2a", self.rank), shards[dst]))
        pieces = []
        holders = []
        for s in range(n):
            if s == self.rank:
                pieces.append(shards[self.rank])
            else:
                arr, holder = self._materialize(self._recv((seq, "a2a", s)))
                pieces.append(arr)
                if holder is not None:
                    holders.append(holder)
        result = np.concatenate(pieces, axis=0)  # copies: views die after
        for h in holders:
            self._finish_consume(h)
        for fut in futs:
            if fut is not None:
                fut.result(timeout=120.0)
        return result

    # -- p2p ----------------------------------------------------------------

    def p2p_send(self, src: int, dst: int, value) -> None:
        self._send(dst, ("p2p", src, dst,
                         self._p2p_counter(src, dst, "send")), value)

    def p2p_recv(self, src: int, dst: int,
                 timeout: Optional[float] = 60.0):
        # Matching monotone counters on both ends keep repeated send/recv
        # pairs FIFO-ordered. The cursor is RESERVED under the lock before
        # blocking — two concurrent recvs for the same (src, dst) get
        # distinct tags instead of racing for one message and stranding the
        # loser on a tag the sender has moved past. A timed-out recv rolls
        # its reservation back (only if it is still the newest — with a
        # later recv outstanding the gap is unrecoverable either way) so a
        # single-threaded retry consumes the late-arriving message.
        key = ("p2p_ctr", src, dst, "recv")
        with self._op_lock:
            d = getattr(self, "_p2p_counts", None)
            if d is None:
                d = self._p2p_counts = {}
            nxt = d.get(key, 0) + 1
            d[key] = nxt
        try:
            return self._recv(("p2p", src, dst, nxt), timeout)
        except BaseException:
            with self._op_lock:
                if self._p2p_counts.get(key) == nxt:
                    self._p2p_counts[key] = nxt - 1
            raise

    def _p2p_counter(self, src: int, dst: int, direction: str) -> int:
        key = ("p2p_ctr", src, dst, direction)
        with self._op_lock:
            d = getattr(self, "_p2p_counts", None)
            if d is None:
                d = self._p2p_counts = {}
            d[key] = d.get(key, 0) + 1
            return d[key]


@dataclass
class GroupInfo:
    name: str
    world_size: int
    backend: str


_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()
# rank registry keyed by execution context: an actor's rank is visible from
# every thread that executes its methods (actor init and method calls run on
# different threads in the runtime).
_ranks: Dict[tuple, Dict[str, int]] = {}


def _ctx_key() -> tuple:
    try:
        rt = get_runtime()
        aid = rt.current_actor_id
        if aid is not None:
            return ("actor", aid)
    except Exception:
        pass
    return ("thread", threading.get_ident())


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "local",
    group_name: str = "default",
) -> None:
    """Join a named collective group (reference: collective.py:120).

    Every member actor/task calls this with its rank; the group state
    rendezvouses through the process-wide registry (the analog of NCCL
    unique-id exchange via the reference's internal KV).
    """
    if backend not in ("local", "gloo", "ring", "device", "xla"):
        raise ValueError(f"unknown backend {backend}")
    if backend == "xla":
        # No silent fallback: inside jit'ed programs device tensors already
        # use XLA collectives over ICI via jax.sharding; the EAGER device
        # tier is backend="device" (single-host multi-chip: a compiled
        # psum over the devices the ranks' arrays live on). Multi-host
        # eager device collectives require a jax.distributed world, which
        # this runtime wires through the mesh/Train layer.
        raise RuntimeError(
            "backend='xla' is the compiled path: device tensors inside "
            "jit'ed programs already use XLA collectives over ICI via "
            "jax.sharding (see ray_tpu.parallel.mesh / JaxTrainer). For "
            "eager collectives between actors use backend='device' "
            "(same-host device arrays, compiled psum over their chips), "
            "'gloo' (host tensors, ring over sockets) or 'local' "
            "(in-process).")
    if backend in ("gloo", "ring"):
        _init_distributed_group(world_size, rank, group_name)
    else:
        cls = _DeviceGroupState if backend == "device" else _GroupState
        with _groups_lock:
            state = _groups.get(group_name)
            if state is None:
                state = cls(world_size)
                _groups[group_name] = state
            elif state.world_size != world_size:
                raise ValueError(
                    f"group {group_name} exists with world_size={state.world_size}"
                )
            elif type(state) is not cls:
                raise ValueError(
                    f"group {group_name} exists with backend="
                    f"{state.backend!r}")
    with _groups_lock:
        _ranks.setdefault(_ctx_key(), {})[group_name] = rank
    # Record membership in the control plane for observability.
    try:
        get_runtime().gcs.kv_put(
            f"collective:{group_name}:{rank}", b"1", namespace="collective"
        )
    except Exception:
        pass


def _init_distributed_group(world_size: int, rank: int, group_name: str) -> None:
    """Cross-process backend: every rank hosts a member mailbox server and
    publishes its address through the control plane's KV (exactly how the
    reference exchanges the NCCL unique id — nccl_collective_group.py via
    the internal KV); collectives then run rank-to-rank over a ring /
    binomial tree with no hub."""
    import time as _time

    from ray_tpu.core.rpc import RpcServer

    with _groups_lock:
        existing = _groups.get(group_name)
        if existing is not None and existing.world_size != world_size:
            raise ValueError(
                f"group {group_name} exists with world_size="
                f"{existing.world_size}")

    import os as _os

    gcs = get_runtime().gcs
    service = _MemberService()
    # Open the node store (and arm the service's shm surface) BEFORE the
    # address is published: a fast peer may deliver_shm the instant it can
    # see this rank. RAY_TPU_COLLECTIVE_SHM=0 disables the shm transport
    # (A/B benching + emergency fallback to pure sockets).
    my_store = _os.environ.get("RAY_TPU_STORE_NAME", "")
    if _os.environ.get("RAY_TPU_COLLECTIVE_SHM", "1") == "0":
        my_store = ""
    if my_store:
        try:
            from ray_tpu.core.native_store import NativeObjectStore

            service.shm = NativeObjectStore.open(my_store)
        except Exception:  # noqa: BLE001 — no local store: socket path
            service.shm = None
            my_store = ""
    server = RpcServer(service, name=f"collective-{group_name}-r{rank}",
                       max_workers=max(8, world_size + 2))
    gcs.kv_put(f"collective:{group_name}:addr:{rank}",
               f"{server.address}|{my_store}".encode(),
               namespace="collective")
    addrs: List[Optional[str]] = [None] * world_size
    stores: List[Optional[str]] = [None] * world_size
    addrs[rank] = server.address
    stores[rank] = my_store or None
    deadline = _time.time() + 60.0
    while any(a is None for a in addrs):
        for r in range(world_size):
            if addrs[r] is None:
                raw = gcs.kv_get(f"collective:{group_name}:addr:{r}",
                                 namespace="collective")
                if raw:
                    text = raw.decode()
                    addr, _, store = text.partition("|")
                    addrs[r] = addr
                    stores[r] = store or None
        if any(a is None for a in addrs):
            if _time.time() > deadline:
                server.stop()
                missing = [r for r in range(world_size) if addrs[r] is None]
                raise TimeoutError(
                    f"collective group {group_name}: ranks {missing} never "
                    f"published their member address")
            _time.sleep(0.05)
    group = _DistributedGroup(world_size, rank, addrs, service, server,
                              stores=stores)
    group._kv_key = f"collective:{group_name}:addr:{rank}"
    with _groups_lock:
        _groups[group_name] = group  # type: ignore[assignment]


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        state = _groups.pop(group_name, None)
    server = getattr(state, "_server", None)
    if server is not None:  # cross-process member mailbox server
        server.stop()
        peers = getattr(state, "_peers", None)
        if peers is not None:  # close per-peer clients (one per rank)
            peers.close_all()
        # Drop the rendezvous key so a re-created group can't race a
        # later joiner onto a dead member's address.
        try:
            get_runtime().gcs.kv_del(getattr(state, "_kv_key", ""),
                                     namespace="collective")
        except Exception:  # noqa: BLE001
            pass


def get_rank(group_name: str = "default") -> int:
    with _groups_lock:
        ranks = _ranks.get(_ctx_key(), {})
        if group_name in ranks:
            return ranks[group_name]
    raise RuntimeError(
        f"init_collective_group must be called in this actor/task first "
        f"(group={group_name})"
    )


def get_collective_group_size(group_name: str = "default") -> int:
    state = _group(group_name)
    return state.world_size


def _group(group_name: str) -> _GroupState:
    with _groups_lock:
        state = _groups.get(group_name)
    if state is None:
        raise RuntimeError(f"collective group '{group_name}' not initialized")
    return state


def _to_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


def _prep(state, tensor):
    """Device-backend groups keep tensors ON DEVICE; host backends get
    numpy (the reference's gloo path copies to host the same way)."""
    if getattr(state, "backend", "local") == "device":
        return tensor
    return _to_numpy(tensor)


def allreduce(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:258."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.exchange_desc(rank, ("allreduce", op), _prep(state, tensor))


def barrier(group_name: str = "default") -> None:
    """reference: collective.py:298."""
    state = _group(group_name)
    state.exchange_desc(get_rank(group_name), ("barrier",), None)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """reference: collective.py:373."""
    state = _group(group_name)
    rank = get_rank(group_name)
    value = _prep(state, tensor) if rank == src_rank else None
    return state.exchange_desc(rank, ("broadcast", src_rank), value)


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """reference: collective.py:423. Returns list of per-rank tensors."""
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.exchange_desc(rank, ("allgather",), _prep(state, tensor))


def reducescatter(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:472. Input split along dim 0 across ranks;
    each rank receives its reduced shard."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    shards = state.exchange_desc(rank, ("reducescatter", op), _to_numpy(tensor))
    return shards[rank]


def alltoall(tensor, group_name: str = "default"):
    """Each rank's input is split along dim 0; shard i goes to rank i.

    The host-side analog of XLA ``all_to_all`` (expert-parallel routing).
    """
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.exchange_desc(rank, ("alltoall",), _to_numpy(tensor))[rank]


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """reference: collective.py:531 (p2p)."""
    state = _group(group_name)
    rank = get_rank(group_name)
    state.p2p_send(rank, dst_rank, _to_numpy(tensor))


def recv(src_rank: int, group_name: str = "default", timeout: float = 60.0):
    """reference: collective.py:594 (p2p)."""
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.p2p_recv(src_rank, rank, timeout)
