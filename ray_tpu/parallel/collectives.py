"""Eager actor-level collectives — the §5.8 API contract.

Analog of the reference's ``ray.util.collective``
(``python/ray/util/collective/collective.py`` — ``init_collective_group``
:120, ``create_collective_group`` :151, ``allreduce`` :258, ``barrier`` :298,
``broadcast`` :373, ``allgather`` :423, ``reducescatter`` :472, ``send``
:531 / ``recv`` :594) re-based for the TPU world:

- **Compiled path (the fast path):** device tensors inside a jitted program
  use XLA collectives over ICI (``psum``/``all_gather``/...) — that path
  lives in the mesh/sharding layer, not here.
- **Eager path (this module):** host-side arrays exchanged between actors in
  a named group — rendezvous through the runtime's control plane exactly the
  way the reference rendezvouses NCCL unique ids through its KV store
  (``nccl_collective_group.py``). The local backend synchronizes ranks with
  barriers and reduces with numpy; it is the Gloo analog and the test
  substrate for multi-host DCN collectives.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.core.runtime import get_runtime
from ray_tpu.utils.logging import get_logger

logger = get_logger("collectives")

_REDUCE_OPS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "prod": lambda arrs: np.prod(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
}


class _GroupState:
    """Shared rendezvous state for one collective group (local backend)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.epoch = 0
        self.slots: Dict[int, np.ndarray] = {}
        self.result = None
        self.arrived = 0
        self.departed = 0
        # Point-to-point mailboxes: (src, dst) -> list of arrays.
        self.p2p: Dict[tuple, List[np.ndarray]] = {}

    def exchange(self, rank: int, value, compute):
        """All ranks deposit, one computes, all withdraw. Returns result."""
        with self.cv:
            # Phase 0: a fast rank can re-enter for the NEXT collective while
            # stragglers are still withdrawing from the previous one; without
            # this drain guard its deposit lands in (and is wiped with) the
            # old round — mixed-epoch corruption.
            while self.arrived == self.world_size or rank in self.slots:
                if not self.cv.wait(timeout=60.0):
                    raise TimeoutError(
                        f"collective drain timed out at rank {rank} "
                        f"(prev round: {self.departed}/{self.world_size} departed)"
                    )
            epoch = self.epoch
            self.slots[rank] = value
            self.arrived += 1
            if self.arrived == self.world_size:
                self.result = compute(self.slots)
                self.cv.notify_all()
            else:
                while self.epoch == epoch and self.arrived < self.world_size:
                    if not self.cv.wait(timeout=60.0):
                        raise TimeoutError(
                            f"collective timed out at rank {rank} "
                            f"({self.arrived}/{self.world_size} arrived)"
                        )
            result = self.result
            self.departed += 1
            if self.departed == self.world_size:
                # Reset for the next collective on this group.
                self.slots = {}
                self.arrived = 0
                self.departed = 0
                self.result = None
                self.epoch += 1
                self.cv.notify_all()
            return result


@dataclass
class GroupInfo:
    name: str
    world_size: int
    backend: str


_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()
# rank registry keyed by execution context: an actor's rank is visible from
# every thread that executes its methods (actor init and method calls run on
# different threads in the runtime).
_ranks: Dict[tuple, Dict[str, int]] = {}


def _ctx_key() -> tuple:
    try:
        rt = get_runtime()
        aid = rt.current_actor_id
        if aid is not None:
            return ("actor", aid)
    except Exception:
        pass
    return ("thread", threading.get_ident())


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "local",
    group_name: str = "default",
) -> None:
    """Join a named collective group (reference: collective.py:120).

    Every member actor/task calls this with its rank; the group state
    rendezvouses through the process-wide registry (the analog of NCCL
    unique-id exchange via the reference's internal KV).
    """
    if backend not in ("local", "gloo", "xla"):
        raise ValueError(f"unknown backend {backend}")
    with _groups_lock:
        state = _groups.get(group_name)
        if state is None:
            state = _GroupState(world_size)
            _groups[group_name] = state
        elif state.world_size != world_size:
            raise ValueError(
                f"group {group_name} exists with world_size={state.world_size}"
            )
    with _groups_lock:
        _ranks.setdefault(_ctx_key(), {})[group_name] = rank
    # Record membership in the control plane for observability.
    try:
        get_runtime().gcs.kv_put(
            f"collective:{group_name}:{rank}", b"1", namespace="collective"
        )
    except Exception:
        pass


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        _groups.pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    with _groups_lock:
        ranks = _ranks.get(_ctx_key(), {})
        if group_name in ranks:
            return ranks[group_name]
    raise RuntimeError(
        f"init_collective_group must be called in this actor/task first "
        f"(group={group_name})"
    )


def get_collective_group_size(group_name: str = "default") -> int:
    state = _group(group_name)
    return state.world_size


def _group(group_name: str) -> _GroupState:
    with _groups_lock:
        state = _groups.get(group_name)
    if state is None:
        raise RuntimeError(f"collective group '{group_name}' not initialized")
    return state


def _to_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


def allreduce(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:258."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    value = _to_numpy(tensor)
    return state.exchange(
        rank, value, lambda slots: _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
    )


def barrier(group_name: str = "default") -> None:
    """reference: collective.py:298."""
    state = _group(group_name)
    state.exchange(get_rank(group_name), None, lambda slots: None)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """reference: collective.py:373."""
    state = _group(group_name)
    rank = get_rank(group_name)
    value = _to_numpy(tensor) if rank == src_rank else None
    return state.exchange(rank, value, lambda slots: slots[src_rank])


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """reference: collective.py:423. Returns list of per-rank tensors."""
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.exchange(
        rank, _to_numpy(tensor), lambda slots: [slots[r] for r in sorted(slots)]
    )


def reducescatter(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:472. Input split along dim 0 across ranks;
    each rank receives its reduced shard."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    world = state.world_size

    def compute(slots):
        reduced = _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
        return np.array_split(reduced, world, axis=0)

    shards = state.exchange(rank, _to_numpy(tensor), compute)
    return shards[rank]


def alltoall(tensor, group_name: str = "default"):
    """Each rank's input is split along dim 0; shard i goes to rank i.

    The host-side analog of XLA ``all_to_all`` (expert-parallel routing).
    """
    state = _group(group_name)
    rank = get_rank(group_name)
    world = state.world_size

    def compute(slots):
        split = {r: np.array_split(slots[r], world, axis=0) for r in slots}
        return {r: np.concatenate([split[s][r] for s in sorted(split)], axis=0)
                for r in range(world)}

    return state.exchange(rank, _to_numpy(tensor), compute)[rank]


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """reference: collective.py:531 (p2p)."""
    state = _group(group_name)
    rank = get_rank(group_name)
    with state.cv:
        state.p2p.setdefault((rank, dst_rank), []).append(_to_numpy(tensor))
        state.cv.notify_all()


def recv(src_rank: int, group_name: str = "default", timeout: float = 60.0):
    """reference: collective.py:594 (p2p)."""
    state = _group(group_name)
    rank = get_rank(group_name)
    key = (src_rank, rank)
    with state.cv:
        while not state.p2p.get(key):
            if not state.cv.wait(timeout=timeout):
                raise TimeoutError(f"recv from rank {src_rank} timed out")
        return state.p2p[key].pop(0)
