"""Eager actor-level collectives — the §5.8 API contract.

Analog of the reference's ``ray.util.collective``
(``python/ray/util/collective/collective.py`` — ``init_collective_group``
:120, ``create_collective_group`` :151, ``allreduce`` :258, ``barrier`` :298,
``broadcast`` :373, ``allgather`` :423, ``reducescatter`` :472, ``send``
:531 / ``recv`` :594) re-based for the TPU world:

- **Compiled path (the fast path):** device tensors inside a jitted program
  use XLA collectives over ICI (``psum``/``all_gather``/...) — that path
  lives in the mesh/sharding layer, not here.
- **Eager path (this module):** host-side arrays exchanged between actors in
  a named group — rendezvous through the runtime's control plane exactly the
  way the reference rendezvouses NCCL unique ids through its KV store
  (``nccl_collective_group.py``). The local backend synchronizes ranks with
  barriers and reduces with numpy; it is the Gloo analog and the test
  substrate for multi-host DCN collectives.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.core.runtime import get_runtime
from ray_tpu.utils.logging import get_logger

logger = get_logger("collectives")

_REDUCE_OPS = {
    "sum": lambda arrs: np.sum(arrs, axis=0),
    "prod": lambda arrs: np.prod(arrs, axis=0),
    "min": lambda arrs: np.min(arrs, axis=0),
    "max": lambda arrs: np.max(arrs, axis=0),
    "mean": lambda arrs: np.mean(arrs, axis=0),
}


class _GroupState:
    """Shared rendezvous state for one collective group (local backend)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.epoch = 0
        self.slots: Dict[int, np.ndarray] = {}
        self.result = None
        self.arrived = 0
        self.departed = 0
        # Point-to-point mailboxes: (src, dst) -> list of arrays.
        self.p2p: Dict[tuple, List[np.ndarray]] = {}

    def exchange(self, rank: int, value, compute):
        """All ranks deposit, one computes, all withdraw. Returns result."""
        with self.cv:
            # Phase 0: a fast rank can re-enter for the NEXT collective while
            # stragglers are still withdrawing from the previous one; without
            # this drain guard its deposit lands in (and is wiped with) the
            # old round — mixed-epoch corruption.
            while self.arrived == self.world_size or rank in self.slots:
                if not self.cv.wait(timeout=60.0):
                    raise TimeoutError(
                        f"collective drain timed out at rank {rank} "
                        f"(prev round: {self.departed}/{self.world_size} departed)"
                    )
            epoch = self.epoch
            self.slots[rank] = value
            self.arrived += 1
            if self.arrived == self.world_size:
                self.result = compute(self.slots)
                self.cv.notify_all()
            else:
                while self.epoch == epoch and self.arrived < self.world_size:
                    if not self.cv.wait(timeout=60.0):
                        raise TimeoutError(
                            f"collective timed out at rank {rank} "
                            f"({self.arrived}/{self.world_size} arrived)"
                        )
            result = self.result
            self.departed += 1
            if self.departed == self.world_size:
                # Reset for the next collective on this group.
                self.slots = {}
                self.arrived = 0
                self.departed = 0
                self.result = None
                self.epoch += 1
                self.cv.notify_all()
            return result

    # Descriptor-driven surface shared with the distributed backend.
    def exchange_desc(self, rank: int, descriptor: tuple, value):
        return self.exchange(rank, value,
                             _compute_for(descriptor, self.world_size))

    def p2p_send(self, src: int, dst: int, value) -> None:
        with self.cv:
            self.p2p.setdefault((src, dst), []).append(value)
            self.cv.notify_all()

    def p2p_recv(self, src: int, dst: int, timeout: float = 60.0):
        key = (src, dst)
        with self.cv:
            while not self.p2p.get(key):
                if not self.cv.wait(timeout=timeout):
                    raise TimeoutError(f"recv from rank {src} timed out")
            return self.p2p[key].pop(0)


def _compute_for(descriptor: tuple, world: int):
    """Server-side compute for a descriptor-driven collective round.

    Both backends funnel through this: the local backend calls it in
    process, the "gloo" backend's rank-0 hub calls it after all ranks'
    payloads arrive over RPC — one implementation of the math either way.
    """
    kind = descriptor[0]
    if kind == "allreduce":
        op = descriptor[1]
        return lambda slots: _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
    if kind == "barrier":
        return lambda slots: None
    if kind == "broadcast":
        src = descriptor[1]
        return lambda slots: slots[src]
    if kind == "allgather":
        return lambda slots: [slots[r] for r in sorted(slots)]
    if kind == "reducescatter":
        op = descriptor[1]

        def compute(slots):
            reduced = _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
            return np.array_split(reduced, world, axis=0)

        return compute
    if kind == "alltoall":
        def compute(slots):
            split = {r: np.array_split(slots[r], world, axis=0) for r in slots}
            return {r: np.concatenate(
                [split[s][r] for s in sorted(split)], axis=0)
                for r in range(world)}

        return compute
    raise ValueError(f"unknown collective descriptor {descriptor}")


class _GroupHubService:
    """Rank 0's RPC surface for the cross-process ("gloo") backend.

    A hub topology: every rank ships its contribution to rank 0's hub,
    which runs the same drain-guarded exchange as the local backend and
    returns the round's result. The reference's gloo groups are likewise
    host-side and rendezvous through a store; a ring/tree is a later
    optimization — correctness and the API contract come first.
    """

    def __init__(self, world_size: int):
        self.state = _GroupState(world_size)

    def exchange(self, rank: int, descriptor: tuple, value):
        compute = _compute_for(descriptor, self.state.world_size)
        return self.state.exchange(rank, value, compute)

    def p2p_send(self, src: int, dst: int, value) -> None:
        self.state.p2p_send(src, dst, value)

    def p2p_recv(self, src: int, dst: int, timeout: float = 60.0):
        return self.state.p2p_recv(src, dst, timeout)


class _DistributedGroup:
    """Client view of a gloo-backend group (duck-types _GroupState usage)."""

    def __init__(self, world_size: int, hub_address: str, hub=None):
        from ray_tpu.core.rpc import RpcClient

        self.world_size = world_size
        self._hub = hub  # rank 0 talks to its hub in-process
        self._client = None if hub is not None else RpcClient(hub_address)

    def exchange_desc(self, rank: int, descriptor: tuple, value):
        if self._hub is not None:
            return self._hub.exchange(rank, descriptor, value)
        return self._client.call("exchange", rank, descriptor, value,
                                 timeout=120.0)

    def p2p_send(self, src: int, dst: int, value) -> None:
        if self._hub is not None:
            self._hub.p2p_send(src, dst, value)
        else:
            self._client.call("p2p_send", src, dst, value, timeout=60.0)

    def p2p_recv(self, src: int, dst: int, timeout: float = 60.0):
        if self._hub is not None:
            return self._hub.p2p_recv(src, dst, timeout)
        return self._client.call("p2p_recv", src, dst, timeout, timeout=None)


@dataclass
class GroupInfo:
    name: str
    world_size: int
    backend: str


_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()
# rank registry keyed by execution context: an actor's rank is visible from
# every thread that executes its methods (actor init and method calls run on
# different threads in the runtime).
_ranks: Dict[tuple, Dict[str, int]] = {}


def _ctx_key() -> tuple:
    try:
        rt = get_runtime()
        aid = rt.current_actor_id
        if aid is not None:
            return ("actor", aid)
    except Exception:
        pass
    return ("thread", threading.get_ident())


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "local",
    group_name: str = "default",
) -> None:
    """Join a named collective group (reference: collective.py:120).

    Every member actor/task calls this with its rank; the group state
    rendezvouses through the process-wide registry (the analog of NCCL
    unique-id exchange via the reference's internal KV).
    """
    if backend not in ("local", "gloo", "xla"):
        raise ValueError(f"unknown backend {backend}")
    if backend == "gloo":
        _init_distributed_group(world_size, rank, group_name)
    else:
        with _groups_lock:
            state = _groups.get(group_name)
            if state is None:
                state = _GroupState(world_size)
                _groups[group_name] = state
            elif state.world_size != world_size:
                raise ValueError(
                    f"group {group_name} exists with world_size={state.world_size}"
                )
    with _groups_lock:
        _ranks.setdefault(_ctx_key(), {})[group_name] = rank
    # Record membership in the control plane for observability.
    try:
        get_runtime().gcs.kv_put(
            f"collective:{group_name}:{rank}", b"1", namespace="collective"
        )
    except Exception:
        pass


def _init_distributed_group(world_size: int, rank: int, group_name: str) -> None:
    """Cross-process backend: rank 0 hosts the hub, its address rendezvouses
    through the control plane's KV (exactly how the reference exchanges the
    NCCL unique id — nccl_collective_group.py via the internal KV)."""
    import time as _time

    gcs = get_runtime().gcs
    kv_key = f"collective:{group_name}:hub"
    with _groups_lock:
        existing = _groups.get(group_name)
        if existing is not None and existing.world_size != world_size:
            raise ValueError(
                f"group {group_name} exists with world_size="
                f"{existing.world_size}")
    if rank == 0:
        from ray_tpu.core.rpc import RpcServer

        hub = _GroupHubService(world_size)
        server = RpcServer(hub, name=f"collective-{group_name}",
                           max_workers=max(8, world_size + 2))
        gcs.kv_put(kv_key, server.address.encode(), namespace="collective")
        group = _DistributedGroup(world_size, server.address, hub=hub)
        group._server = server  # keep alive with the group
        group._kv_key = kv_key
    else:
        deadline = _time.time() + 30.0
        addr = None
        while _time.time() < deadline:
            raw = gcs.kv_get(kv_key, namespace="collective")
            if raw:
                addr = raw.decode()
                break
            _time.sleep(0.05)
        if addr is None:
            raise TimeoutError(
                f"rank 0's hub address never appeared for group {group_name}")
        group = _DistributedGroup(world_size, addr)
    with _groups_lock:
        _groups[group_name] = group  # type: ignore[assignment]


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        state = _groups.pop(group_name, None)
    server = getattr(state, "_server", None)
    if server is not None:  # rank 0 of a gloo group hosts the hub
        server.stop()
        # Drop the rendezvous key so a re-created group can't race a
        # later joiner onto the dead hub's address.
        try:
            get_runtime().gcs.kv_del(getattr(state, "_kv_key", ""),
                                     namespace="collective")
        except Exception:  # noqa: BLE001
            pass


def get_rank(group_name: str = "default") -> int:
    with _groups_lock:
        ranks = _ranks.get(_ctx_key(), {})
        if group_name in ranks:
            return ranks[group_name]
    raise RuntimeError(
        f"init_collective_group must be called in this actor/task first "
        f"(group={group_name})"
    )


def get_collective_group_size(group_name: str = "default") -> int:
    state = _group(group_name)
    return state.world_size


def _group(group_name: str) -> _GroupState:
    with _groups_lock:
        state = _groups.get(group_name)
    if state is None:
        raise RuntimeError(f"collective group '{group_name}' not initialized")
    return state


def _to_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


def allreduce(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:258."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.exchange_desc(rank, ("allreduce", op), _to_numpy(tensor))


def barrier(group_name: str = "default") -> None:
    """reference: collective.py:298."""
    state = _group(group_name)
    state.exchange_desc(get_rank(group_name), ("barrier",), None)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """reference: collective.py:373."""
    state = _group(group_name)
    rank = get_rank(group_name)
    value = _to_numpy(tensor) if rank == src_rank else None
    return state.exchange_desc(rank, ("broadcast", src_rank), value)


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """reference: collective.py:423. Returns list of per-rank tensors."""
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.exchange_desc(rank, ("allgather",), _to_numpy(tensor))


def reducescatter(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:472. Input split along dim 0 across ranks;
    each rank receives its reduced shard."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    shards = state.exchange_desc(rank, ("reducescatter", op), _to_numpy(tensor))
    return shards[rank]


def alltoall(tensor, group_name: str = "default"):
    """Each rank's input is split along dim 0; shard i goes to rank i.

    The host-side analog of XLA ``all_to_all`` (expert-parallel routing).
    """
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.exchange_desc(rank, ("alltoall",), _to_numpy(tensor))[rank]


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """reference: collective.py:531 (p2p)."""
    state = _group(group_name)
    rank = get_rank(group_name)
    state.p2p_send(rank, dst_rank, _to_numpy(tensor))


def recv(src_rank: int, group_name: str = "default", timeout: float = 60.0):
    """reference: collective.py:594 (p2p)."""
    state = _group(group_name)
    rank = get_rank(group_name)
    return state.p2p_recv(src_rank, rank, timeout)
