"""Eager actor-level collectives — the §5.8 API contract.

Analog of the reference's ``ray.util.collective``
(``python/ray/util/collective/collective.py`` — ``init_collective_group``
:120, ``create_collective_group`` :151, ``allreduce`` :258, ``barrier`` :298,
``broadcast`` :373, ``allgather`` :423, ``reducescatter`` :472, ``send``
:531 / ``recv`` :594) re-based for the TPU world:

- **Compiled path (the fast path):** device tensors inside a jitted program
  use XLA collectives over ICI (``psum``/``all_gather``/...) — that path
  lives in the mesh/sharding layer, not here.
- **Eager path (this module):** host-side arrays exchanged between actors in
  a named group — rendezvous through the runtime's control plane exactly the
  way the reference rendezvouses NCCL unique ids through its KV store
  (``nccl_collective_group.py``). The local backend synchronizes ranks with
  barriers and reduces with numpy; it is the Gloo analog and the test
  substrate for multi-host DCN collectives.

The cross-process backend is TOPOLOGY-AWARE, mirroring the two physical
tiers of a TPU pod (fast ICI inside a slice, slower DCN between hosts):
ranks that share a node store (the ICI analog) reduce intra-node through
shm first, node LEADERS run the inter-node ring (the DCN analog) moving
size/num_nodes bytes per node instead of per rank, and results fan back
out intra-node by shm key — the reduce-local / cross-once / broadcast-local
recipe of arXiv:2011.03641 §4 and Podracer (arXiv:2104.06272).
``collective_hierarchy_enabled=0`` restores the flat topology-blind ring.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ray_tpu.core.config import config as _get_config
from ray_tpu.core.runtime import get_runtime
from ray_tpu.util import flightrec
from ray_tpu.utils.logging import get_logger, log_swallowed

logger = get_logger("collectives")

# In-place accumulation kernels: every reduce site accumulates with ufunc
# ``out=`` into a private buffer (mean = sum + one final in-place divide)
# instead of stacking contributions and reducing the stack — no O(world)
# temporary per step.
_UFUNCS = {
    "sum": np.add,
    "prod": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
}


def _acc_dtype(dtype, op: str) -> np.dtype:
    """Accumulator dtype matching numpy's stack-then-reduce promotion rules
    (``np.sum``/``np.prod`` widen sub-word ints to the platform int;
    ``np.mean`` of integral input is float64) so the in-place kernels return
    the same dtypes the old ``np.sum(arrs, axis=0)`` path did."""
    dtype = np.dtype(dtype)
    if op == "mean":
        return dtype if np.issubdtype(dtype, np.inexact) else np.dtype(np.float64)
    if op in ("sum", "prod"):
        if dtype.kind in "bi":
            return np.result_type(dtype, np.int_)
        if dtype.kind == "u":
            return np.result_type(dtype, np.uint)
    return dtype


def _reduce_inplace(op: str, arrs):
    """Reduce a list of arrays with in-place ufunc accumulation. The inputs
    are never mutated: the first contribution is copied into a private
    accumulator (promoting per :func:`_acc_dtype`), the rest accumulate with
    ``out=``. float16 mean keeps ``np.mean``'s float32 intermediate (cast
    back at the end) so half-precision results don't round per
    contribution."""
    acc_op = "sum" if op == "mean" else op
    first = np.asarray(arrs[0])
    out_dt = _acc_dtype(first.dtype, op)
    acc_dt = (np.dtype(np.float32)
              if op == "mean" and out_dt == np.float16 else out_dt)
    acc = first.astype(acc_dt, copy=True)
    uf = _UFUNCS[acc_op]
    for a in arrs[1:]:
        uf(acc, a, out=acc)
    if op == "mean":
        np.divide(acc, len(arrs), out=acc)
    return acc if acc_dt == out_dt else acc.astype(out_dt)


# Public op table (kept for the op-validation contract): each entry reduces
# a LIST of per-rank arrays, now via the in-place kernels above.
_REDUCE_OPS = {op: functools.partial(_reduce_inplace, op)
               for op in ("sum", "prod", "min", "max", "mean")}


def _device_allreduce(slots: Dict[int, "object"], op: str, world: int):
    """Compiled allreduce over the DEVICES the ranks' arrays already live
    on: a 1-D mesh is built from those devices, the per-rank buffers are
    assembled into one global array (``make_array_from_single_device_
    arrays`` — no host round trip), and a jitted ``shard_map`` psum/pmax/
    pmin reduces over the mesh axis. Each rank gets its result shard back
    ON ITS OWN DEVICE — the single-host multi-chip tier of §5.8 (the
    NCCL-group analog; on TPU hardware the reduction rides ICI)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    ranks = sorted(slots)
    arrs = [slots[r] for r in ranks]
    devices = []
    for a in arrs:
        ds = list(a.devices()) if hasattr(a, "devices") else []
        devices.append(ds[0] if len(ds) == 1 else None)
    distinct = (all(d is not None for d in devices)
                and len(set(devices)) == len(devices))
    if not distinct:
        # Co-located (or host) inputs: still a compiled reduction, just on
        # one device — the mesh path needs one device per rank.
        stacked = jnp.stack([jnp.asarray(a) for a in arrs])
        red = _jnp_reduce_fn(op)(stacked)
        return {r: red for r in ranks}

    mesh_devices = tuple(devices)
    expanded = [a[None] for a in arrs]  # computed on each rank's device
    mesh = Mesh(list(mesh_devices), ("r",))
    global_arr = jax.make_array_from_single_device_arrays(
        (len(arrs),) + tuple(arrs[0].shape),
        NamedSharding(mesh, P("r")),
        expanded)
    fn = _device_allreduce_fn(mesh_devices, op, world)
    out = fn(global_arr)
    per = {}
    for shard in out.addressable_shards:
        idx = devices.index(shard.device)
        per[ranks[idx]] = shard.data[0]
    return per


@functools.lru_cache(maxsize=64)
def _device_allreduce_fn(mesh_devices: tuple, op: str, world: int):
    """Jitted shard_map reduction, cached by (devices, op, world) — jit's
    own cache is keyed on function identity, so a fresh closure per call
    would retrace+recompile every allreduce."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(list(mesh_devices), ("r",))

    def body(s):
        if op == "sum":
            return lax.psum(s, "r")
        if op == "mean":
            return lax.psum(s, "r") / world
        if op == "max":
            return lax.pmax(s, "r")
        if op == "min":
            return lax.pmin(s, "r")
        g = lax.all_gather(s, "r", axis=0, tiled=True)
        return jnp.prod(g, axis=0, keepdims=True)

    return jax.jit(jax.shard_map(body, mesh=mesh, in_specs=P("r"),
                                 out_specs=P("r"), check_vma=False))


@functools.lru_cache(maxsize=16)
def _jnp_reduce_fn(op: str):
    import jax
    import jax.numpy as jnp

    fns = {"sum": jnp.sum, "prod": jnp.prod, "min": jnp.min,
           "max": jnp.max, "mean": jnp.mean}
    return jax.jit(functools.partial(fns[op], axis=0))


class _GroupState:
    """Shared rendezvous state for one collective group (local backend)."""

    backend = "local"

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.epoch = 0
        self.slots: Dict[int, np.ndarray] = {}
        self.result = None
        self.arrived = 0
        self.departed = 0
        self._timeout = float(_get_config().collective_timeout_s)
        # Point-to-point mailboxes: (src, dst) -> list of arrays.
        self.p2p: Dict[tuple, List[np.ndarray]] = {}

    def exchange(self, rank: int, value, compute):
        """All ranks deposit, one computes, all withdraw. Returns result."""
        with self.cv:
            # Phase 0: a fast rank can re-enter for the NEXT collective while
            # stragglers are still withdrawing from the previous one; without
            # this drain guard its deposit lands in (and is wiped with) the
            # old round — mixed-epoch corruption.
            while self.arrived == self.world_size or rank in self.slots:
                if not self.cv.wait(timeout=self._timeout):
                    raise TimeoutError(
                        f"collective drain timed out at rank {rank} "
                        f"(prev round: {self.departed}/{self.world_size} departed)"
                    )
            epoch = self.epoch
            self.slots[rank] = value
            self.arrived += 1
            if self.arrived == self.world_size:
                self.result = compute(self.slots)
                self.cv.notify_all()
            else:
                while self.epoch == epoch and self.arrived < self.world_size:
                    if not self.cv.wait(timeout=self._timeout):
                        raise TimeoutError(
                            f"collective timed out at rank {rank} "
                            f"({self.arrived}/{self.world_size} arrived)"
                        )
            result = self.result
            self.departed += 1
            if self.departed == self.world_size:
                # Reset for the next collective on this group.
                self.slots = {}
                self.arrived = 0
                self.departed = 0
                self.result = None
                self.epoch += 1
                self.cv.notify_all()
            return result

    # Descriptor-driven surface shared with the distributed backend.
    def exchange_desc(self, rank: int, descriptor: tuple, value):
        return self.exchange(rank, value,
                             _compute_for(descriptor, self.world_size))

    def p2p_send(self, src: int, dst: int, value) -> None:
        with self.cv:
            self.p2p.setdefault((src, dst), []).append(value)
            self.cv.notify_all()

    def p2p_recv(self, src: int, dst: int, timeout: Optional[float] = None):
        if timeout is None:
            timeout = self._timeout
        key = (src, dst)
        with self.cv:
            while not self.p2p.get(key):
                if not self.cv.wait(timeout=timeout):
                    raise TimeoutError(f"recv from rank {src} timed out")
            return self.p2p[key].pop(0)


class _DeviceGroupState(_GroupState):
    """In-process group whose allreduce runs COMPILED on the ranks' own
    devices (``backend="device"``). Broadcast/allgather hand device arrays
    through untouched; reducescatter/alltoall fall back to the host
    compute (their payloads coerce via numpy)."""

    backend = "device"

    def exchange_desc(self, rank: int, descriptor: tuple, value):
        if descriptor[0] == "allreduce":
            op = descriptor[1]
            per = self.exchange(
                rank, value,
                lambda slots: _device_allreduce(slots, op, self.world_size))
            return per[rank]
        return self.exchange(rank, value,
                             _compute_for(descriptor, self.world_size))


def _compute_for(descriptor: tuple, world: int):
    """Server-side compute for a descriptor-driven collective round.

    Both backends funnel through this: the local backend calls it in
    process, the "gloo" backend's rank-0 hub calls it after all ranks'
    payloads arrive over RPC — one implementation of the math either way.
    """
    kind = descriptor[0]
    if kind == "allreduce":
        op = descriptor[1]
        return lambda slots: _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
    if kind == "barrier":
        return lambda slots: None
    if kind == "broadcast":
        src = descriptor[1]
        return lambda slots: slots[src]
    if kind == "allgather":
        return lambda slots: [slots[r] for r in sorted(slots)]
    if kind == "reducescatter":
        op = descriptor[1]

        def compute(slots):
            reduced = _REDUCE_OPS[op]([slots[r] for r in sorted(slots)])
            return np.array_split(reduced, world, axis=0)

        return compute
    if kind == "alltoall":
        def compute(slots):
            split = {r: np.array_split(slots[r], world, axis=0) for r in slots}
            return {r: np.concatenate(
                [split[s][r] for s in sorted(split)], axis=0)
                for r in range(world)}

        return compute
    raise ValueError(f"unknown collective descriptor {descriptor}")


class _Topology:
    """rank → node grouping for one cross-process group, derived from the
    store names every rank rendezvoused through the GCS group KV: ranks
    publishing the same (non-empty) node-store name share a node — the ICI
    analog; distinct stores are separated by the DCN analog. A rank with no
    reachable store is its own singleton node (no zero-copy plane to share).
    """

    def __init__(self, stores: List[Optional[str]]):
        key_to_idx: Dict[object, int] = {}
        self.node_of: List[int] = []
        for r, s in enumerate(stores):
            key = s if s else ("#solo", r)
            idx = key_to_idx.setdefault(key, len(key_to_idx))
            self.node_of.append(idx)
        self.nodes: List[List[int]] = [[] for _ in key_to_idx]
        for r, idx in enumerate(self.node_of):
            self.nodes[idx].append(r)
        # Node leader = lowest rank sharing the store; leaders alone run the
        # inter-node ring.
        self.leaders = [g[0] for g in self.nodes]

    @property
    def num_nodes(self) -> int:
        return len(self.nodes)

    @property
    def multi_rank_nodes(self) -> bool:
        """True when at least one node hosts >1 rank — the only shape where
        the two-level schedule differs from (and beats) the flat ring."""
        return any(len(g) > 1 for g in self.nodes)


class _ShmIncoming:
    """A chunk delivered by shm reference: the array is a zero-copy view
    into the node's object store; ``close()`` releases the view and acks
    the origin so it can delete the backing object."""

    __slots__ = ("arr", "key", "origin", "_shm", "_closed")

    def __init__(self, arr, key, origin, shm):
        self.arr = arr
        self.key = key
        self.origin = origin
        self._shm = shm
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._shm.release(self.key)
        except Exception:  # noqa: BLE001 — store gone at shutdown
            log_swallowed(logger, "shm release at close")


_TAKE_DEFAULT = object()  # sentinel: "use the service's configured timeout"


class _MemberService:
    """Every rank's RPC surface in the cross-process backend: a tagged
    mailbox. Peers deliver (tag -> payload) messages; the local rank waits
    on its mailbox. Tags are (op_seq, step, src) so concurrent steps of
    pipelined rounds can't mix.

    Same-node peers can deliver big tensors BY SHM REFERENCE
    (``deliver_shm``): the payload crosses as a 16-byte object key; the
    receiver maps a zero-copy view out of the shared arena — the §5.8
    "large host tensors ride the shm object plane" tier."""

    def __init__(self):
        self.lock = threading.Lock()
        self.cv = threading.Condition(self.lock)
        self.box: Dict[tuple, object] = {}
        self.shm = None  # set by the group when a node store is reachable
        # Default blocking-take timeout; the owning group overrides it with
        # its collective_timeout_s.
        self.default_timeout: Optional[float] = 120.0
        # Origin-side: shm chunks awaiting consumer acks -> pending count.
        self._outstanding: Dict[bytes, int] = {}

    def deliver(self, tag: tuple, value) -> None:
        with self.cv:
            self.box[tuple(tag)] = value
            self.cv.notify_all()

    def deliver_shm(self, tag: tuple, key: bytes, shape, dtype: str,
                    origin: int) -> None:
        import numpy as _np

        view = self.shm.get(key) if self.shm is not None else None
        if view is None:
            raise RuntimeError(
                f"shm chunk {key.hex()[:12]} not found in local store")
        arr = _np.frombuffer(view, dtype=_np.dtype(dtype)).reshape(shape)
        with self.cv:
            self.box[tuple(tag)] = _ShmIncoming(arr, key, origin, self.shm)
            self.cv.notify_all()

    def note_outstanding(self, key: bytes, consumers: int) -> None:
        with self.lock:
            self._outstanding[key] = consumers

    def shm_done(self, key: bytes) -> None:
        """Consumer ack: delete the backing object once all consumers of
        this chunk have released their views."""
        with self.lock:
            n = self._outstanding.get(key, 1) - 1
            if n > 0:
                self._outstanding[key] = n
                return
            self._outstanding.pop(key, None)
        if self.shm is not None:
            try:
                self.shm.delete(key)
            except Exception:  # noqa: BLE001 — store gone at shutdown
                log_swallowed(logger, "shm delete after acks")

    def take(self, tag: tuple, timeout=_TAKE_DEFAULT):
        import time as _time

        if timeout is _TAKE_DEFAULT:
            timeout = self.default_timeout
        end = None if timeout is None else _time.time() + timeout
        tag = tuple(tag)
        with self.cv:
            while tag not in self.box:
                if end is None:  # block indefinitely (p2p recv contract)
                    self.cv.wait(timeout=1.0)
                    continue
                remaining = end - _time.time()
                if remaining <= 0:
                    raise TimeoutError(f"collective step {tag} never arrived")
                self.cv.wait(timeout=min(remaining, 1.0))
            return self.box.pop(tag)

    def ping(self) -> str:
        return "pong"


class _DistributedGroup:
    """One rank's view of a cross-process group.

    Two schedules, chosen from the rendezvoused topology:

    - **Two-level (default when some node hosts >1 rank):** intra-node
      reduce through shm into the node leader's private buffer (ufunc
      ``out=`` over peers' zero-copy views), a SEGMENTED PIPELINED ring
      between node leaders moving size/num_nodes bytes per node over the
      cross-node fabric, then an intra-node fan-out by shm key. This is the
      host-side mirror of a TPU pod's ICI/DCN hierarchy (§5.8).
    - **Flat ring** (``collective_hierarchy_enabled=0``, or no shared
      stores): ring reduce-scatter/allgather over all ranks — each rank
      moves O(size) bytes per allreduce regardless of world size. The
      reduce phase is segmented the same way, so segment k's in-place
      reduction overlaps segment k+1's transfer.
    """

    # Payloads at or above this ride the shm object plane between
    # same-node ranks (below it, the socket path's latency wins).
    SHM_MIN_BYTES = 1 << 20

    # Class-level defaults so partially-constructed instances (unit tests
    # build the group via ``object.__new__``) still run the flat paths.
    _timeout = 120.0
    _segment_bytes = 1 << 20
    _hier = False
    _topo: Optional[_Topology] = None
    stats: Optional[Dict[str, int]] = None

    def __init__(self, world_size: int, rank: int, addrs: List[str],
                 service: _MemberService, server,
                 stores: Optional[List[Optional[str]]] = None,
                 hierarchy: Optional[bool] = None):
        from ray_tpu.core.rpc import RpcClientPool

        cfg = _get_config()
        self.world_size = world_size
        self.rank = rank
        self._addrs = addrs
        self._service = service
        self._server = server  # keeps the member server alive
        self._peers = RpcClientPool()
        self._op_seq = 0
        self._op_lock = threading.Lock()
        self._timeout = float(cfg.collective_timeout_s)
        self._segment_bytes = max(4096, int(cfg.collective_segment_size))
        service.default_timeout = self._timeout
        # Same-node shm fast path: ranks publishing the same store name
        # share one arena; big chunks cross as object keys. The stores list
        # is the KV-RENDEZVOUSED view — identical on every rank — and it
        # alone decides the topology/schedule; a rank whose own store failed
        # to open published "" (so everyone, itself included, sees it as a
        # solo node) and gates only its local shm TRANSPORT off via
        # ``self._shm`` — zeroing the whole list here would make this rank
        # pick the flat schedule while its peers run the hierarchy, and
        # their tags would never pair.
        self._stores = stores or [None] * world_size
        # The store handle is opened by _init_distributed_group BEFORE the
        # rank's address is published (a peer may deliver_shm the moment it
        # can see us); here we just adopt it off the service.
        self._shm = service.shm
        self._topo = _Topology(self._stores)
        self._hier = (bool(cfg.collective_hierarchy_enabled)
                      if hierarchy is None else bool(hierarchy))
        # Instrumentation: logical payload bytes sent, split by whether
        # the destination shares this rank's store (the DCN-analog
        # "cross-store" traffic is what the hierarchy minimizes), plus
        # which schedule each reduction round took.
        self.stats = {"bytes_cross_store": 0, "bytes_same_store": 0,
                      "hier_rounds": 0, "flat_rounds": 0}
        # Homogeneous single-node group: broadcast can write once and
        # circulate one key through the whole tree.
        self._all_same_store = bool(
            self._stores[0]
            and all(s == self._stores[0] for s in self._stores))

    # -- plumbing -----------------------------------------------------------

    def _next_seq(self) -> int:
        with self._op_lock:
            self._op_seq += 1
            return self._op_seq

    def _use_hier(self) -> bool:
        return (self._hier and self._topo is not None
                and self._topo.multi_rank_nodes and self.world_size > 1)

    def _acct(self, dst: int, nbytes: int) -> None:
        st = self.stats
        if st is None or not nbytes:
            return
        same = (self._stores[dst] is not None
                and self._stores[dst] == self._stores[self.rank])
        st["bytes_same_store" if same else "bytes_cross_store"] += int(nbytes)

    def _send(self, dst: int, tag: tuple, value) -> None:
        if dst == self.rank:
            self._service.deliver(tag, value)
            return
        self._acct(dst, getattr(value, "nbytes", 0))
        self._peers.get(self._addrs[dst]).call(
            "deliver", tag, value, timeout=self._timeout)

    @staticmethod
    def _bc_subtree_consumers(rel: int, n: int) -> int:
        """How many DESCENDANTS of relative rank ``rel`` in the binomial
        broadcast tree will receive (and ack) a key published by ``rel``.
        Node ``rel`` owns children ``rel + 2^k`` for ``2^k > rel`` while
        ``rel + 2^k < n``; descendants ack recursively. Publishing with
        ``n - 1`` on a non-root republisher (root's publish failed, chunk
        arrived by socket) would leave ``shm_done`` forever short — only
        the republisher's own subtree ever acks."""
        count = 0
        k = 1
        while k < n:
            if rel < k and rel + k < n:
                child = rel + k
                count += 1 + _DistributedGroup._bc_subtree_consumers(child, n)
            k *= 2
        return count

    def _ring_shm_consumers(self, ring: List[int], start_pos: int,
                            hops: int) -> int:
        """How many CONSECUTIVE downstream ring receivers (starting at ring
        position ``start_pos``, following the ring for ``hops`` hops) share
        this rank's store. Only those receive the chunk BY KEY and ack; once
        the ring crosses to a different store the chunk continues as socket
        copies — counting those would leave the backing object undeletable."""
        m = len(ring)
        count = 0
        for i in range(hops):
            r = ring[(start_pos + i) % m]
            if self._stores[r] != self._stores[self.rank]:
                break
            count += 1
        return count

    def _send_async(self, dst: int, tag: tuple, value, *,
                    consumers: int = 1, holder=None):
        """Fire-and-overlap send: returns a future (or None for self-
        delivery). Ring steps overlap their outgoing transfer with the
        blocking wait for the incoming one — full-duplex links move both
        directions at once instead of serializing on the deliver ack.

        Big numpy payloads to SAME-NODE peers go by shm reference: one
        copy into the shared arena, a 16-byte key over the socket, a
        zero-copy view on the other side. A chunk already BACKED by shm
        (``holder``) is forwarded by key — zero copies on any hop;
        ``consumers`` (total ranks that will ack) is fixed by the
        creator."""
        if dst == self.rank:
            self._service.deliver(tag, value)
            return None
        same_store = (self._shm is not None
                      and self._stores[dst] == self._stores[self.rank])
        if holder is not None and same_store:
            return self._peers.get(self._addrs[dst]).call_async(
                "deliver_shm", tag, holder.key, value.shape,
                value.dtype.str, holder.origin)
        if (same_store
                and isinstance(value, np.ndarray)
                and value.nbytes >= self.SHM_MIN_BYTES
                and consumers > 0):
            key = self._publish_shm(value, consumers)
            if key is not None:
                return self._peers.get(self._addrs[dst]).call_async(
                    "deliver_shm", tag, key, value.shape, value.dtype.str,
                    self.rank)
            # Arena full: fall through to the socket path.
        self._acct(dst, getattr(value, "nbytes", 0))
        return self._peers.get(self._addrs[dst]).call_async(
            "deliver", tag, value)

    def _publish_shm(self, arr: np.ndarray, consumers: int) -> Optional[bytes]:
        """Seal one shm object holding ``arr``; returns its key (None when
        the arena is full). The creator expects ``consumers`` acks before
        deleting."""
        import os as _os

        key = _os.urandom(16)
        view = self._shm.create(key, arr.nbytes)
        if view is None:
            return None
        flat = np.frombuffer(view, dtype=arr.dtype)
        flat[:] = np.ascontiguousarray(arr).reshape(-1)
        self._shm.seal(key)
        self._service.note_outstanding(key, consumers)
        if self.stats is not None:
            self.stats["bytes_same_store"] += int(arr.nbytes)
        return key

    def _materialize(self, incoming):
        """(ndarray, holder) for a received chunk. shm-delivered chunks
        come back as zero-copy views with a non-None holder: the caller
        uses the array, then MUST call ``_finish_consume(holder)`` (a
        caller that keeps the array beyond the step copies it first)."""
        if isinstance(incoming, _ShmIncoming):
            return incoming.arr, incoming
        return np.asarray(incoming), None

    def _ack_shm(self, incoming: "_ShmIncoming") -> None:
        try:
            self._peers.get(self._addrs[incoming.origin]).notify(
                "shm_done", incoming.key)
        except Exception:  # noqa: BLE001 — origin gone; its store reaps
            log_swallowed(logger, "shm consumer ack")

    def _finish_consume(self, holder) -> None:
        if holder is not None:
            holder.close()
            self._ack_shm(holder)

    def _recv(self, tag: tuple, timeout: Optional[float] = None):
        return self._service.take(
            tag, self._timeout if timeout is None else timeout)

    def _segment_slices(self, n_elems: int, itemsize: int) -> List[slice]:
        """Split a 1-D chunk into ``collective_segment_size``-byte segments.
        Both ring ends compute the same split from the (globally agreed)
        chunk length, so segment tags pair up without negotiation."""
        if n_elems == 0:
            return []
        seg = max(1, self._segment_bytes // max(1, itemsize))
        return [slice(i, min(i + seg, n_elems))
                for i in range(0, n_elems, seg)]

    def _chunk_segments(self, peer: int, n_elems: int,
                        itemsize: int) -> List[slice]:
        """Segmentation policy for one ring hop: chunks CROSSING stores
        (the inter-node / DCN-analog hop) are segmented so reduction
        overlaps transfer; same-store chunks ride shm whole — one key, one
        arena copy, zero-copy reduce (per-segment objects would only add
        RPC overhead on the fast tier). Sender and receiver derive the same
        split from the shared topology, so tags pair up."""
        if n_elems == 0:
            return []
        if (self._shm is not None
                and self._stores[peer] == self._stores[self.rank]):
            return [slice(0, n_elems)]
        return self._segment_slices(n_elems, itemsize)

    # -- collectives --------------------------------------------------------

    def exchange_desc(self, rank: int, descriptor: tuple, value):
        assert rank == self.rank
        kind = descriptor[0]
        seq = self._next_seq()
        hier = self._use_hier()
        if kind == "allreduce":
            return self._allreduce(seq, value, descriptor[1])
        if kind == "reducescatter":
            if hier:
                reduced = self._hier_reduce_scatter(seq, value, descriptor[1])
            else:
                reduced = self._reduce_scatter(seq, value, descriptor[1])
            # API contract: caller indexes [rank]; return full split list
            # shape-compatible with the local backend.
            out = [None] * self.world_size
            out[self.rank] = reduced
            return out
        if kind == "allgather":
            if hier:
                return self._hier_allgather(seq, value)
            return self._allgather(seq, value)
        if kind == "broadcast":
            if hier:
                return self._hier_broadcast(seq, value, descriptor[1])
            return self._broadcast(seq, value, descriptor[1])
        if kind == "barrier":
            # 1-byte payloads: the flat ring's latency is the floor either
            # way; the two-level schedule only adds hops here.
            self._allgather(seq, np.zeros(1, dtype=np.uint8))
            return None
        if kind == "alltoall":
            return {self.rank: self._alltoall(seq, value)}
        raise ValueError(f"unknown collective descriptor {descriptor}")

    # -- ring engine --------------------------------------------------------

    def _ring_allreduce_inplace(self, seq: int, buf: np.ndarray, acc_op: str,
                                ring: List[int], phase: str = "r",
                                src: Optional[np.ndarray] = None) -> None:
        """Segmented pipelined ring allreduce over the ranks in ``ring``
        (all of which must be calling this with the same ring),
        accumulating IN PLACE into the 1-D ``buf`` — the caller owns the
        buffer and applies any mean division afterwards.

        ``src`` (optional, same length) carries this rank's ORIGINAL
        contribution with ``buf`` left uninitialized: each chunk's first
        accumulation then reads straight from the input into ``buf``
        (``uf(src, incoming, out=buf)``) and step-0 sends ship input views
        — the full-size private entry copy disappears.

        Phase 1 (reduce-scatter) moves each store-crossing chunk as
        ``collective_segment_size`` segments: the peer posts every segment
        up front (persistent per-peer connection, sends overlap), so
        segment k's in-place reduction here overlaps segment k+1's
        transfer; same-store chunks ride shm whole. Phase 2 (allgather)
        circulates each owner's fully-reduced chunk whole — published to
        shm once and forwarded BY KEY between same-store ranks — and lands
        it straight into ``buf`` (no final concatenate)."""
        m = len(ring)
        if m == 1:
            if src is not None:
                np.copyto(buf, src)
            return
        pos = ring.index(self.rank)
        nxt = ring[(pos + 1) % m]
        prv = ring[(pos - 1) % m]
        uf = _UFUNCS[acc_op]
        chunks = np.array_split(buf, m)  # views into buf
        src_chunks = np.array_split(src, m) if src is not None else chunks
        touched = [src is None] * m
        rs_tag, ag_tag = phase + "rs", phase + "ag"
        # Phase 1 — after step s, this rank holds the running reduction of
        # chunk (pos - s) % m over s+1 contributors.
        for step in range(m - 1):
            send_idx = (pos - step) % m
            recv_idx = (pos - step - 1) % m
            out_chunk = (chunks if touched[send_idx]
                         else src_chunks)[send_idx]
            futs = [self._send_async(nxt, (seq, rs_tag, step, g),
                                     out_chunk[sl])
                    for g, sl in enumerate(self._chunk_segments(
                        nxt, len(out_chunk), out_chunk.itemsize))]
            dst = chunks[recv_idx]
            first = not touched[recv_idx]
            for g, sl in enumerate(self._chunk_segments(prv, len(dst),
                                                        dst.itemsize)):
                arr, holder = self._materialize(
                    self._recv((seq, rs_tag, step, g)))
                seg = dst[sl]
                uf(src_chunks[recv_idx][sl] if first else seg, arr, out=seg)
                self._finish_consume(holder)
            touched[recv_idx] = True
            for fut in futs:
                if fut is not None:
                    fut.result(timeout=self._timeout)
        # Phase 2 — allgather the reduced chunks around the ring. Each
        # reduced chunk is written to shm ONCE by its owner and then
        # FORWARDED BY KEY: every same-store rank reads the same backing
        # object, copies its range into ``buf``, forwards, and acks.
        holders: List[Optional[_ShmIncoming]] = [None] * m
        for step in range(m - 1):
            send_idx = (pos + 1 - step) % m
            recv_idx = (pos - step) % m
            # consumers = the consecutive same-store receivers downstream
            # of THIS send (the chunk has m-1-step hops left; once the
            # ring crosses stores it continues as socket copies that never
            # ack — counting them would leak the backing object).
            fut = self._send_async(
                nxt, (seq, ag_tag, step), chunks[send_idx],
                consumers=self._ring_shm_consumers(ring, (pos + 1) % m,
                                                   m - 1 - step),
                holder=holders[send_idx])
            arr, holder = self._materialize(self._recv((seq, ag_tag, step)))
            np.copyto(chunks[recv_idx], arr)
            holders[recv_idx] = holder  # kept for the key-forward next step
            if fut is not None:
                fut.result(timeout=self._timeout)
        for h in holders:
            self._finish_consume(h)

    def _allreduce(self, seq: int, value, op: str):
        n = self.world_size
        arr = np.asarray(value)
        if n == 1:
            return _REDUCE_OPS[op]([arr])
        orig_shape = arr.shape
        arr = np.atleast_1d(arr)
        acc_op = "sum" if op == "mean" else op
        if self._use_hier():
            return self._hier_allreduce(seq, arr, acc_op,
                                        op).reshape(orig_shape)
        if self.stats is not None:
            self.stats["flat_rounds"] += 1
        # Private working buffer; the caller's input is never mutated. When
        # no dtype promotion is needed, the buffer starts EMPTY and each
        # chunk's first accumulation reads the input directly (``src``) —
        # no full-size entry copy. Promoting ops (int sum/prod, int mean)
        # pre-copy so every accumulation runs in the promoted dtype.
        acc_dt = _acc_dtype(arr.dtype, op)
        flat_in = np.ascontiguousarray(arr).reshape(-1)
        if flat_in.dtype == acc_dt:
            buf = np.empty(flat_in.size, dtype=acc_dt)
            self._ring_allreduce_inplace(seq, buf, acc_op, list(range(n)),
                                         src=flat_in)
        else:
            buf = flat_in.astype(acc_dt)
            self._ring_allreduce_inplace(seq, buf, acc_op, list(range(n)))
        if op == "mean":
            np.divide(buf, n, out=buf)
        return buf.reshape(orig_shape)

    # -- two-level schedule -------------------------------------------------

    def _reduce_to_leader(self, seq: int, arr: np.ndarray, acc_op: str,
                          op: str) -> Optional[np.ndarray]:
        """Intra-node reduce (the ICI-analog tier): non-leaders ship their
        ORIGINAL array to the node leader — by shm reference when big
        enough, with no intermediate promote-copy — and the leader
        accumulates IN PLACE into a private promoted buffer over the
        incoming zero-copy views. Returns that buffer on the leader;
        non-leaders return None and await the fan-out."""
        topo = self._topo
        group = topo.nodes[topo.node_of[self.rank]]
        leader = group[0]
        if self.rank != leader:
            fut = self._send_async(leader, (seq, "hup", self.rank),
                                   np.ascontiguousarray(arr))
            if fut is not None:
                fut.result(timeout=self._timeout)
            return None
        acc_dt = _acc_dtype(arr.dtype, op)
        uf = _UFUNCS[acc_op]
        buf = None
        for peer in group[1:]:
            inc, holder = self._materialize(self._recv((seq, "hup", peer)))
            if buf is not None:
                uf(buf, inc.reshape(buf.shape), out=buf)
            elif arr.dtype == acc_dt:
                # First accumulation ALLOCATES the private buffer (one
                # fused read-read-write pass instead of copy-then-add).
                buf = uf(arr, inc.reshape(arr.shape), dtype=acc_dt)
            else:
                buf = arr.astype(acc_dt, order="C", copy=True)
                uf(buf, inc.reshape(buf.shape), out=buf)
            self._finish_consume(holder)
        if buf is None:  # leader with no node peers
            buf = arr.astype(acc_dt, order="C", copy=True)
        # The inter-node ring and fan-out flatten this buffer with
        # reshape(-1), which must be a VIEW: a non-C-contiguous buffer
        # (astype order='K' preserves an F-ordered input's layout) would
        # silently detach the flat copy from buf.
        if not buf.flags.c_contiguous:
            buf = np.ascontiguousarray(buf)
        return buf

    def _take_down(self, seq: int, tag: str):
        """Receive a fan-out result: a socket-delivered payload is already
        private and returns WITHOUT a copy; only shm views (whose backing
        object dies with the ack) detach."""
        inc, holder = self._materialize(self._recv((seq, tag, self.rank)))
        if holder is not None:
            inc = np.array(inc)
            self._finish_consume(holder)
        return inc

    def _fan_out(self, seq: int, tag: str, arr: np.ndarray,
                 peers: List[int]) -> None:
        """Intra-node distribution: publish ``arr`` to shm ONCE and hand
        every peer the key (each acks; the object dies after the last),
        falling back to per-peer socket sends when small or arena-full."""
        if not peers:
            return
        futs = []
        key = None
        if (self._shm is not None and isinstance(arr, np.ndarray)
                and arr.nbytes >= self.SHM_MIN_BYTES):
            key = self._publish_shm(arr, len(peers))
        for p in peers:
            if key is not None:
                futs.append(self._peers.get(self._addrs[p]).call_async(
                    "deliver_shm", (seq, tag, p), key, arr.shape,
                    arr.dtype.str, self.rank))
            else:
                futs.append(self._send_async(p, (seq, tag, p), arr))
        for fut in futs:
            if fut is not None:
                fut.result(timeout=self._timeout)

    def _hier_allreduce(self, seq: int, arr: np.ndarray, acc_op: str,
                        op: str) -> np.ndarray:
        """Two-level allreduce of ``arr`` (atleast-1d, never mutated):
        intra-node reduce at the leader, segmented ring between node
        leaders moving size/num_nodes bytes per node across the slow
        fabric, one final in-place mean divide, then fan-out by shm key."""
        if self.stats is not None:
            self.stats["hier_rounds"] += 1
        topo = self._topo
        group = topo.nodes[topo.node_of[self.rank]]
        buf = self._reduce_to_leader(seq, arr, acc_op, op)
        if buf is None:
            return self._take_down(seq, "hdn").reshape(arr.shape)
        flat = buf.reshape(-1)
        if topo.num_nodes > 1:
            self._ring_allreduce_inplace(seq, flat, acc_op, topo.leaders,
                                         phase="h")
        if op == "mean":
            np.divide(flat, self.world_size, out=flat)
        self._fan_out(seq, "hdn", flat, group[1:])
        return buf

    def _hier_reduce_scatter(self, seq: int, value, op: str):
        """Two-level reduce-scatter: intra-node reduce to the leader,
        leaders allreduce over the ring, leader hands each node peer ONLY
        its own ``slots[rank]`` slice (zero-copy by shm key when big)."""
        n = self.world_size
        arr = np.asarray(value)
        acc_op = "sum" if op == "mean" else op
        topo = self._topo
        group = topo.nodes[topo.node_of[self.rank]]
        buf = self._reduce_to_leader(seq, arr, acc_op, op)
        if buf is None:
            return self._take_down(seq, "hdn")
        if topo.num_nodes > 1:
            self._ring_allreduce_inplace(seq, buf.reshape(-1), acc_op,
                                         topo.leaders, phase="h")
        if op == "mean":
            np.divide(buf, n, out=buf)
        split = np.array_split(buf, n, axis=0)
        futs = [self._send_async(p, (seq, "hdn", p), split[p])
                for p in group[1:]]
        for fut in futs:
            if fut is not None:
                fut.result(timeout=self._timeout)
        return split[self.rank]

    def _hier_allgather(self, seq: int, value) -> List[np.ndarray]:
        """Two-level allgather: each node's leader collects its ranks'
        arrays, leaders circulate ONE block per node around their ring
        (each node's data crosses the slow fabric once per hop instead of
        once per rank), and leaders hand the assembled result back down."""
        topo = self._topo
        n = self.world_size
        group = topo.nodes[topo.node_of[self.rank]]
        leader = group[0]
        arr = np.asarray(value)
        if self.rank != leader:
            fut = self._send_async(leader, (seq, "gup", self.rank), arr)
            if fut is not None:
                fut.result(timeout=self._timeout)
            # Equal-shape results arrive STACKED as one ndarray (published
            # to shm once per node by the leader); ragged results arrive as
            # a pickled list over the socket.
            got = self._recv((seq, "gdn", self.rank))
            if isinstance(got, list):
                return got
            stacked, holder = self._materialize(got)
            if holder is not None:
                stacked = np.array(stacked)  # detach from shm before ack
                self._finish_consume(holder)
            return [stacked[i] for i in range(len(stacked))]
        block = {self.rank: arr}
        for peer in group[1:]:
            a, holder = self._materialize(self._recv((seq, "gup", peer)))
            if holder is not None:
                a = np.array(a)  # kept past the step: detach from shm
                self._finish_consume(holder)
            block[peer] = a
        blocks = {topo.node_of[self.rank]: block}
        ring = topo.leaders
        m = len(ring)
        if m > 1:
            pos = ring.index(self.rank)
            nxt = ring[(pos + 1) % m]
            carry = (topo.node_of[self.rank], block)
            for step in range(m - 1):
                fut = self._send_async(nxt, (seq, "hga", step), carry)
                carry = self._recv((seq, "hga", step))
                blocks[carry[0]] = carry[1]
                if fut is not None:
                    fut.result(timeout=self._timeout)
        out: List[Optional[np.ndarray]] = [None] * n
        for blk in blocks.values():
            for r, a in blk.items():
                out[r] = a
        if group[1:]:
            same = all(isinstance(a, np.ndarray) and a.shape == out[0].shape
                       and a.dtype == out[0].dtype for a in out)
            if same:
                # One stacked array fans down by shm key (one arena write
                # per node) instead of pickling the full result list once
                # per peer through the socket.
                self._fan_out(seq, "gdn", np.stack(out), group[1:])
            else:
                futs = [self._send_async(p, (seq, "gdn", p), out)
                        for p in group[1:]]
                for fut in futs:
                    if fut is not None:
                        fut.result(timeout=self._timeout)
        return out  # type: ignore[return-value]

    def _hier_broadcast(self, seq: int, value, src: int):
        """Two-level broadcast: the root sends ONE copy per remote node (to
        its leader, crossing the slow fabric once per node), and every
        node's distributor fans out intra-node by shm key."""
        topo = self._topo
        my_node = topo.node_of[self.rank]
        src_node = topo.node_of[src]
        if self.rank == src:
            arr = np.asarray(value)
            futs = []
            for nidx, grp in enumerate(topo.nodes):
                if nidx == src_node:
                    continue
                futs.append(self._send_async(grp[0], (seq, "hbc", grp[0]),
                                             arr))
            # The root distributes within its own node (even when it is not
            # the node leader — one fewer intra-node hop).
            self._fan_out(seq, "hbc", arr,
                          [r for r in topo.nodes[src_node] if r != src])
            for fut in futs:
                if fut is not None:
                    fut.result(timeout=self._timeout)
            return arr
        arr, holder = self._materialize(self._recv((seq, "hbc", self.rank)))
        if my_node != src_node and self.rank == topo.nodes[my_node][0]:
            self._fan_out(seq, "hbc", arr,
                          [r for r in topo.nodes[my_node] if r != self.rank])
        if holder is not None:
            arr = np.array(arr)  # result is returned to the caller
            self._finish_consume(holder)
        return arr

    # -- flat schedule ------------------------------------------------------

    def _reduce_scatter(self, seq: int, value, op: str):
        n = self.world_size
        arr = np.asarray(value)
        if n == 1:
            return _REDUCE_OPS[op]([arr])
        acc_op = "sum" if op == "mean" else op
        # Private promoted copy: ring steps accumulate in place into its
        # chunk views (axis-0 split — the slots[rank] contract).
        buf = arr.astype(_acc_dtype(arr.dtype, op), copy=True)
        chunks = np.array_split(buf, n, axis=0)
        uf = _UFUNCS[acc_op]
        nxt = (self.rank + 1) % n
        for step in range(n - 1):
            send_idx = (self.rank - step) % n
            recv_idx = (self.rank - step - 1) % n
            fut = self._send_async(nxt, (seq, "rs", step), chunks[send_idx])
            arr_in, holder = self._materialize(self._recv((seq, "rs", step)))
            uf(chunks[recv_idx], arr_in, out=chunks[recv_idx])
            self._finish_consume(holder)
            if fut is not None:
                fut.result(timeout=self._timeout)
        owned = (self.rank + 1) % n
        res = chunks[owned]
        if op == "mean":
            np.divide(res, n, out=res)
        # Rotate so the API's slots[rank] convention holds: ring ownership
        # is chunk (rank+1)%n; the contract gives rank its OWN index. The
        # rotation rides the async path (big chunks cross by shm key); the
        # received chunk is copied ONLY when an shm holder is attached — a
        # socket-delivered chunk is already private.
        fut = self._send_async((self.rank + 1) % n, (seq, "rsrot", 0), res)
        out, holder = self._materialize(self._recv((seq, "rsrot", 0)))
        if holder is not None:
            out = np.array(out)  # returned to the caller: detach from shm
            self._finish_consume(holder)
        if fut is not None:
            fut.result(timeout=self._timeout)
        return out

    def _allgather(self, seq: int, value) -> List[np.ndarray]:
        n = self.world_size
        out: List[Optional[np.ndarray]] = [None] * n
        out[self.rank] = np.asarray(value)
        if n == 1:
            return out  # type: ignore[return-value]
        nxt = (self.rank + 1) % n
        carry_idx = self.rank
        for step in range(n - 1):
            fut = self._send_async(nxt, (seq, "ag", step), out[carry_idx])
            carry_idx = (self.rank - step - 1) % n
            arr, holder = self._materialize(self._recv((seq, "ag", step)))
            if holder is not None:
                arr = np.array(arr)
                self._finish_consume(holder)
            out[carry_idx] = arr
            if fut is not None:
                fut.result(timeout=self._timeout)
        return out  # type: ignore[return-value]

    def _broadcast(self, seq: int, value, src: int):
        """Binomial tree: log2(N) rounds, no rank sends more than
        ceil(log2 N) copies (vs the hub serializing N sends)."""
        n = self.world_size
        rel = (self.rank - src) % n
        holder = None
        if rel != 0:
            arr, holder = self._materialize(self._recv((seq, "bc", rel)))
        else:
            arr = np.asarray(value)
        # Forward to children in the binomial tree over RELATIVE ranks:
        # node `rel` owns children rel + 2^k for 2^k > rel. Sends overlap
        # (async); on a homogeneous same-store group the payload is
        # written to shm ONCE (by the root) and the whole tree circulates
        # its key — every forward hop is a 16-byte message.
        children = []
        k = 1
        while k < n:
            if rel < k and rel + k < n:
                children.append(rel + k)
            k *= 2
        futs = []
        key_holder = holder
        if (children and key_holder is None and self._all_same_store
                and self._shm is not None and isinstance(arr, np.ndarray)
                and arr.nbytes >= self.SHM_MIN_BYTES):
            key = self._publish_shm(
                arr, self._bc_subtree_consumers(rel, n))
            if key is not None:
                # Root-side pseudo-holder: carries the key for forwarding;
                # the root itself never acks/closes it.
                key_holder = _ShmIncoming(arr, key, self.rank, self._shm)
        for child_rel in children:
            if key_holder is not None and self._all_same_store:
                futs.append(self._peers.get(
                    self._addrs[(src + child_rel) % n]).call_async(
                    "deliver_shm", (seq, "bc", child_rel), key_holder.key,
                    arr.shape, arr.dtype.str, key_holder.origin))
            else:
                futs.append(self._send_async(
                    (src + child_rel) % n, (seq, "bc", child_rel), arr))
        for fut in futs:
            if fut is not None:
                fut.result(timeout=self._timeout)
        if holder is not None:
            arr = np.array(arr)  # result is returned to the caller
            self._finish_consume(holder)
        return arr

    def _alltoall(self, seq: int, value):
        n = self.world_size
        shards = np.array_split(np.asarray(value), n, axis=0)
        futs = []
        for dst in range(n):
            if dst != self.rank:
                futs.append(self._send_async(
                    dst, (seq, "a2a", self.rank), shards[dst]))
        pieces = []
        holders = []
        for s in range(n):
            if s == self.rank:
                pieces.append(shards[self.rank])
            else:
                arr, holder = self._materialize(self._recv((seq, "a2a", s)))
                pieces.append(arr)
                if holder is not None:
                    holders.append(holder)
        result = np.concatenate(pieces, axis=0)  # copies: views die after
        for h in holders:
            self._finish_consume(h)
        for fut in futs:
            if fut is not None:
                fut.result(timeout=self._timeout)
        return result

    # -- p2p ----------------------------------------------------------------

    def p2p_send(self, src: int, dst: int, value) -> None:
        self._send(dst, ("p2p", src, dst,
                         self._p2p_counter(src, dst, "send")), value)

    def p2p_recv(self, src: int, dst: int,
                 timeout: Optional[float] = None):
        # Matching monotone counters on both ends keep repeated send/recv
        # pairs FIFO-ordered. The cursor is RESERVED under the lock before
        # blocking — two concurrent recvs for the same (src, dst) get
        # distinct tags instead of racing for one message and stranding the
        # loser on a tag the sender has moved past. A timed-out recv rolls
        # its reservation back (only if it is still the newest — with a
        # later recv outstanding the gap is unrecoverable either way) so a
        # single-threaded retry consumes the late-arriving message.
        if timeout is None:
            timeout = self._timeout
        key = ("p2p_ctr", src, dst, "recv")
        with self._op_lock:
            d = getattr(self, "_p2p_counts", None)
            if d is None:
                d = self._p2p_counts = {}
            nxt = d.get(key, 0) + 1
            d[key] = nxt
        try:
            return self._recv(("p2p", src, dst, nxt), timeout)
        except BaseException:
            with self._op_lock:
                if self._p2p_counts.get(key) == nxt:
                    self._p2p_counts[key] = nxt - 1
            raise

    def _p2p_counter(self, src: int, dst: int, direction: str) -> int:
        key = ("p2p_ctr", src, dst, direction)
        with self._op_lock:
            d = getattr(self, "_p2p_counts", None)
            if d is None:
                d = self._p2p_counts = {}
            d[key] = d.get(key, 0) + 1
            return d[key]


@dataclass
class GroupInfo:
    name: str
    world_size: int
    backend: str


_groups: Dict[str, _GroupState] = {}
_groups_lock = threading.Lock()
# rank registry keyed by execution context: an actor's rank is visible from
# every thread that executes its methods (actor init and method calls run on
# different threads in the runtime).
_ranks: Dict[tuple, Dict[str, int]] = {}


def _ctx_key() -> tuple:
    try:
        rt = get_runtime()
        aid = rt.current_actor_id
        if aid is not None:
            return ("actor", aid)
    except Exception:  # noqa: BLE001 — no runtime: plain thread context
        log_swallowed(logger, "runtime lookup in _ctx_key")
    return ("thread", threading.get_ident())


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "local",
    group_name: str = "default",
) -> None:
    """Join a named collective group (reference: collective.py:120).

    Every member actor/task calls this with its rank; the group state
    rendezvouses through the process-wide registry (the analog of NCCL
    unique-id exchange via the reference's internal KV).
    """
    if backend not in ("local", "gloo", "ring", "device", "xla"):
        raise ValueError(f"unknown backend {backend}")
    if backend == "xla":
        # No silent fallback: inside jit'ed programs device tensors already
        # use XLA collectives over ICI via jax.sharding; the EAGER device
        # tier is backend="device" (single-host multi-chip: a compiled
        # psum over the devices the ranks' arrays live on). Multi-host
        # eager device collectives require a jax.distributed world, which
        # this runtime wires through the mesh/Train layer.
        raise RuntimeError(
            "backend='xla' is the compiled path: device tensors inside "
            "jit'ed programs already use XLA collectives over ICI via "
            "jax.sharding (see ray_tpu.parallel.mesh / JaxTrainer). For "
            "eager collectives between actors use backend='device' "
            "(same-host device arrays, compiled psum over their chips), "
            "'gloo' (host tensors, ring over sockets) or 'local' "
            "(in-process).")
    if backend in ("gloo", "ring"):
        _init_distributed_group(world_size, rank, group_name)
    else:
        cls = _DeviceGroupState if backend == "device" else _GroupState
        with _groups_lock:
            state = _groups.get(group_name)
            if state is None:
                state = cls(world_size)
                _groups[group_name] = state
            elif state.world_size != world_size:
                raise ValueError(
                    f"group {group_name} exists with world_size={state.world_size}"
                )
            elif type(state) is not cls:
                raise ValueError(
                    f"group {group_name} exists with backend="
                    f"{state.backend!r}")
    with _groups_lock:
        _ranks.setdefault(_ctx_key(), {})[group_name] = rank
    # Record membership in the control plane for observability.
    try:
        get_runtime().gcs.kv_put(
            f"collective:{group_name}:{rank}", b"1", namespace="collective"
        )
    except Exception:  # noqa: BLE001 — observability only
        log_swallowed(logger, "membership kv_put")


def _init_distributed_group(world_size: int, rank: int, group_name: str) -> None:
    """Cross-process backend: every rank hosts a member mailbox server and
    publishes its address — AND its node-store name + hierarchy vote, the
    topology rendezvous — through the control plane's KV (exactly how the
    reference exchanges the NCCL unique id — nccl_collective_group.py via
    the internal KV); collectives then run rank-to-rank over the two-level
    or flat schedule with no hub."""
    import time as _time

    from ray_tpu.core.rpc import RpcServer

    with _groups_lock:
        existing = _groups.get(group_name)
        if existing is not None and existing.world_size != world_size:
            raise ValueError(
                f"group {group_name} exists with world_size="
                f"{existing.world_size}")

    import os as _os

    cfg = _get_config()
    gcs = get_runtime().gcs
    service = _MemberService()
    # Open the node store (and arm the service's shm surface) BEFORE the
    # address is published: a fast peer may deliver_shm the instant it can
    # see this rank. RAY_TPU_COLLECTIVE_SHM=0 disables the shm transport
    # (A/B benching + emergency fallback to pure sockets).
    my_store = _os.environ.get("RAY_TPU_STORE_NAME", "")
    if _os.environ.get("RAY_TPU_COLLECTIVE_SHM", "1") == "0":
        my_store = ""
    if my_store:
        try:
            from ray_tpu.core.native_store import NativeObjectStore

            service.shm = NativeObjectStore.open(my_store)
        except Exception:  # noqa: BLE001 — no local store: socket path
            service.shm = None
            my_store = ""
    server = RpcServer(service, name=f"collective-{group_name}-r{rank}",
                       max_workers=max(8, world_size + 2))
    my_hier = "1" if cfg.collective_hierarchy_enabled else "0"
    gcs.kv_put(f"collective:{group_name}:addr:{rank}",
               f"{server.address}|{my_store}|{my_hier}".encode(),
               namespace="collective")
    addrs: List[Optional[str]] = [None] * world_size
    stores: List[Optional[str]] = [None] * world_size
    hier_votes: List[bool] = [True] * world_size
    addrs[rank] = server.address
    stores[rank] = my_store or None
    hier_votes[rank] = my_hier == "1"
    deadline = _time.time() + 60.0
    while any(a is None for a in addrs):
        for r in range(world_size):
            if addrs[r] is None:
                raw = gcs.kv_get(f"collective:{group_name}:addr:{r}",
                                 namespace="collective")
                if raw:
                    parts = raw.decode().split("|")
                    addrs[r] = parts[0]
                    stores[r] = (parts[1] or None) if len(parts) > 1 else None
                    hier_votes[r] = parts[2] != "0" if len(parts) > 2 else True
        if any(a is None for a in addrs):
            if _time.time() > deadline:
                server.stop()
                missing = [r for r in range(world_size) if addrs[r] is None]
                raise TimeoutError(
                    f"collective group {group_name}: ranks {missing} never "
                    f"published their member address")
            _time.sleep(0.05)
    # The schedule must be identical on every rank (tags would never pair
    # up otherwise): the hierarchy runs only when EVERY member voted for it.
    group = _DistributedGroup(world_size, rank, addrs, service, server,
                              stores=stores, hierarchy=all(hier_votes))
    group._kv_key = f"collective:{group_name}:addr:{rank}"
    with _groups_lock:
        _groups[group_name] = group  # type: ignore[assignment]


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        state = _groups.pop(group_name, None)
    server = getattr(state, "_server", None)
    if server is not None:  # cross-process member mailbox server
        server.stop()
        peers = getattr(state, "_peers", None)
        if peers is not None:  # close per-peer clients (one per rank)
            peers.close_all()
        # Drop the rendezvous key so a re-created group can't race a
        # later joiner onto a dead member's address.
        try:
            get_runtime().gcs.kv_del(getattr(state, "_kv_key", ""),
                                     namespace="collective")
        except Exception:  # noqa: BLE001 — GCS gone at teardown
            log_swallowed(logger, "rendezvous kv_del")


def get_rank(group_name: str = "default") -> int:
    with _groups_lock:
        ranks = _ranks.get(_ctx_key(), {})
        if group_name in ranks:
            return ranks[group_name]
    raise RuntimeError(
        f"init_collective_group must be called in this actor/task first "
        f"(group={group_name})"
    )


def get_collective_group_size(group_name: str = "default") -> int:
    state = _group(group_name)
    return state.world_size


def get_group_stats(group_name: str = "default") -> Dict[str, int]:
    """Instrumentation snapshot for a cross-process group: logical payload
    bytes sent split by same-store vs cross-store destination (the
    DCN-analog traffic the hierarchy minimizes) and how many reduction
    rounds took each schedule. Empty for in-process backends."""
    state = _group(group_name)
    st = getattr(state, "stats", None)
    return dict(st) if st else {}


def all_group_stats() -> Dict[str, Dict[str, int]]:
    """:func:`get_group_stats` over every live group in this process — the
    metrics exporter's collector mirrors these into per-group gauges."""
    with _groups_lock:
        items = list(_groups.items())
    out: Dict[str, Dict[str, int]] = {}
    for name, state in items:
        st = getattr(state, "stats", None)
        if st:
            out[name] = dict(st)
    return out


def _group(group_name: str) -> _GroupState:
    with _groups_lock:
        state = _groups.get(group_name)
    if state is None:
        raise RuntimeError(f"collective group '{group_name}' not initialized")
    return state


def _to_numpy(tensor) -> np.ndarray:
    return np.asarray(tensor)


def _prep(state, tensor):
    """Device-backend groups keep tensors ON DEVICE; host backends get
    numpy (the reference's gloo path copies to host the same way)."""
    if getattr(state, "backend", "local") == "device":
        return tensor
    return _to_numpy(tensor)


def _traced_op(op: str, group: str, rank: int, call):
    """Flight-record the enter/exit edges of one collective op — a rank
    that dies inside the rendezvous leaves an unmatched ``enter`` in its
    ring, which is exactly what the postmortem needs to name the straggler
    that hung the group."""
    flightrec.record("collective", group[:32], f"enter {op} rank={rank}")
    try:
        result = call()
    except BaseException as e:
        flightrec.record("collective", group[:32],
                         f"FAIL {op} rank={rank}: {type(e).__name__}")
        raise
    flightrec.record("collective", group[:32], f"exit {op} rank={rank}")
    return result


def allreduce(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:258."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    return _traced_op("allreduce", group_name, rank, lambda: state.
                      exchange_desc(rank, ("allreduce", op),
                                    _prep(state, tensor)))


def barrier(group_name: str = "default") -> None:
    """reference: collective.py:298."""
    state = _group(group_name)
    rank = get_rank(group_name)
    _traced_op("barrier", group_name, rank,
               lambda: state.exchange_desc(rank, ("barrier",), None))


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """reference: collective.py:373."""
    state = _group(group_name)
    rank = get_rank(group_name)
    value = _prep(state, tensor) if rank == src_rank else None
    return _traced_op("broadcast", group_name, rank, lambda: state.
                      exchange_desc(rank, ("broadcast", src_rank), value))


def allgather(tensor, group_name: str = "default") -> List[np.ndarray]:
    """reference: collective.py:423. Returns list of per-rank tensors."""
    state = _group(group_name)
    rank = get_rank(group_name)
    return _traced_op("allgather", group_name, rank, lambda: state.
                      exchange_desc(rank, ("allgather",),
                                    _prep(state, tensor)))


def reducescatter(tensor, op: str = "sum", group_name: str = "default"):
    """reference: collective.py:472. Input split along dim 0 across ranks;
    each rank receives its reduced shard."""
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op}")
    state = _group(group_name)
    rank = get_rank(group_name)
    shards = _traced_op("reducescatter", group_name, rank, lambda: state.
                        exchange_desc(rank, ("reducescatter", op),
                                      _to_numpy(tensor)))
    return shards[rank]


def alltoall(tensor, group_name: str = "default"):
    """Each rank's input is split along dim 0; shard i goes to rank i.

    The host-side analog of XLA ``all_to_all`` (expert-parallel routing).
    """
    state = _group(group_name)
    rank = get_rank(group_name)
    return _traced_op("alltoall", group_name, rank, lambda: state.
                      exchange_desc(rank, ("alltoall",),
                                    _to_numpy(tensor)))[rank]


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """reference: collective.py:531 (p2p)."""
    state = _group(group_name)
    rank = get_rank(group_name)
    _traced_op("send", group_name, rank,
               lambda: state.p2p_send(rank, dst_rank, _to_numpy(tensor)))


def recv(src_rank: int, group_name: str = "default",
         timeout: Optional[float] = None):
    """reference: collective.py:594 (p2p). ``timeout=None`` uses the
    group's ``collective_timeout_s``."""
    state = _group(group_name)
    rank = get_rank(group_name)
    return _traced_op("recv", group_name, rank,
                      lambda: state.p2p_recv(src_rank, rank, timeout))
