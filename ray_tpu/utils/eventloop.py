"""Shared teardown for the aiohttp-in-a-daemon-thread servers."""

from __future__ import annotations

import asyncio

from ray_tpu.utils.logging import get_logger, log_swallowed


def drain_and_close_loop(loop: asyncio.AbstractEventLoop,
                         logger_name: str) -> None:
    """Join the loop's default-executor workers, then close the loop.

    ``loop.close()`` alone abandons the ``run_in_executor`` pool — one
    leaked set of worker threads per server restart.
    """
    try:
        loop.run_until_complete(loop.shutdown_default_executor())
    except Exception:  # noqa: BLE001 — close() still shuts it down
        log_swallowed(get_logger(logger_name), "default-executor shutdown")
    loop.close()
