"""Structured logging for all runtime components.

Analog of the reference's spdlog-backed ``RAY_LOG`` (``src/ray/util/logging.cc``)
— one logger namespace per component, process/component prefix on every line so
interleaved multi-process logs stay attributable.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(process)d %(name)s] %(message)s"
_configured = False


def get_logger(component: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("RAY_TPU_LOG_LEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        root = logging.getLogger("ray_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    return logging.getLogger(f"ray_tpu.{component}")
