"""Structured logging for all runtime components.

Analog of the reference's spdlog-backed ``RAY_LOG`` (``src/ray/util/logging.cc``)
— one logger namespace per component, process/component prefix on every line so
interleaved multi-process logs stay attributable.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(process)d %(name)s] %(message)s"
_configured = False


def log_swallowed(logger: logging.Logger, context: str) -> None:
    """Record an intentionally-swallowed exception instead of `pass`.

    For the `except Exception:` arms in daemon/thread loops where the
    failure is genuinely expected and non-fatal (peer gone at shutdown,
    best-effort cleanup): a bare `pass` hides real bugs behind the expected
    noise, while this keeps the traceback one `RAY_TPU_LOG_LEVEL=DEBUG`
    away. Call from inside the `except` block; never raises — not even
    during interpreter teardown.
    """
    try:
        logger.debug("swallowed exception in %s", context, exc_info=True)
    except Exception:  # raylint: ignore[swallowed-exception] — the helper
        pass


def get_logger(component: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("RAY_TPU_LOG_LEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, datefmt="%H:%M:%S"))
        root = logging.getLogger("ray_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    return logging.getLogger(f"ray_tpu.{component}")
