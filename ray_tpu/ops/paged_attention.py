"""Paged attention — a Pallas TPU decode kernel over the block-pool KV cache.

The serve engine's decode hot path (``models/generate.py
_forward_decode_paged``) holds K/V in a SHARED pool of
``block_tokens``-sized blocks addressed through per-sequence block tables.
The straightforward JAX formulation gathers the whole table back out —
``k_pool[tables].reshape(S, max_len, H, D)`` — which materializes
S × max_len × H × D every token and reads every pool block a slot's table
points at, live or not. Decode is memory-bandwidth-bound, so that gather is
exactly the HBM traffic the roofline says we cannot afford.

This kernel reads the block table NATIVELY instead: the table and the
per-slot lengths ride in as scalar-prefetch operands
(``pltpu.PrefetchScalarGridSpec``), so the BlockSpec index map dereferences
``tables[s, j]`` on the host side of the DMA pipeline and each grid program
streams pool blocks straight from HBM into VMEM — only the
``ceil(len/block_tokens)`` LIVE blocks of its slot do real work. Dead table
entries point at the reserved trash block 0, and because consecutive grid
steps that map to the same pool block skip the re-fetch, the dead tail of a
table costs one block of traffic, not ``NB - live``. Softmax is the online
(m, l, acc) accumulator pattern shared with ``flash_attention._flash_kernel``,
held in VMEM scratch across the kv sweep.

Layout: ``q`` [S, T, H, D] — T > 1 is the multi-token speculative-decoding
verify (and the paged prefill, S == 1): query t of slot s sits at absolute
position ``lengths[s] + t`` and attends kv positions ``<= lengths[s] + t``.
The T new tokens' K/V must already be scattered into the pool at those
positions (the caller writes K/V first, then attends — same order as the
gather path).

Runs compiled on TPU and in interpret mode on CPU (the tier-1 path);
``paged_attention_reference`` is the gather-path oracle the kernel is
validated against.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _paged_kernel(
    tables_ref, lengths_ref,   # scalar prefetch: [S, NB] int32, [S] int32
    q_ref,                     # [1, H, T, D] block
    k_ref, v_ref,              # [1, bt, H, D] block — pool block tables[s, j]
    o_ref,                     # [1, H, T, D] block
    m_scr, l_scr, acc_scr,     # VMEM scratch: [H*T, 1], [H*T, 1], [H*T, D]
    *,
    scale: float,
    block_tokens: int,
    num_heads: int,
    q_tokens: int,
    nb_seq: int,
):
    s = pl.program_id(0)
    j = pl.program_id(1)
    bt, H, T = block_tokens, num_heads, q_tokens
    ctx = lengths_ref[s]
    # Highest block index holding any attendable position: query T-1 sits at
    # ctx + T - 1. Blocks past it are dead — their table entries are trash
    # (block 0), the revisit-skip makes their DMA free, and the body skips.
    last_blk = jax.lax.div(ctx + T - 1, bt)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(j <= last_blk)
    def _body():
        qb = q_ref[0].astype(jnp.float32)            # [H, T, D]
        kb = k_ref[0].astype(jnp.float32)            # [bt, H, D]
        vb = v_ref[0].astype(jnp.float32)            # [bt, H, D]
        # Causal + validity in one mask: kv position vs absolute q position.
        kv_pos = j * bt + jax.lax.broadcasted_iota(jnp.int32, (T, bt), 1)
        q_pos = ctx + jax.lax.broadcasted_iota(jnp.int32, (T, bt), 0)
        mask = kv_pos <= q_pos
        for h in range(H):                           # static unroll
            scores = jax.lax.dot_general(
                qb[h], kb[:, h, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale                                 # [T, bt]
            scores = jnp.where(mask, scores, _NEG_INF)
            r0, r1 = h * T, (h + 1) * T
            m_prev = m_scr[r0:r1]                     # [T, 1]
            m_cur = jnp.max(scores, axis=-1, keepdims=True)
            m_new = jnp.maximum(m_prev, m_cur)
            alpha = jnp.exp(m_prev - m_new)
            p = jnp.exp(scores - m_new)               # [T, bt]
            l_scr[r0:r1] = alpha * l_scr[r0:r1] + jnp.sum(
                p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p, vb[:, h, :], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                         # [T, D]
            acc_scr[r0:r1] = acc_scr[r0:r1] * alpha + pv
            m_scr[r0:r1] = m_new

    @pl.when(j == nb_seq - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)          # [H*T, 1]
        out = (acc_scr[:] / denom).reshape(H, T, acc_scr.shape[-1])
        o_ref[0] = out.astype(o_ref.dtype)


def paged_attention(
    q: jax.Array,                # [S, T, H, D]
    k_pool: jax.Array,           # [num_blocks, bt, H, D] (one layer's pool)
    v_pool: jax.Array,
    tables: jax.Array,           # [S, NB] int32 — pool block ids, 0 = trash
    lengths: jax.Array,          # [S] int32 — valid context BEFORE the T tokens
    *,
    scale: Optional[float] = None,
    interpret: bool = False,
) -> jax.Array:
    """Fused paged-attention over the block pool; returns [S, T, H, D].

    Query t of slot s is at absolute position ``lengths[s] + t`` and attends
    positions ``<= lengths[s] + t`` gathered through ``tables[s]``. No
    ``[S, max_len, H, D]`` intermediate exists at any point."""
    S, T, H, D = q.shape
    bt = k_pool.shape[1]
    nb_seq = tables.shape[1]
    s_val = scale if scale is not None else 1.0 / D**0.5
    tables = tables.astype(jnp.int32)
    lengths = lengths.astype(jnp.int32)
    qt = q.transpose(0, 2, 1, 3)                      # [S, H, T, D]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, nb_seq),
        in_specs=[
            pl.BlockSpec((1, H, T, D), lambda s, j, tbl, ln: (s, 0, 0, 0)),
            pl.BlockSpec((1, bt, H, D),
                         lambda s, j, tbl, ln: (tbl[s, j], 0, 0, 0)),
            pl.BlockSpec((1, bt, H, D),
                         lambda s, j, tbl, ln: (tbl[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, T, D),
                               lambda s, j, tbl, ln: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H * T, 1), jnp.float32),
            pltpu.VMEM((H * T, 1), jnp.float32),
            pltpu.VMEM((H * T, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel, scale=s_val, block_tokens=bt, num_heads=H,
            q_tokens=T, nb_seq=nb_seq),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, H, T, D), q.dtype),
        interpret=interpret,
    )(tables, lengths, qt, k_pool, v_pool)
    return out.transpose(0, 2, 1, 3)                  # [S, T, H, D]


def paged_attention_reference(q, k_pool, v_pool, tables, lengths, *,
                              scale: Optional[float] = None) -> jax.Array:
    """Gather-path oracle: materializes [S, NB*bt, H, D] through the table
    and runs masked dense attention — numerically what the pre-kernel decode
    did, kept as the equivalence target and the CPU fallback reference."""
    S, T, H, D = q.shape
    bt = k_pool.shape[1]
    nb_seq = tables.shape[1]
    s_val = scale if scale is not None else 1.0 / D**0.5
    kc = k_pool[tables].reshape(S, nb_seq * bt, H, D)
    vc = v_pool[tables].reshape(S, nb_seq * bt, H, D)
    scores = jnp.einsum("bthd,bshd->bhts", q, kc,
                        preferred_element_type=jnp.float32) * s_val
    kv_pos = jnp.arange(nb_seq * bt)[None, None, None, :]
    q_pos = (lengths.reshape(-1, 1, 1, 1)
             + jnp.arange(T)[None, None, :, None])
    scores = jnp.where(kv_pos <= q_pos, scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhts,bshd->bthd", probs, vc.astype(jnp.float32))
    return out.astype(q.dtype)
