"""Mixture-of-Experts FFN — expert parallelism over the ``expert`` mesh axis.

Absent from the reference entirely (SURVEY §2.4: EP "Absent from Ray
itself"); TPU-native it is a mesh axis: experts shard onto ``expert``, tokens
route to their expert via ``all_to_all`` over ICI, compute locally, and route
back. Static shapes throughout (XLA requirement): per-expert capacity is
fixed and overflow tokens drop (standard Switch-style capacity factor).

Layout: tokens [B, S, D] → top-1 router → dispatch [E, C, D] (E experts,
C capacity) → expert FFN → combine back to [B, S, D] weighted by router
probability. Under ``shard_map`` the E axis is sharded on ``expert`` so each
device runs only its local experts; the dispatch/combine einsums become
all_to_all-style collectives compiled by XLA.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.ops.layers import gelu


@dataclass(frozen=True)
class MoEConfig:
    d_model: int
    d_ff: int
    num_experts: int
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32

    def capacity(self, tokens_per_batch: int) -> int:
        c = int(self.capacity_factor * tokens_per_batch / self.num_experts)
        return max(4, ((c + 3) // 4) * 4)  # pad to a friendly multiple


def init_params(cfg: MoEConfig, key: jax.Array) -> Dict:
    k1, k2, k3 = jax.random.split(key, 3)
    E, D, F = cfg.num_experts, cfg.d_model, cfg.d_ff
    return {
        "router": (jax.random.normal(k1, (D, E)) * 0.02).astype(cfg.dtype),
        "w_up": (jax.random.normal(k2, (E, D, F)) * (2.0 / D) ** 0.5).astype(cfg.dtype),
        "w_down": (jax.random.normal(k3, (E, F, D)) * (2.0 / F) ** 0.5).astype(cfg.dtype),
    }


def logical_axes(cfg: MoEConfig) -> Dict:
    return {
        "router": (None, None),
        "w_up": ("experts", "embed", "mlp"),
        "w_down": ("experts", "mlp", "embed"),
    }


def moe_ffn(params: Dict, x: jax.Array, cfg: MoEConfig) -> Tuple[jax.Array, Dict]:
    """Top-1 (Switch) MoE FFN. x: [B, S, D] → ([B, S, D], aux metrics).

    Pure function of static shapes — safe inside jit/shard_map; the caller
    shards ``w_up``/``w_down`` on the ``expert`` axis via logical rules.
    """
    B, S, D = x.shape
    E = cfg.num_experts
    T = B * S
    C = cfg.capacity(T)
    flat = x.reshape(T, D)

    logits = jnp.einsum("td,de->te", flat.astype(jnp.float32), params["router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)          # [T, E]
    expert_idx = jnp.argmax(probs, axis=-1)          # [T]
    gate = jnp.max(probs, axis=-1)                   # [T]

    # position of each token within its expert's queue
    onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.int32)       # [T, E]
    pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1) * onehot      # [T, E]
    pos = jnp.sum(pos_in_expert, axis=-1)                          # [T]
    keep = pos < C                                                  # overflow drops

    # dispatch tensor [T, E, C] — one-hot of (expert, slot); overflow tokens
    # map to slot C which is sliced away
    slot_onehot = jax.nn.one_hot(
        jnp.where(keep, pos, C), C + 1, dtype=jnp.float32
    )[:, :C]  # [T, C]
    dispatch = onehot.astype(jnp.float32)[:, :, None] * slot_onehot[:, None, :]  # [T, E, C]
    expert_in = jnp.einsum("tec,td->ecd", dispatch, flat.astype(jnp.float32))  # [E, C, D]

    h = jnp.einsum("ecd,edf->ecf", expert_in, params["w_up"].astype(jnp.float32))
    h = gelu(h)
    out = jnp.einsum("ecf,efd->ecd", h, params["w_down"].astype(jnp.float32))  # [E, C, D]

    combine = dispatch * (gate * keep)[:, None, None]               # [T, E, C]
    y = jnp.einsum("tec,ecd->td", combine, out).astype(x.dtype)     # [T, D]

    # load-balancing auxiliary loss (Switch: E * sum_e f_e * P_e)
    frac_tokens = jnp.mean(onehot.astype(jnp.float32), axis=0)      # [E]
    frac_probs = jnp.mean(probs, axis=0)                            # [E]
    aux_loss = E * jnp.sum(frac_tokens * frac_probs)
    metrics = {
        "aux_loss": aux_loss,
        "dropped_fraction": 1.0 - jnp.mean(keep.astype(jnp.float32)),
    }
    return y.reshape(B, S, D), metrics
