"""Core neural-net ops, TPU-shaped.

Conventions: params are plain pytrees of jnp arrays; computation runs in the
array's dtype with float32 accumulation where it matters (layernorm stats,
attention softmax, loss). Matmuls use ``preferred_element_type=float32`` so
bf16 params hit the MXU with f32 accumulation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def layer_norm(x, gamma, beta, eps: float = 1e-5):
    """LayerNorm with f32 statistics regardless of input dtype."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)).astype(x.dtype)


def rms_norm(x, gamma, eps: float = 1e-6):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(ms + eps) * gamma.astype(jnp.float32)).astype(x.dtype)


def gelu(x):
    # tanh approximation (GPT-2 uses this exact form)
    return 0.5 * x * (1.0 + jnp.tanh(0.7978845608028654 * (x + 0.044715 * x * x * x)))


def linear(x, w, b=None):
    y = jnp.einsum("...d,df->...f", x, w, preferred_element_type=jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return y.astype(x.dtype)


def softmax_cross_entropy(logits, labels, ignore_index: int = -100):
    """Token-level CE with f32 logits; ignores masked positions.

    Returns (mean_loss, n_valid_tokens).
    """
    logits32 = logits.astype(jnp.float32)
    valid = labels != ignore_index
    safe_labels = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits32, axis=-1)
    gold = jnp.take_along_axis(logits32, safe_labels[..., None], axis=-1)[..., 0]
    nll = (logz - gold) * valid
    n = jnp.maximum(valid.sum(), 1)
    return nll.sum() / n, n


def rope(x, positions, *, base: float = 10000.0):
    """Rotary position embedding on the last dim (pairs interleaved as
    [even|odd] halves). x: [..., L, H, D]."""
    d = x.shape[-1]
    half = d // 2
    freqs = jnp.exp(-jnp.log(base) * jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., L, half]
    cos = jnp.cos(angles)[..., None, :]
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    y1 = x1 * cos - x2 * sin
    y2 = x2 * cos + x1 * sin
    return jnp.concatenate([y1, y2], axis=-1).astype(x.dtype)
