"""Flash attention — Pallas TPU kernel.

The hot op of the transformer stack. The reference delegates attention math to
torch/framework kernels; TPU-native it is a Pallas kernel: grid over
(batch*heads, q-blocks, kv-blocks) with the kv axis innermost (sequential on
TPU), online-softmax accumulators (m, l, acc) held in VMEM scratch across the
kv sweep, causal blocks fully skipped via ``pl.when``, and the MXU fed
(block_q × d) @ (d × block_k) tiles in f32 accumulation.

Training integrates via ``jax.custom_vjp``: forward uses the kernel; backward
recomputes attention with the XLA dense path (remat-style — the standard
memory/compute trade; a dedicated backward kernel is a later optimization).
Numerics are validated against ``parallel.ring_attention.reference_attention``
in interpret mode on CPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_kernel(
    q_ref, k_ref, v_ref,  # [1, block_q, d], [1, block_k, d]
    o_ref,                # [1, block_q, d]
    m_scr, l_scr, acc_scr,  # VMEM scratch: [block_q, 1], [block_q, 1], [block_q, d]
    *,
    scale: float,
    causal: bool,
    block_q: int,
    block_k: int,
    kv_blocks: int,
):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q_start = qi * block_q
    k_start = ki * block_k

    # Causal: a kv block strictly after the q block contributes nothing.
    run = True
    if causal:
        run = k_start <= q_start + block_q - 1

    @pl.when(run if causal else True)
    def _body():
        q = q_ref[0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0].astype(jnp.float32)          # [bk, d]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale                                  # [bq, bk]
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) + q_start
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1) + k_start
            scores = jnp.where(rows >= cols, scores, _NEG_INF)

        m_prev = m_scr[:]                          # [bq, 1]
        m_cur = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)            # rescale of old accumulators
        p = jnp.exp(scores - m_new)                # [bq, bk]
        l_new = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        acc_scr[:] = acc_scr[:] * alpha + pv
        m_scr[:] = m_new
        l_scr[:] = l_new

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        denom = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / denom).astype(o_ref.dtype)


def _flash_forward(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *, scale: float, causal: bool, block_q: int, block_k: int,
    interpret: bool,
) -> jax.Array:
    """q/k/v: [BH, L, D] (batch*heads flattened). Returns [BH, L, D]."""
    bh, lq, d = q.shape
    lk = k.shape[1]
    assert lq % block_q == 0 and lk % block_k == 0, (
        f"seq lens ({lq},{lk}) must divide blocks ({block_q},{block_k})"
    )
    q_blocks = lq // block_q
    kv_blocks = lk // block_k

    kernel = functools.partial(
        _flash_kernel,
        scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, kv_blocks=kv_blocks,
    )
    return pl.pallas_call(
        kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, lq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _dense_reference(q, k, v, *, scale, causal):
    scores = jnp.einsum("blhd,bkhd->bhlk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        l, kk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((l, kk), bool))
        scores = jnp.where(mask[None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhlk,bkhd->blhd", probs, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: bool = False,
) -> jax.Array:
    """Multi-head attention, [B, L, H, D] layout (matches
    ``models.transformer``). Heads fold into the grid's batch dim."""
    return _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret)[0]


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    b, l, h, d = q.shape
    s = scale if scale is not None else 1.0 / d**0.5
    bq = min(block_q, l)
    bk = min(block_k, l)
    if l % bq != 0 or k.shape[1] % bk != 0:
        # Odd sequence lengths: take the dense path rather than tracing a
        # kernel with ragged blocks (padding+masking inside the kernel is a
        # later optimization; odd L is never the perf-critical case).
        return _dense_reference(q, k, v, scale=s, causal=causal), (q, k, v)
    fold = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)
    out = _flash_forward(
        fold(q), fold(k), fold(v),
        scale=s, causal=causal, block_q=bq, block_k=bk, interpret=interpret,
    )
    out = out.reshape(b, h, l, d).transpose(0, 2, 1, 3)
    return out, (q, k, v)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    s = scale if scale is not None else 1.0 / q.shape[-1] ** 0.5
    # Recompute-through-XLA backward (remat): correct grads, O(L^2) compute,
    # no O(L^2) residual storage from the forward.
    _, vjp = jax.vjp(lambda q, k, v: _dense_reference(q, k, v, scale=s, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
